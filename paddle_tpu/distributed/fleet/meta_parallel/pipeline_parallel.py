"""PipelineParallel — microbatch schedules over pipeline stages.

Reference parity: python/paddle/distributed/fleet/meta_parallel/
pipeline_parallel.py (+ pp_utils/p2p_communication.py — unverified, mount
empty): ``train_batch`` splits the global batch into micro-batches and
drives the F-then-B (GPipe) or 1F1B schedule with gradient accumulation,
averaging the per-microbatch losses.

TPU redesign: in the SPMD execution model every process owns the whole
program, so stage-to-stage "p2p" inside this engine is an activation
handoff (the compiled multi-chip path expresses the same schedule with
ppermute over the pp mesh axis — paddle_tpu/parallel/pipeline.py). The
schedule ORDER (warmup / steady 1F1B / cooldown) matches the reference
exactly, which is what bounds live activation memory: at most
``pp_degree`` microbatch graphs are alive at any point of the steady
state, versus all ``accumulate_steps`` under naive F-then-B.
"""
from __future__ import annotations

import numpy as np

from ....core.tensor import Tensor
from ....nn.layer.layers import Layer
from .parallel_layers.pp_layers import (  # noqa: F401 (re-export parity)
    LayerDesc,
    PipelineLayer,
    SharedLayerDesc,
)


def _split_microbatches(vals, n):
    """Split leading batch dim of every tensor into n microbatches."""
    outs = []
    for i in range(n):
        chunk = []
        for v in vals:
            b = v.shape[0]
            if b % n != 0:
                raise ValueError(
                    f"batch size {b} not divisible by accumulate_steps {n}"
                )
            m = b // n
            chunk.append(v[i * m : (i + 1) * m])
        outs.append(chunk)
    return outs


class PipelineParallel(Layer):
    def __init__(self, layers, hcg, strategy=None):
        super().__init__()
        if not isinstance(layers, PipelineLayer):
            raise TypeError(
                "fleet.distributed_model for pp_degree>1 expects a "
                "PipelineLayer"
            )
        self._layers = layers
        self._hcg = hcg
        pipe_cfg = {}
        if strategy is not None:
            pipe_cfg = dict(getattr(strategy, "pipeline_configs", {}) or {})
        self.micro_batch_size = int(pipe_cfg.get("micro_batch_size", 1))
        self.accumulate_steps = int(pipe_cfg.get("accumulate_steps", 1))
        self.num_stages = layers.num_stages
        self.stage_id = hcg.get_stage_id() if hcg is not None else 0
        # compiled schedule: ONE jitted step running the ppermute ring
        # (jit.pipeline_trainer); the eager engine below stays the
        # debug/correctness path
        self._use_compiled = bool(pipe_cfg.get("compiled", False))
        self._compiled_amp = pipe_cfg.get("amp_level", None)
        self._compiled_amp_dtype = pipe_cfg.get("amp_dtype", "bfloat16")
        self._compiled_step = None

    # re-expose the wrapped model
    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    def parameters(self, *a, **k):
        return self._layers.parameters(*a, **k)

    def named_parameters(self, *a, **k):
        return self._layers.named_parameters(*a, **k)

    # ----------------------------------------------------------- schedule
    def _forward_micro(self, inputs, labels, scaler):
        """One microbatch through every stage + loss (scaled by 1/acc)."""
        model = self._layers
        x = inputs[0] if len(inputs) == 1 else tuple(inputs)
        for stage in range(self.num_stages):
            x = model.run_stage(x, stage, training=True)
        if model._loss_fn is None:
            raise ValueError("PipelineLayer needs loss_fn for train_batch")
        loss = model._loss_fn(x, *labels)
        loss = loss / float(self.accumulate_steps)
        if scaler is not None:
            loss = scaler.scale(loss)
        return loss

    def _train_batch_compiled(self, inputs, labels, optimizer,
                              lr_scheduler=None, scaler=None):
        from ....jit.trainer import CompiledTrainStep

        if (self._compiled_step is not None
                and (self._compiled_step.optimizer is not optimizer
                     or self._compiled_step.scaler
                     is not CompiledTrainStep._normalize_scaler(scaler))):
            # a fresh optimizer/scaler (e.g. after resume) needs a rebuilt
            # step — the jitted update is bound to their state layout
            self._compiled_step = None
        if self._compiled_step is None:
            from ....jit.pipeline_trainer import CompiledPipelineTrainStep

            model = self._layers
            if model._loss_fn is None:
                raise ValueError(
                    "PipelineLayer needs loss_fn for train_batch"
                )
            self._compiled_step = CompiledPipelineTrainStep(
                model,
                lambda out, *lbls: model._loss_fn(out, *lbls),
                optimizer,
                micro_batches=self.accumulate_steps,
                num_virtual=model.get_num_virtual_stages(),
                amp_level=self._compiled_amp,
                amp_dtype=self._compiled_amp_dtype,
                scaler=scaler,
            )
        self._layers.train()
        loss, _ = self._compiled_step(inputs, labels)
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """1F1B: warmup forwards, steady-state alternating 1F/1B, cooldown
        backwards. Single-process SPMD runs the same order the multi-chip
        schedule would issue on the last stage, bounding live graphs to
        ``num_stages`` instead of ``accumulate_steps``."""
        inputs, labels = data
        inputs = [v if isinstance(v, Tensor) else Tensor(v) for v in
                  (inputs if isinstance(inputs, (list, tuple)) else [inputs])]
        labels = [v if isinstance(v, Tensor) else Tensor(v) for v in
                  (labels if isinstance(labels, (list, tuple)) else [labels])]

        if self._use_compiled:
            return self._train_batch_compiled(
                inputs, labels, optimizer, lr_scheduler, scaler
            )

        acc = self.accumulate_steps
        micro_in = _split_microbatches(inputs, acc)
        micro_lb = _split_microbatches(labels, acc)

        self._layers.train()
        num_warmup = min(self.num_stages, acc)
        pending = []  # live losses awaiting backward (1F1B window)
        total = 0.0

        def fire_backward():
            loss = pending.pop(0)
            loss.backward()
            return float(np.asarray(loss.numpy()))

        fwd_i = 0
        # warmup: fill the pipeline
        for _ in range(num_warmup):
            pending.append(
                self._forward_micro(micro_in[fwd_i], micro_lb[fwd_i], scaler)
            )
            fwd_i += 1
        # steady state: 1F1B
        while fwd_i < acc:
            total += fire_backward()
            pending.append(
                self._forward_micro(micro_in[fwd_i], micro_lb[fwd_i], scaler)
            )
            fwd_i += 1
        # cooldown: drain
        while pending:
            total += fire_backward()

        if scaler is not None:
            # capture the scale the losses were actually multiplied by
            # BEFORE update() grows/shrinks it, or the reported loss is
            # wrong by the incr/decr ratio on adjustment steps
            scale_used = float(scaler._scale)
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        # total is the mean loss over the global batch (losses were
        # pre-scaled by 1/acc); unscale report if a scaler is active
        if scaler is not None:
            total = total / scale_used
        return Tensor(np.float32(total))

    def eval_batch(self, data, compute_loss=True):
        inputs, labels = data
        inputs = [v if isinstance(v, Tensor) else Tensor(v) for v in
                  (inputs if isinstance(inputs, (list, tuple)) else [inputs])]
        labels = [v if isinstance(v, Tensor) else Tensor(v) for v in
                  (labels if isinstance(labels, (list, tuple)) else [labels])]
        self._layers.eval()
        from ....core import tape

        model = self._layers
        with tape.no_grad():
            x = inputs[0] if len(inputs) == 1 else tuple(inputs)
            for stage in range(self.num_stages):
                x = model.run_stage(x, stage, training=False)
            if compute_loss and model._loss_fn is not None:
                return model._loss_fn(x, *labels)
        return x
