"""RNGStatesTracker — per-parallel-axis RNG streams.

Reference parity: python/paddle/distributed/fleet/meta_parallel/
parallel_layers/random.py (unverified, mount empty): named RNG states so
dropout inside TP regions is identical within an mp group and distinct
across it (SURVEY.md §7 hard part #7).

JAX redesign: streams are key *derivations*, not mutable cuRAND states.
``rng_state(name)`` installs a key_scope whose base key folds together
(ambient step key if inside a compiled step, the stream's seed, a
per-entry counter). Multi-process ranks fold their mp rank into the seed
at ``model_parallel_random_seed`` time; in single-process SPMD the mask
is generated globally and sharded, which is the same distribution.
"""
from __future__ import annotations

import contextlib
import zlib

import jax

from .....core import random as random_mod

MODEL_PARALLEL_RNG = "model_parallel_rng"


class RNGStatesTracker:
    def __init__(self):
        self.seeds_ = set()
        self.states_ = {}
        self._entry_counts = {}

    def reset(self):
        self.seeds_ = set()
        self.states_ = {}
        self._entry_counts = {}

    def get_states_tracker(self):
        return dict(self.states_)

    def set_states_tracker(self, states):
        self.states_ = dict(states)
        self.seeds_ = set(self.states_.values())
        self._entry_counts = {
            k: self._entry_counts.get(k, 0) for k in self.states_
        }

    def add(self, name, seed):
        if seed in self.seeds_:
            raise ValueError(f"seed {seed} already exists")
        if name in self.states_:
            raise ValueError(f"state {name} already exists")
        self.seeds_.add(seed)
        self.states_[name] = int(seed)
        self._entry_counts[name] = 0

    @contextlib.contextmanager
    def rng_state(self, name=MODEL_PARALLEL_RNG):
        if name not in self.states_:
            raise ValueError(f"state {name} does not exist")
        seed = self.states_[name]
        tag = zlib.crc32(name.encode())
        # per-entry counter: distinct call sites (traced once each) and
        # distinct eager entries get distinct streams
        n = self._entry_counts[name]
        self._entry_counts[name] = n + 1
        scope = random_mod._STATE.scope
        if scope is not None:
            # inside a compiled step: derive from the ambient step key so
            # each step gets fresh masks without retracing
            base = jax.random.fold_in(
                jax.random.fold_in(jax.random.fold_in(scope[0], tag), seed),
                n,
            )
        else:
            base = jax.random.fold_in(
                jax.random.fold_in(jax.random.key(seed), tag), n
            )
        with random_mod.key_scope(base):
            yield


_TRACKER = RNGStatesTracker()


def get_rng_state_tracker():
    return _TRACKER


def model_parallel_random_seed(seed=None):
    import random as py_random

    if seed is None:
        seed = py_random.randint(0, 2**31 - 1)
    from ... import fleet as fleet_mod

    mp_rank = 0
    hcg = getattr(fleet_mod.fleet, "_hcg", None)
    if hcg is not None:
        mp_rank = hcg.get_model_parallel_rank()
    global_seed = seed
    local_seed = seed + 1024 + mp_rank
    _TRACKER.reset()
    _TRACKER.add(MODEL_PARALLEL_RNG, local_seed)
    random_mod.seed(global_seed)


def determinate_seed(name):  # paddle-compat helper
    return zlib.crc32(name.encode()) % (2**31)
