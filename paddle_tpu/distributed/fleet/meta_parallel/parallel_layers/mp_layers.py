"""Tensor-parallel (mp) layers — GSPMD sharding-constraint style.

Reference parity: python/paddle/distributed/fleet/meta_parallel/
parallel_layers/mp_layers.py (unverified, mount empty):
VocabParallelEmbedding, ColumnParallelLinear, RowParallelLinear,
ParallelCrossEntropy with the same constructor surface.

TPU-first redesign: instead of per-rank weight shards plus hand-written
NCCL collectives, each layer holds the *global* weight placed with a
NamedSharding over the hybrid mesh's ``mp`` axis, and stamps sharding
constraints on activations. XLA's SPMD partitioner then derives the exact
Megatron collective pattern (identity/allreduce pairs, masked vocab
lookup + psum, distributed softmax) — see paddle_tpu/parallel/tp_ops.py
for the equivalent explicit shard_map form, tested to match.

The specs themselves come from the ACTIVE ``parallel.layout.LayoutPolicy``
(one rule per parameter family), so the whole layout is a swappable
object: the default ``tp-pp-dp`` policy reproduces the historical
hard-coded annotations byte-for-byte, and swapping in e.g.
``pp-sharded-state`` changes optimizer-state placement and the loss
collective pattern without touching any model code. An ``mp_group``
carrying a custom ``mesh_axis`` still overrides the policy's mp axis
(reference subgroup semantics).

Initialization uses the *full* logical weight (same RNG stream as the
single-device model), so mp-sharded training is bit-comparable to gold —
this replaces the reference's per-rank RNG tracker init dance.
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from .....core import dispatch
from .....core.tensor import Tensor
from .....nn import functional as F
from .....nn import initializer as I
from .....nn.layer.layers import Layer
from .....parallel import layout as layout_mod
from .....parallel import mesh as mesh_mod
from .....parallel import tp_ops


def _mp_axis(mp_group):
    if mp_group is not None and getattr(mp_group, "mesh_axis", None):
        return mp_group.mesh_axis
    return layout_mod.get_policy().mp_axis


def _family_spec(family, axis):
    """The active policy's spec for a parameter family, re-expressed on
    ``axis`` when an mp_group overrides the policy's mp axis name."""
    pol = layout_mod.get_policy()
    spec = pol.spec(family)
    if axis == pol.mp_axis:
        return tuple(spec)
    return tuple(axis if e == pol.mp_axis else e for e in spec)


def _mp_degree(axis):
    return mesh_mod.global_mesh_shape().get(axis, 1)


def _wsc(x, *, spec, epoch):
    mesh = mesh_mod.get_mesh()
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(*spec))
    )


def shard_constraint(t, *spec):
    """Tape-aware with_sharding_constraint on a Tensor (autograd flows:
    the VJP of a sharding constraint is the same constraint on the
    cotangent, which jax.vjp derives automatically)."""
    return dispatch.apply(
        "shard_constraint", _wsc, (t,),
        {"spec": tuple(spec), "epoch": mesh_mod.mesh_epoch()},
    )


def _place(param, *spec):
    """Shard a freshly initialized full parameter over the global mesh.

    Under ``paddle.LazyGuard`` the parameter is abstract: the sharding is
    attached to the ShapeDtypeStruct instead of moving bytes, so a lazily
    built TP model lowers with its real parameter layout."""
    if param is None:
        return None
    from .....core import lazy as lazy_mod

    mesh = mesh_mod.get_mesh()
    if lazy_mod.is_abstract(param.value):
        param.value = lazy_mod.abstract_like(
            param.value.shape, param.value.dtype,
            sharding=NamedSharding(mesh, P(*spec)),
        )
    else:
        param.value = jax.device_put(
            param.value, NamedSharding(mesh, P(*spec))
        )
    return param


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded over the mp axis.

    Weight: [num_embeddings, embedding_dim] with NamedSharding P('mp', None)
    — each mp rank stores vocab/mp rows. The lookup partitions to the
    masked-local-gather + psum pattern.
    """

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self._axis = _mp_axis(mp_group)
        self._world_size = _mp_degree(self._axis)
        if num_embeddings % max(self._world_size, 1) != 0:
            raise ValueError(
                f"num_embeddings {num_embeddings} must be divisible by the "
                f"mp degree {self._world_size}"
            )
        self.weight = _place(
            self.create_parameter(
                [num_embeddings, embedding_dim], attr=weight_attr,
                default_initializer=I.XavierUniform(),
            ),
            *_family_spec("embedding", self._axis),
        )

    def forward(self, x):
        out = F.embedding(x, self.weight)
        return shard_constraint(out, *([None] * (len(out.shape) - 1)))


class ColumnParallelLinear(Layer):
    """Linear with the output dim sharded over mp.

    Weight: [in, out] P(None, 'mp'); bias: [out] P('mp'). Forward input is
    (logically) replicated over mp; output stays out-sharded unless
    ``gather_output``.
    """

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self._axis = _mp_axis(mp_group)
        self._world_size = _mp_degree(self._axis)
        self.gather_output = gather_output
        if out_features % max(self._world_size, 1) != 0:
            raise ValueError(
                f"out_features {out_features} must be divisible by the "
                f"mp degree {self._world_size}"
            )
        self.weight = _place(
            self.create_parameter(
                [in_features, out_features], attr=weight_attr,
                default_initializer=I.XavierUniform(
                    fan_in=in_features, fan_out=out_features
                ),
            ),
            *_family_spec("column_weight", self._axis),
        )
        self.bias = None
        if has_bias is None or has_bias:
            self.bias = _place(
                self.create_parameter(
                    [out_features], is_bias=True,
                ),
                *_family_spec("column_bias", self._axis),
            )

    def forward(self, x):
        y = F.linear(x, self.weight, self.bias)
        lead = [None] * (len(y.shape) - 1)
        if self.gather_output:
            return shard_constraint(y, *lead)
        return shard_constraint(y, *lead, self._axis)


class RowParallelLinear(Layer):
    """Linear with the input dim sharded over mp.

    Weight: [in, out] P('mp', None); bias [out] replicated (added after
    the reduce). With ``input_is_parallel`` the incoming activation is
    already sharded on its last dim (the ColumnParallel output); otherwise
    XLA scatters it. Output is replicated over mp (partial sums reduced).
    """

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self._axis = _mp_axis(mp_group)
        self._world_size = _mp_degree(self._axis)
        self.input_is_parallel = input_is_parallel
        if in_features % max(self._world_size, 1) != 0:
            raise ValueError(
                f"in_features {in_features} must be divisible by the "
                f"mp degree {self._world_size}"
            )
        self.weight = _place(
            self.create_parameter(
                [in_features, out_features], attr=weight_attr,
                default_initializer=I.XavierUniform(
                    fan_in=in_features, fan_out=out_features
                ),
            ),
            *_family_spec("row_weight", self._axis),
        )
        self.bias = None
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)

    def forward(self, x):
        if self.input_is_parallel:
            x = shard_constraint(
                x, *([None] * (len(x.shape) - 1)), self._axis
            )
        y = F.linear(x, self.weight)
        y = shard_constraint(y, *([None] * (len(y.shape) - 1)))
        if self.bias is not None:
            y = y + self.bias
        return y


def _vp_ce_op(logits, labels, *, axis_name, ignore_index, lead_axes,
              epoch):
    """dispatch op body for the explicit vocab-parallel CE (``epoch``
    keys the op cache to the installed mesh, like shard_constraint)."""
    del epoch
    return tp_ops.vocab_parallel_cross_entropy_spmd(
        logits, labels, axis_name=axis_name, lead_axes=lead_axes,
        ignore_index=ignore_index,
    )


class ParallelCrossEntropy(Layer):
    """Softmax cross entropy over vocab-sharded logits.

    Two lowerings behind one layer, selected by the active
    ``parallel.layout`` policy:

    - default (GSPMD): the logits keep their P(..., 'mp') sharding
      through log-softmax; XLA partitions the max/sum-exp reductions
      across the mp axis (the distributed-softmax pattern of the
      reference's ParallelCrossEntropy).
    - ``vocab_parallel_loss`` policies: the explicit Megatron form —
      tp_ops.vocab_parallel_cross_entropy inside a shard_map, so each
      chip's fp32 block is the LOCAL [rows, V/mp] shard and the
      full-vocab fp32 logits array is never materialized (the 7B
      memory lever; fp32-tolerance parity with the GSPMD path is
      tier-1-pinned).
    """

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self._axis = _mp_axis(mp_group)
        self.ignore_index = ignore_index

    def forward(self, input, label):
        pol = layout_mod.get_policy()
        deg = _mp_degree(self._axis)
        if (
            pol.vocab_parallel_loss
            and deg > 1
            and int(input.shape[-1]) % deg == 0
        ):
            return dispatch.apply(
                "vocab_parallel_cross_entropy", _vp_ce_op,
                (input, label),
                {
                    "axis_name": self._axis,
                    "ignore_index": int(self.ignore_index),
                    "lead_axes": pol.loss_lead_axes(),
                    "epoch": mesh_mod.mesh_epoch(),
                },
            )
        logits = shard_constraint(
            input, *([None] * (len(input.shape) - 1)), self._axis
        )
        return F.cross_entropy(
            logits, label, reduction="none",
            ignore_index=self.ignore_index,
        )
