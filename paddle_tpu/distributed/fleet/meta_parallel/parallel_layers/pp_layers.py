"""PipelineLayer — stage segmentation of a layer stack.

Reference parity: python/paddle/distributed/fleet/meta_parallel/
parallel_layers/pp_layers.py (unverified, mount empty): LayerDesc /
SharedLayerDesc descriptions, segmentation by uniform count or by layer
class ("layer:ClassName"), recompute_interval, shared-weight stages.

TPU redesign: in single-process SPMD every stage is built in this
process; the stage structure drives (a) the 1F1B microbatch schedule in
PipelineParallel and (b) the stacked-stage shard_map pipeline in
paddle_tpu.parallel.pipeline for the compiled path. On multi-process
meshes each process still owns all stage definitions (weights are sharded
arrays), matching the SPMD execution model.
"""
from __future__ import annotations

import re

from .....nn.layer.layers import Layer


class LayerDesc:
    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs
        if not (isinstance(layer_func, type) and issubclass(layer_func, Layer)) \
                and not callable(layer_func):
            raise TypeError("LayerDesc expects a Layer subclass or callable")

    def build_layer(self):
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        name = getattr(self.layer_func, "__name__", str(self.layer_func))
        return f"LayerDesc({name})"


class SharedLayerDesc(LayerDesc):
    """A layer whose weights are shared between stages (e.g. embedding and
    output head). All occurrences with the same ``key`` resolve to ONE
    built layer instance, so sharing is by construction (no grad-sync
    dance needed: the tape accumulates both paths into the same params)."""

    def __init__(self, key, layer_func, forward_func=None,
                 shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0,
                 recompute_ctx=None, num_virtual_pipeline_stages=None):
        super().__init__()
        # interleaved virtual stages are honored by the COMPILED schedule
        # (jit.pipeline_trainer / pipeline_configs["compiled"]); the eager
        # engine runs items in order either way (same math)
        self._num_virtual = int(num_virtual_pipeline_stages or 1)
        self._descs = list(layers)
        self._topology = topology
        if num_stages is None:
            if topology is None:
                raise ValueError("need num_stages or topology")
            num_stages = topology.get_dim("pp")
        self._num_stages = int(num_stages)
        self._loss_fn = loss_fn
        self._recompute_interval = int(recompute_interval)
        self.seg_method = seg_method

        # build layers (shared descs dedupe by key)
        shared = {}
        built = []
        for d in self._descs:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name not in shared:
                    shared[d.layer_name] = d.build_layer()
                built.append((d, shared[d.layer_name]))
            elif isinstance(d, LayerDesc):
                built.append((d, d.build_layer()))
            elif isinstance(d, Layer):
                built.append((None, d))
            elif callable(d):
                built.append((None, d))
            else:
                raise TypeError(f"cannot build pipeline item {d!r}")
        self._items = built

        # register parameters (each built layer once)
        seen = set()
        for i, (_, l) in enumerate(built):
            if isinstance(l, Layer) and id(l) not in seen:
                seen.add(id(l))
                self.add_sublayer(str(i), l)

        self._stage_bounds = self._segment()

    # -------------------------------------------------------- segmentation
    def _segment(self):
        n = len(self._items)
        s = self._num_stages
        if n < s:
            raise ValueError(f"{n} layers cannot fill {s} stages")
        if self.seg_method.startswith("layer:"):
            cls_name = self.seg_method.split(":", 1)[1]
            marks = [
                i for i, (_, l) in enumerate(self._items)
                if type(l).__name__ == cls_name
            ]
            if len(marks) < s:
                raise ValueError(
                    f"seg_method {self.seg_method!r}: only {len(marks)} "
                    f"{cls_name} layers for {s} stages"
                )
            # distribute marked layers evenly; each later stage starts at
            # a marked layer, stage 0 absorbs any unmarked prefix
            per, rem = divmod(len(marks), s)
            bounds = [0]
            cum = 0
            for st in range(s - 1):
                cum += per + (1 if st < rem else 0)
                bounds.append(marks[cum])
            bounds.append(n)
            return bounds
        # uniform by count
        per = n // s
        rem = n % s
        bounds = [0]
        for st in range(s):
            bounds.append(bounds[-1] + per + (1 if st < rem else 0))
        return bounds

    # ----------------------------------------------------------- execution
    @property
    def num_stages(self):
        return self._num_stages

    def get_num_virtual_stages(self):
        return self._num_virtual

    def stage_items(self, stage):
        lo, hi = self._stage_bounds[stage], self._stage_bounds[stage + 1]
        return [l for _, l in self._items[lo:hi]]

    def _run_item(self, desc_layer, x):
        d, l = desc_layer
        if isinstance(d, SharedLayerDesc) and d.forward_func is not None:
            return d.forward_func(l, *(x if isinstance(x, tuple) else (x,)))
        if isinstance(x, tuple):
            return l(*x)
        return l(x)

    def run_stage(self, x, stage, training=True):
        """Run one stage's chunk (optionally recomputed)."""
        lo, hi = self._stage_bounds[stage], self._stage_bounds[stage + 1]
        items = self._items[lo:hi]
        if training and self._recompute_interval > 0:
            from ...recompute import recompute as rc

            runner = self._run_item
            i = 0
            while i < len(items):
                chunk = items[i : i + self._recompute_interval]
                i += self._recompute_interval

                # a Layer wrapper (not a bare closure) so recompute()
                # tracks the chunk's parameters as grad inputs
                class _Chunk(Layer):
                    def __init__(self, its):
                        super().__init__()
                        self._its = its
                        for j, (_, l) in enumerate(its):
                            if isinstance(l, Layer):
                                self.add_sublayer(str(j), l)

                    def forward(self, v):
                        for it in self._its:
                            v = runner(it, v)
                        return v

                x = rc(_Chunk(chunk), x)
            return x
        for it in items:
            x = self._run_item(it, x)
        return x

    def forward(self, x):
        for stage in range(self._num_stages):
            x = self.run_stage(x, stage, training=self.training)
        return x
