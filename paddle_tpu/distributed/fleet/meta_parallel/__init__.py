"""meta_parallel — hybrid-parallel model wrappers and parallel layers.

Reference parity: python/paddle/distributed/fleet/meta_parallel/
(unverified, mount empty). TP layers are GSPMD sharding-constraint
layers; PP arrives as PipelineLayer + schedules.
"""
from .parallel_layers import (  # noqa: F401
    ColumnParallelLinear,
    LayerDesc,
    ParallelCrossEntropy,
    PipelineLayer,
    RNGStatesTracker,
    RowParallelLinear,
    SharedLayerDesc,
    VocabParallelEmbedding,
    get_rng_state_tracker,
    model_parallel_random_seed,
    shard_constraint,
)
from .pipeline_parallel import PipelineParallel  # noqa: F401


def wrap_hybrid_model(model, hcg, strategy=None):
    """fleet.distributed_model for hybrid topologies.

    TP layers already carry their mp shardings; PP models (PipelineLayer)
    get the pipeline engine; everything else gets DP gradient sync over
    the dp axis when dp_degree > 1 (XLA handles the rest of the axes
    inside the compiled step).
    """
    from .pipeline_parallel import PipelineLayer, PipelineParallel

    if isinstance(model, PipelineLayer):
        return PipelineParallel(model, hcg, strategy)
    if hcg.get_data_parallel_world_size() > 1:
        from ...parallel import DataParallel

        return DataParallel(model, group=hcg.get_data_parallel_group())
    return model
