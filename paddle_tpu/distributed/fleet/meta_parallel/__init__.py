"""meta_parallel — hybrid-parallel model wrappers and parallel layers.

Reference parity: python/paddle/distributed/fleet/meta_parallel/
(unverified, mount empty). TP layers are GSPMD sharding-constraint
layers; PP arrives as PipelineLayer + schedules.
"""
from .parallel_layers import (  # noqa: F401
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RNGStatesTracker,
    RowParallelLinear,
    VocabParallelEmbedding,
    get_rng_state_tracker,
    model_parallel_random_seed,
    shard_constraint,
)
