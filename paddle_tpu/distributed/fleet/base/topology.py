"""CommunicateTopology / HybridCommunicateGroup.

Reference parity: python/paddle/distributed/fleet/base/topology.py
(unverified, mount empty): rank -> coordinate in the (dp, pp, sharding,
sep, mp) grid, one communication group per axis.

TPU redesign: the topology IS a jax.sharding.Mesh. "Groups" become mesh
axis names consumed by sharding specs and shard_map collectives; the
per-axis ProcessGroup objects are retained for the eager API so reference
code (``hcg.get_model_parallel_group()``…) keeps working. The device
count used for the grid is the TOTAL chip count (n_processes ×
local_devices) — in single-process SPMD all "ranks" live in one process.
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as np

import jax

from ....parallel import mesh as mesh_mod
from ...process_group import ProcessGroup

# reference axis order, outermost first
_ORDER = ["dp", "pp", "sharding", "sep", "mp"]


class CommunicateTopology:
    def __init__(self, hybrid_group_names=None, dims=None):
        self._parallel_names = hybrid_group_names or _ORDER
        self._dims = list(dims or [1] * len(self._parallel_names))
        self._world = int(np.prod(self._dims))
        shape = self._dims
        self._coord_of = {}
        self._rank_of = {}
        for rank in range(self._world):
            coord = np.unravel_index(rank, shape)
            self._coord_of[rank] = tuple(int(c) for c in coord)
            self._rank_of[self._coord_of[rank]] = rank

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return self._world

    def get_rank(self, **kwargs):
        coord = tuple(kwargs[n] for n in self._parallel_names)
        return self._rank_of[coord]

    def get_coord(self, rank):
        return self._coord_of[rank]

    def get_axis_list(self, axis_name, index):
        """All ranks whose coordinate on axis==index."""
        ax = self._parallel_names.index(axis_name)
        return [
            r for r, c in self._coord_of.items() if c[ax] == index
        ]

    def get_comm_list(self, axis_name):
        """Groups of ranks varying only along axis_name."""
        ax = self._parallel_names.index(axis_name)
        groups = OrderedDict()
        for r, c in self._coord_of.items():
            key = c[:ax] + c[ax + 1 :]
            groups.setdefault(key, []).append(r)
        return list(groups.values())


class HybridCommunicateGroup:
    def __init__(self, topology: CommunicateTopology):
        self._topo = topology
        from ... import env as dist_env

        world = topology.world_size()
        self.global_rank = dist_env.get_rank()

        self._dp_degree = topology.get_dim("dp")
        self._pp_degree = topology.get_dim("pp")
        self._sharding_degree = topology.get_dim("sharding")
        self._sep_degree = topology.get_dim("sep") if "sep" in topology.get_hybrid_group_names() else 1
        self._mp_degree = topology.get_dim("mp")

        # THE mesh: axes in reference order over all chips
        axes = OrderedDict()
        for name in topology.get_hybrid_group_names():
            axes[name] = topology.get_dim(name)
        self.mesh = mesh_mod.init_mesh(axes)

        # eager per-axis groups for the current rank (reference API parity).
        # pg ids must agree across processes -> deterministic crc32, not the
        # per-process-salted hash()
        import zlib

        self._groups = {}
        coord = topology.get_coord(min(self.global_rank, world - 1))
        for name in topology.get_hybrid_group_names():
            for ranks in topology.get_comm_list(name):
                if min(self.global_rank, world - 1) in ranks:
                    tag = f"{name}:{','.join(map(str, ranks))}".encode()
                    self._groups[name] = ProcessGroup(
                        ranks, pg_id=zlib.crc32(tag) % 100000,
                        mesh_axis=name,
                    )
                    break
        self._coord = dict(zip(topology.get_hybrid_group_names(), coord))

    # ------------------------------------------------------- degrees/ranks
    def get_parallel_mode(self):
        if self._mp_degree > 1 or self._pp_degree > 1 or self._sharding_degree > 1:
            return "hybrid"
        return "data_parallel" if self._dp_degree > 1 else "single"

    def topology(self):
        return self._topo

    def get_global_rank(self):
        return self.global_rank

    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_rank(self):
        return self._coord.get("dp", 0)

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_rank(self):
        return self._coord.get("mp", 0)

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_stage_id(self):
        return self._coord.get("pp", 0)

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_rank(self):
        return self._coord.get("sharding", 0)

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    def get_sep_parallel_rank(self):
        return self._coord.get("sep", 0)

    # --------------------------------------------------------- groups/axes
    def get_data_parallel_group(self):
        return self._groups.get("dp")

    def get_model_parallel_group(self):
        return self._groups.get("mp")

    def get_pipe_parallel_group(self):
        return self._groups.get("pp")

    def get_sharding_parallel_group(self):
        return self._groups.get("sharding")

    def get_sep_parallel_group(self):
        return self._groups.get("sep")

    def get_data_parallel_group_src_rank(self):
        g = self._groups.get("dp")
        return g.ranks[0] if g else 0

    def get_model_parallel_group_src_rank(self):
        g = self._groups.get("mp")
        return g.ranks[0] if g else 0

    # TPU-native accessors: mesh axis names for sharding specs
    def dp_axis(self):
        return "dp"

    def mp_axis(self):
        return "mp"

    def pp_axis(self):
        return "pp"

    def sharding_axis(self):
        return "sharding"

    def sep_axis(self):
        return "sep"
