"""DistributedStrategy.

Reference parity: python/paddle/distributed/fleet/base/distributed_strategy.py
backed by distributed_strategy.proto (unverified, mount empty). The proto
carrier is replaced by a plain attribute bag with the same field names —
there is no cross-language boundary to serialize across on TPU.
"""
from __future__ import annotations

import copy


class DistributedStrategy:
    def __init__(self):
        # hybrid parallel degrees (reference hybrid_configs)
        self.hybrid_configs = {
            "dp_degree": -1,
            "mp_degree": 1,
            "pp_degree": 1,
            "sharding_degree": 1,
            "sep_degree": 1,
        }
        self.amp = False
        self.amp_configs = {
            "init_loss_scaling": 32768.0,
            "use_dynamic_loss_scaling": True,
            "custom_white_list": [],
            "custom_black_list": [],
            "use_pure_fp16": False,
            "use_bf16": True,
        }
        self.recompute = False
        self.recompute_configs = {"checkpoints": []}
        self.sharding = False
        self.sharding_configs = {"stage": 1, "degree": 8}
        self.pipeline = False
        self.pipeline_configs = {
            "accumulate_steps": 1,
            "micro_batch_size": 1,
            "schedule_mode": "1F1B",
        }
        self.tensor_parallel = False
        self.tensor_parallel_configs = {"tensor_parallel_degree": 1}
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1, "avg": True}
        self.lamb = False
        self.dgc = False
        self.localsgd = False
        self.heter_ccl_mode = False
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1  # accepted, meaningless on ICI
        self.gradient_scale_configs = {"scale_strategy": "avg"}
        self.without_graph_optimization = False

    def __deepcopy__(self, memo):
        new = DistributedStrategy()
        for k, v in self.__dict__.items():
            setattr(new, k, copy.deepcopy(v, memo))
        return new

    def __repr__(self):
        fields = ", ".join(
            f"{k}={v}" for k, v in self.__dict__.items() if not k.endswith("_configs")
        )
        return f"DistributedStrategy({fields})"
