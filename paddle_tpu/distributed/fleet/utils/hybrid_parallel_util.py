"""Hybrid-parallel gradient utilities.

Reference parity: python/paddle/distributed/fleet/utils/
hybrid_parallel_util.py (fused_allreduce_gradients — unverified, mount
empty): the bucketed data-parallel gradient reduction the
HybridParallelOptimizer fires at step boundaries.

TPU notes: under single-process SPMD the "dp axis" is a sharding layout —
eager per-op jits already compute global-batch gradients, so there is
nothing to reduce (world_size 1 short-circuits). The fused path below is
the MULTI-PROCESS mechanism, shared with DataParallel.sync_gradients:
dtype-bucketed (no silent promotion), 25MB-capped fused mean-allreduces,
mirroring the reference reducer's comm_buffer_size_MB behavior.
"""
from __future__ import annotations

import jax.numpy as jnp

from ....core.tensor import Tensor
from ... import env as dist_env

# fused-buffer cap per collective (reference reducer.cc default)
COMM_BUCKET_BYTES = 25 * 1024 * 1024


def _reduce_bucket(group, params):
    flat = jnp.concatenate([p.grad.value.reshape(-1) for p in params])
    t = Tensor(flat)
    group.all_reduce(t, op="mean")
    off = 0
    for p in params:
        n = p.grad.size
        p.grad = Tensor(
            t.value[off: off + n].reshape(p.grad.value.shape)
        )
        off += n


def fused_allreduce_gradients(parameter_list, hcg=None, group=None):
    """Mean-allreduce the gradients of ``parameter_list`` over the data-
    parallel group (``hcg.get_data_parallel_group()`` when given, else
    ``group``, else the world group), fused into dtype/size buckets."""
    if dist_env.get_world_size() <= 1:
        return
    if group is None:
        if hcg is not None:
            group = hcg.get_data_parallel_group()
        if group is None:
            from ...communication import _world_group

            group = _world_group()
    params = [
        p for p in parameter_list
        if getattr(p, "grad", None) is not None
    ]
    if not params:
        return
    buckets: dict = {}
    for p in params:
        buckets.setdefault(str(p.grad.value.dtype), []).append(p)
    for plist in buckets.values():
        chunk, chunk_bytes = [], 0
        for p in plist:
            nbytes = p.grad.size * p.grad.value.dtype.itemsize
            if chunk and chunk_bytes + nbytes > COMM_BUCKET_BYTES:
                _reduce_bucket(group, chunk)
                chunk, chunk_bytes = [], 0
            chunk.append(p)
            chunk_bytes += nbytes
        if chunk:
            _reduce_bucket(group, chunk)
