"""Megatron-style sequence parallelism (SP) utilities.

Reference parity: python/paddle/distributed/fleet/utils/
sequence_parallel_utils.py (unverified, mount empty): ScatterOp/GatherOp/
AllGatherOp/ReduceScatterOp autograd functions plus
ColumnSequenceParallelLinear / RowSequenceParallelLinear and the
sequence-parallel parameter grad-allreduce hooks.

TPU-first redesign (GSPMD): SP shards *activations* along the sequence dim
over the same ``mp`` axis the TP weights use, so the LayerNorm/dropout
regions between the Megatron matmuls hold only S/mp of the sequence. Where
the reference hand-writes allgather-before-qkv / reduce-scatter-after-proj,
here the layers stamp sharding constraints:

    seq-sharded  P(None, 'mp', None)   (LayerNorm / dropout / residual)
      -- ColumnSequenceParallelLinear: constraint to seq-replicated
         (XLA inserts the allgather), matmul with P(None, 'mp') weight,
         output P(None, None, 'mp')
      -- RowSequenceParallelLinear: matmul with P('mp', None) weight,
         output constrained back to P(None, 'mp', None) — XLA lowers the
         partial-sum + re-shard to ONE reduce-scatter (the Megatron-SP
         trick: same bytes as TP's allreduce, but the result is seq-sharded)

The Scatter/Gather op surface is kept: under GSPMD each is just a sharding
constraint whose gradient is the transposed constraint, which jax derives.
Activations stay logically global, so code written against the reference
API (explicit split/allgather bookkeeping) maps onto whole-array ops.

Parameter grad sync: with global parameters under SPMD, gradients of
replicated params (LayerNorm scales inside the seq-sharded region) are
already correct — XLA reduces across the mp axis when lowering. The
mark/register hook APIs are therefore kept as no-op markers for parity.
"""
from __future__ import annotations

from ....nn import functional as F
from ..meta_parallel.parallel_layers.mp_layers import (
    ColumnParallelLinear,
    RowParallelLinear,
    _mp_axis,
    shard_constraint,
)


def _seq_spec(t, axis):
    """P(None, axis, None, ...) — sequence dim of a [B, S, ...] tensor."""
    return [None, axis] + [None] * (len(t.shape) - 2)


class ScatterOp:
    """Forward: shard the sequence dim over mp; backward: the transposed
    constraint (an allgather of the cotangent). Reference API is a static
    ``apply``."""

    @staticmethod
    def apply(x, axis=1):
        mp = _mp_axis(None)
        spec = [None] * len(x.shape)
        spec[axis] = mp
        return shard_constraint(x, *spec)


class GatherOp:
    """Forward: replicate the sequence dim (allgather); backward: re-shard
    the cotangent (a scatter)."""

    @staticmethod
    def apply(x, axis=1):
        return shard_constraint(x, *([None] * len(x.shape)))


# reference aliases: in GSPMD form allgather==gather and the reduce-scatter
# materializes from the Row layer's output constraint
AllGatherOp = GatherOp
ReduceScatterOp = ScatterOp


def scatter(x, axis=1):
    return ScatterOp.apply(x, axis)


def all_gather(x, axis=1):
    return GatherOp.apply(x, axis)


class ColumnSequenceParallelLinear(ColumnParallelLinear):
    """ColumnParallelLinear whose input arrives sequence-sharded: the
    implied allgather over S happens on entry (XLA inserts it), output
    stays sharded on the feature dim over mp. Constructor surface
    inherited from ColumnParallelLinear (gather_output defaults False in
    the SP pattern)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=False, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__(in_features, out_features, weight_attr=weight_attr,
                         has_bias=has_bias, gather_output=gather_output,
                         fuse_matmul_bias=fuse_matmul_bias,
                         mp_group=mp_group, name=name)

    def forward(self, x):
        # allgather the sequence shards (constraint to seq-replicated)
        x = shard_constraint(x, *([None] * len(x.shape)))
        return super().forward(x)


class RowSequenceParallelLinear(RowParallelLinear):
    """RowParallelLinear whose output leaves sequence-sharded: the
    partial-sum reduce and the sequence re-shard fuse into one
    reduce-scatter (XLA lowers the output constraint)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=True,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__(in_features, out_features, weight_attr=weight_attr,
                         has_bias=has_bias,
                         input_is_parallel=input_is_parallel,
                         fuse_matmul_bias=fuse_matmul_bias,
                         mp_group=mp_group, name=name)

    def forward(self, x):
        if self.input_is_parallel:
            x = shard_constraint(
                x, *([None] * (len(x.shape) - 1)), self._axis
            )
        y = F.linear(x, self.weight)
        # reduce-scatter: partial sums over mp -> seq-sharded output
        y = shard_constraint(y, *_seq_spec(y, self._axis))
        if self.bias is not None:
            y = y + self.bias
        return y


def mark_as_sequence_parallel_parameter(parameter):
    """Mark a parameter (e.g. a LayerNorm scale used inside the
    seq-sharded region) as sequence-parallel. Under SPMD with global
    parameters the grad reduction over mp is inserted by XLA, so the mark
    is metadata-only (kept for reference API parity and introspection)."""
    parameter.sequence_parallel = True
    return parameter


def is_sequence_parallel_parameter(parameter):
    return getattr(parameter, "sequence_parallel", False)


def register_sequence_parallel_allreduce_hooks(model, accumulation_steps=1,
                                               fuse_allreduce=False):
    """No-op under SPMD (grad reduction is compiled into the step); kept
    so reference training scripts run unchanged."""
    return model
