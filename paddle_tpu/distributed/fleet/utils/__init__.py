"""fleet.utils parity surface (reference: …/fleet/utils/__init__.py).

``paddle.distributed.fleet.utils.recompute`` is the documented public
path for activation recomputation.
"""
from ..recompute.recompute import recompute, recompute_sequential  # noqa: F401
from . import sequence_parallel_utils  # noqa: F401
from .hybrid_parallel_util import fused_allreduce_gradients  # noqa: F401
