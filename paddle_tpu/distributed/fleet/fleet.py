"""fleet API singleton.

Reference parity: python/paddle/distributed/fleet/fleet.py (unverified,
mount empty): fleet.init / distributed_model / distributed_optimizer.
TPU-first: init builds the hybrid mesh (topology.py); distributed_model
wraps per strategy (DataParallel for pure DP; TP/PP wrappers arrive with
meta_parallel); distributed_optimizer returns HybridParallelOptimizer which
syncs eager grads per axis and exposes the compiled fleet train step.
"""
from __future__ import annotations

import numpy as np

import jax

from ...core.tensor import Tensor
from .. import env as dist_env
from ..parallel import DataParallel, init_parallel_env
from .base.distributed_strategy import DistributedStrategy
from .base.topology import CommunicateTopology, HybridCommunicateGroup


class HybridParallelOptimizer:
    """Wraps a paddle_tpu optimizer with per-axis gradient sync (eager path).

    On the compiled path (fleet_train_step / CompiledTrainStep over the
    mesh) XLA inserts all reductions and this wrapper's step() is a plain
    inner step.
    """

    def __init__(self, optimizer, hcg=None, strategy=None, model=None):
        self._inner = optimizer
        self._hcg = hcg
        self._model = model

    def __getattr__(self, name):
        return getattr(self.__dict__["_inner"], name)

    def step(self):
        if self._model is not None and hasattr(self._model, "sync_gradients"):
            self._model.sync_gradients()
        elif (
            self._hcg is not None
            and dist_env.get_world_size() > 1
            and self._hcg.get_data_parallel_world_size() > 1
        ):
            # non-DataParallel wrappers (PipelineParallel, bare TP nets)
            # still need the dp-axis grad reduction in multi-process runs
            from .utils.hybrid_parallel_util import (
                fused_allreduce_gradients,
            )

            fused_allreduce_gradients(
                [p for _, p in self._inner._all_params()], hcg=self._hcg
            )
        self._inner.step()

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        return None, None

    def clear_grad(self, set_to_zero=False):
        self._inner.clear_grad(set_to_zero)


class Fleet:
    def __init__(self):
        self._strategy = None
        self._hcg = None
        self._topology = None
        self._initialized = False
        self._last_model = None
        # reference ordering allows distributed_optimizer before
        # distributed_model; these queues are drained when the model arrives
        self._pending_opt_wrappers = []
        self._pending_sharding_opts = []

    # ---------------------------------------------------------------- init
    def init(self, role_maker=None, is_collective=True, strategy=None,
             log_level="INFO"):
        self._strategy = strategy or DistributedStrategy()
        # PS mode: a role_maker with server/worker roles switches fleet
        # into the parameter-server runtime. The reference call shape is
        # fleet.init(role) (its is_collective defaults False), so detect
        # the role maker itself rather than keying on our default.
        if (
            role_maker is not None
            and hasattr(role_maker, "is_server")
            and not getattr(role_maker, "_is_collective", False)
        ):
            from ..ps import PSContext

            self._role = role_maker
            self._ps = PSContext(role_maker)
            self._initialized = True
            return self
        init_parallel_env()
        n_chips = len(jax.devices())
        hc = dict(self._strategy.hybrid_configs)
        mp = max(1, int(hc.get("mp_degree", 1)))
        pp = max(1, int(hc.get("pp_degree", 1)))
        sharding = max(1, int(hc.get("sharding_degree", 1)))
        sep = max(1, int(hc.get("sep_degree", 1)))
        dp = int(hc.get("dp_degree", -1))
        if dp in (-1, 0):
            dp = n_chips // (mp * pp * sharding * sep)
        if dp * mp * pp * sharding * sep != n_chips:
            raise ValueError(
                f"hybrid degrees dp={dp} sharding={sharding} pp={pp} "
                f"sep={sep} mp={mp} must multiply to chip count {n_chips}"
            )
        from ...parallel.mesh import HYBRID_AXES

        self._topology = CommunicateTopology(
            list(HYBRID_AXES), [dp, pp, sharding, sep, mp]
        )
        self._hcg = HybridCommunicateGroup(self._topology)
        self._initialized = True
        # a fresh topology invalidates bindings from a previous job
        self._last_model = None
        self._pending_opt_wrappers = []
        self._pending_sharding_opts = []
        return self

    def get_hybrid_communicate_group(self):
        return self._hcg

    def is_first_worker(self):
        r = getattr(self, "_role", None)
        if r is not None:
            return r.is_first_worker()
        return dist_env.get_rank() == 0

    def worker_index(self):
        r = getattr(self, "_role", None)
        if r is not None:
            return r.trainer_id
        return dist_env.get_rank()

    def worker_num(self):
        r = getattr(self, "_role", None)
        if r is not None:
            return r.trainers_num
        return dist_env.get_world_size()

    def is_worker(self):
        r = getattr(self, "_role", None)
        return True if r is None else r.is_worker()

    # ------------------------------------------------------------- PS mode
    def is_server(self):
        r = getattr(self, "_role", None)
        return False if r is None else r.is_server()

    @property
    def ps(self):
        return getattr(self, "_ps", None)

    def init_server(self, *args, **kwargs):
        """Tables are created lazily by the first worker push in this
        build; kept for reference-call-sequence parity."""
        assert self.is_server(), "init_server on a non-server role"

    def run_server(self):
        assert self.is_server(), "run_server on a non-server role"
        self._ps.run_server()

    def stop_worker(self):
        if getattr(self, "_ps", None) is not None:
            self._ps.stop_servers()

    def worker_endpoints(self, to_string=False):
        if getattr(self, "_ps", None) is not None:
            eps = self._ps.trainer_endpoints()
        else:
            eps = dist_env.get_trainer_endpoints()
        return ",".join(eps) if to_string else eps

    def barrier_worker(self):
        if getattr(self, "_ps", None) is not None:
            # PS mode has no collective runtime; barrier through server 0
            if self.is_worker():
                self._ps.barrier()
            return
        from ..communication import barrier

        barrier()

    # --------------------------------------------------------------- wrap
    def distributed_model(self, model):
        assert self._initialized, "call fleet.init first"
        hcg = self._hcg
        if hcg.get_parallel_mode() in ("single", "data_parallel"):
            wrapped = DataParallel(model)
        else:
            from .meta_parallel import wrap_hybrid_model

            wrapped = wrap_hybrid_model(model, hcg, self._strategy)
        self._last_model = wrapped
        for opt in self._pending_sharding_opts:
            self._install_sharding_placements(opt, wrapped)
        self._pending_sharding_opts.clear()
        for hp_opt in self._pending_opt_wrappers:
            if hp_opt._model is None:
                hp_opt._model = wrapped
        self._pending_opt_wrappers.clear()
        return wrapped

    def _install_sharding_placements(self, optimizer, model):
        """DygraphShardingOptimizer semantics (ZeRO-1 over the sharding
        axis): optimizer state placed sharded. Params/buffers must live
        on the same device set (mesh-replicated), or eager updates mix
        single-device params with mesh-sharded accumulators."""
        import jax as _jax
        from jax.sharding import NamedSharding, PartitionSpec

        from ..sharding.group_sharded import install_stage1_placements

        mesh = self._hcg.mesh
        install_stage1_placements(
            optimizer, model.named_parameters(),
            axis=self._hcg.sharding_axis(), mesh=mesh,
        )
        replicated = NamedSharding(mesh, PartitionSpec())

        def _lift(t):
            # leaves already carrying a multi-device placement (TP weights
            # sharded by mp_layers, FSDP storage) keep it; only lift
            # single-device leaves onto the mesh
            v = t.value
            if getattr(v, "ndim", None) is None:
                return
            s = getattr(v, "sharding", None)
            if isinstance(s, NamedSharding) and s.mesh.size > 1:
                return
            t.value = _jax.device_put(v, replicated)

        for _, p in model.named_parameters():
            _lift(p)
        for _, b in model.named_buffers():
            _lift(b)

    def distributed_optimizer(self, optimizer, strategy=None):
        assert self._initialized, "call fleet.init first"
        if (
            self._hcg is not None
            and self._hcg.get_sharding_parallel_world_size() > 1
        ):
            if self._last_model is not None:
                self._install_sharding_placements(optimizer, self._last_model)
            else:
                # reference ordering allows distributed_optimizer before
                # distributed_model; finish the install when the model
                # arrives
                self._pending_sharding_opts.append(optimizer)
        wrapped = HybridParallelOptimizer(
            optimizer, self._hcg, strategy or self._strategy,
            model=self._last_model,
        )
        if self._last_model is None:
            self._pending_opt_wrappers.append(wrapped)
        return wrapped

    # ------------------------------------------------------------- save/load
    def save_persistables(self, executor=None, dirname=None, main_program=None):
        raise NotImplementedError("use paddle.save(model.state_dict(), ...)")


fleet = Fleet()
