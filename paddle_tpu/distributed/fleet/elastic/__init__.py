"""Elastic training manager (fleet.elastic parity).

Reference: python/paddle/distributed/fleet/elastic/manager.py (unverified,
mount empty). See manager.py for the TPU redesign notes.
"""
from .manager import (  # noqa: F401
    ElasticManager,
    ElasticStatus,
    ElasticSupervisor,
    latest_checkpoint,
)
