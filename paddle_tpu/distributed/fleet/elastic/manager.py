"""ElasticManager — peer registry, scale events, restart-from-checkpoint.

Reference parity: python/paddle/distributed/fleet/elastic/manager.py
(unverified, mount empty): nodes register under an etcd job prefix, watch
peer keys, detect scale-in/scale-out, rewrite PADDLE_TRAINER_ENDPOINTS,
and restart workers from the latest checkpoint.

TPU redesign: the registry is a directory of per-node heartbeat files
(name = node rank, contents = endpoint, liveness = mtime) instead of
etcd — on TPU pods the jobs already share a filesystem (GCS fuse / NFS)
and `jax.distributed` supplies the in-job coordination service, so the
only piece elastic needs is the OUT-of-job membership view that survives
process death. The manager's surface (register/watch/endpoint rewrite /
ElasticStatus) mirrors the reference so launcher logic ports unchanged.

Recovery model is the reference's: restart-from-checkpoint, not
in-flight repair. `latest_checkpoint` picks the newest complete save in
a directory (distributed-checkpoint dirs with metadata.json, or
paddle.save files), for the training script to resume from.
"""
from __future__ import annotations

import os
import re
import threading
import time


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    def __init__(self, job_id, registry_dir, node_rank, endpoint,
                 np_range=(1, 1), heartbeat_interval=None,
                 timeout=6.0):
        if heartbeat_interval is None:
            # beats must outpace the staleness timeout or live peers flap
            heartbeat_interval = max(0.05, timeout / 4.0)
        self.job_id = job_id
        self.dir = os.path.join(registry_dir, job_id, "nodes")
        os.makedirs(self.dir, exist_ok=True)
        self.node_rank = int(node_rank)
        self.endpoint = endpoint
        self.lo, self.hi = int(np_range[0]), int(np_range[1])
        self.heartbeat_interval = heartbeat_interval
        self.timeout = timeout
        self._stop = threading.Event()
        self._thread = None
        self._last_view = None

    # ------------------------------------------------------------ registry
    def _path(self, rank=None):
        return os.path.join(
            self.dir, str(self.node_rank if rank is None else rank)
        )

    def register(self):
        """Write this node's heartbeat file and start refreshing it."""
        self._write()
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._beat, daemon=True
            )
            self._thread.start()
        return self

    def _write(self):
        tmp = self._path() + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.endpoint)
        os.replace(tmp, self._path())

    def _beat(self):
        while not self._stop.wait(self.heartbeat_interval):
            try:
                self._write()
            except OSError:
                pass

    def deregister(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        try:
            os.remove(self._path())
        except OSError:
            pass

    # ---------------------------------------------------------------- view
    def peers(self):
        """Live peers: [(rank, endpoint)] sorted by rank; a peer whose
        heartbeat is older than ``timeout`` counts as dead."""
        now = time.time()
        out = []
        try:
            names = os.listdir(self.dir)
        except OSError:
            return out
        for name in names:
            if not name.isdigit():
                continue
            p = os.path.join(self.dir, name)
            try:
                st = os.stat(p)
                if now - st.st_mtime > self.timeout:
                    continue
                with open(p) as f:
                    out.append((int(name), f.read().strip()))
            except OSError:
                continue
        return sorted(out)

    def endpoints(self):
        """PADDLE_TRAINER_ENDPOINTS for the CURRENT membership (the
        endpoint-rewrite step of a scale event)."""
        return ",".join(ep for _, ep in self.peers())

    def watch(self):
        """One poll: HOLD while membership is unchanged and within range,
        RESTART when it changed but still >= lo nodes, EXIT when below
        the minimum."""
        view = tuple(self.peers())
        prev, self._last_view = self._last_view, view
        n = len(view)
        if n < self.lo:
            return ElasticStatus.EXIT
        if prev is not None and view != prev:
            return ElasticStatus.RESTART
        return ElasticStatus.HOLD


class ElasticSupervisor:
    """Single-host supervisor loop: rank subprocesses that SURVIVE a
    dead or wedged member.

    The launcher's ``_watch`` restarts a pod when a child exits; this
    grows that into the elastic recovery loop the training runtime
    needs: spawn ``nprocs`` rank subprocesses, watch for a rank DYING
    (nonzero exit / signal) or WEDGING (its watchdog heartbeat file
    under ``heartbeat_dir`` goes stale — the ``TrainWatchdog`` writes
    one per dispatch), tear the remaining ranks down cleanly, re-form
    the world at the relaunched (or, with ``shrink_on_failure``, the
    surviving) size, and relaunch — each child resumes from the last
    COMMITTED checkpoint via its own ``CheckpointManager``/
    ``latest_checkpoint`` discovery, with the dedup-across-restarts
    log discipline keeping step records exactly-once.

    ``cmd`` is the argv list every rank runs, or a callable
    ``cmd(rank, world) -> argv``. Children get the launcher env
    contract (``PADDLE_TRAINER_ID`` / ``PADDLE_TRAINERS_NUM``) plus
    ``PADDLE_TPU_HEARTBEAT_DIR`` so an in-child ``TrainWatchdog``
    heartbeats without extra wiring. Restart events are counted in
    ``paddle_training_elastic_restarts_total{reason}`` and land in the
    flight ring."""

    def __init__(self, cmd, nprocs, *, min_procs=1, max_restarts=3,
                 heartbeat_dir=None, heartbeat_timeout_s=None,
                 shrink_on_failure=False, grace_seconds=5.0,
                 poll_interval_s=0.1, env=None, log_dir=None):
        self.cmd = cmd
        self.nprocs = int(nprocs)
        self.min_procs = int(min_procs)
        self.max_restarts = int(max_restarts)
        self.heartbeat_dir = heartbeat_dir
        self.heartbeat_timeout_s = (
            float(heartbeat_timeout_s)
            if heartbeat_timeout_s is not None else None
        )
        self.shrink_on_failure = bool(shrink_on_failure)
        self.grace_seconds = float(grace_seconds)
        self.poll_interval_s = float(poll_interval_s)
        self.env = dict(env) if env is not None else None
        self.log_dir = log_dir
        self.restarts = 0
        self.events = []  # [(reason, rank, world)]
        self._metric = None
        try:
            from ....observability import Counter, get_registry

            self._metric = Counter(
                "training_elastic_restarts",
                prom_name="paddle_training_elastic_restarts_total",
                help="supervisor pod restarts, by trigger "
                     "(rank_failed|rank_wedged)",
            )
            get_registry().register_all([self._metric])
        except Exception:
            pass

    # ------------------------------------------------------------- plumbing
    def _note(self, reason, rank, world):
        self.events.append((reason, rank, world))
        if self._metric is not None:
            self._metric.inc(reason=reason)
        try:
            from ....observability import get_flight_recorder

            get_flight_recorder().note(
                "elastic_event", reason=reason, rank=rank, world=world,
            )
        except Exception:
            pass

    def _argv(self, rank, world):
        return self.cmd(rank, world) if callable(self.cmd) \
            else list(self.cmd)

    def _spawn(self, world):
        import subprocess

        procs = []
        for rank in range(world):
            env = dict(self.env if self.env is not None else os.environ)
            env.update({
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_TRAINERS_NUM": str(world),
            })
            if self.heartbeat_dir:
                env["PADDLE_TPU_HEARTBEAT_DIR"] = self.heartbeat_dir
            logf = None
            if self.log_dir:
                os.makedirs(self.log_dir, exist_ok=True)
                logf = open(os.path.join(
                    self.log_dir, f"rank.{rank}.log"), "a")
            procs.append((rank, subprocess.Popen(
                self._argv(rank, world), env=env, stdout=logf,
                stderr=subprocess.STDOUT if logf else None,
            ), logf))
        return procs

    def _teardown(self, procs):
        import signal as _signal
        import subprocess

        for _rank, p, _f in procs:
            if p.poll() is None:
                try:
                    p.send_signal(_signal.SIGTERM)
                except OSError:
                    pass
        deadline = time.time() + self.grace_seconds
        for _rank, p, logf in procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
            if logf:
                logf.close()

    def _stale_rank(self, procs):
        """A LIVE rank whose heartbeat file went stale (wedged, not
        dead) — the straggler the watchdog heartbeats exist for."""
        if not (self.heartbeat_dir and self.heartbeat_timeout_s):
            return None
        now = time.time()
        for rank, p, _f in procs:
            if p.poll() is not None:
                continue
            hb = os.path.join(self.heartbeat_dir, str(rank))
            try:
                age = now - os.stat(hb).st_mtime
            except OSError:
                continue  # never beat yet: startup, not a wedge
            if age > self.heartbeat_timeout_s:
                return rank
        return None

    # ------------------------------------------------------------------ run
    def run(self):
        """Supervise until the pod completes (all ranks exit 0 →
        returns 0) or the restart budget is spent (returns the last
        failing rank's exit code, or 1 for a wedge)."""
        world = self.nprocs
        last_code = 0
        while True:
            procs = self._spawn(world)
            reason = None
            failed_rank = None
            while True:
                exited_clean = 0
                for rank, p, _f in procs:
                    code = p.poll()
                    if code == 0:
                        exited_clean += 1
                    elif code is not None:
                        reason, failed_rank, last_code = (
                            "rank_failed", rank, code
                        )
                        break
                if reason is not None:
                    break
                if exited_clean == len(procs):
                    self._teardown(procs)
                    return 0
                wedged = self._stale_rank(procs)
                if wedged is not None:
                    reason, failed_rank, last_code = (
                        "rank_wedged", wedged, 1
                    )
                    break
                time.sleep(self.poll_interval_s)
            self._teardown(procs)
            self._clear_heartbeats()
            self._note(reason, failed_rank, world)
            if self.restarts >= self.max_restarts:
                return last_code or 1
            self.restarts += 1
            if self.shrink_on_failure and world - 1 >= self.min_procs:
                world -= 1  # re-form at the surviving world size
            # else: relaunch the failed rank at the same world size

    def _clear_heartbeats(self):
        """Stale beats from the torn-down pod must not instantly trip
        the next one's staleness check."""
        if not self.heartbeat_dir:
            return
        try:
            for name in os.listdir(self.heartbeat_dir):
                if name.isdigit():
                    try:
                        os.remove(os.path.join(self.heartbeat_dir, name))
                    except OSError:
                        pass
        except OSError:
            pass


_STEP_PAT = re.compile(r"(\d+)")


def latest_checkpoint(ckpt_dir):
    """Newest COMPLETE checkpoint under ``ckpt_dir``.

    Discovery is manifest-based for checkpoint-runtime saves
    (``paddle_tpu.checkpoint``): a directory only counts once its
    commit manifest parses, the step comes FROM the manifest — a
    directory name is never trusted on its own — and the generation
    must additionally VERIFY against its manifest (checksums, sizes,
    shard coverage), so neither a torn save (killed mid-write, before
    the commit rename) nor a torn GENERATION (committed, then
    truncated/bit-rotted/short a shard) can ever be picked up:
    discovery falls back to the next-newest intact one instead.
    Legacy layouts remain discoverable: bare distributed-checkpoint
    dirs need a parsable metadata.json; paddle.save files are plain
    files ordered by the trailing step number in the name (else
    mtime). Returns a path or None."""
    if not os.path.isdir(ckpt_dir):
        return None
    from ....checkpoint.commit import (
        _STEP_DIR_RE,
        TMP_SUFFIX,
        read_manifest,
        verify_checkpoint,
    )

    candidates = []  # (step, mtime, path, needs_verify)
    for name in os.listdir(ckpt_dir):
        p = os.path.join(ckpt_dir, name)
        if os.path.isdir(p):
            if name.endswith(TMP_SUFFIX):
                continue  # in-flight or orphaned save: never committed
            manifest = read_manifest(p)
            if manifest is not None:
                candidates.append(
                    (int(manifest["step"]), os.path.getmtime(p), p, True)
                )
                continue
            if _STEP_DIR_RE.fullmatch(name):
                # runtime-layout name (commit.step_dir_name's shape —
                # ONE regex, shared with the commit module, so a
                # >8-digit step can't slip past) WITHOUT its commit
                # manifest: the commit protocol writes the manifest
                # last, so this is a torn/rotted generation
                # masquerading as a legacy dir (its serializer
                # metadata.json would parse) — never trust it
                continue
            meta = os.path.join(p, "metadata.json")
            try:
                import json

                with open(meta) as f:
                    json.load(f)
            except (OSError, ValueError):
                continue  # torn save: absent or unparsable metadata
        nums = _STEP_PAT.findall(name)
        step = int(nums[-1]) if nums else -1
        candidates.append((step, os.path.getmtime(p), p, False))
    for step, _mtime, path, needs_verify in sorted(candidates,
                                                   reverse=True):
        # "files" level: per-file size + CRC against the manifest (the
        # serializer metadata coverage check needs the full runtime
        # layout, which bare manifest dirs legitimately lack)
        if needs_verify and verify_checkpoint(path, level="files"):
            continue  # torn generation: fall back to the previous one
        return path
    return None
