"""ElasticManager — peer registry, scale events, restart-from-checkpoint.

Reference parity: python/paddle/distributed/fleet/elastic/manager.py
(unverified, mount empty): nodes register under an etcd job prefix, watch
peer keys, detect scale-in/scale-out, rewrite PADDLE_TRAINER_ENDPOINTS,
and restart workers from the latest checkpoint.

TPU redesign: the registry is a directory of per-node heartbeat files
(name = node rank, contents = endpoint, liveness = mtime) instead of
etcd — on TPU pods the jobs already share a filesystem (GCS fuse / NFS)
and `jax.distributed` supplies the in-job coordination service, so the
only piece elastic needs is the OUT-of-job membership view that survives
process death. The manager's surface (register/watch/endpoint rewrite /
ElasticStatus) mirrors the reference so launcher logic ports unchanged.

Recovery model is the reference's: restart-from-checkpoint, not
in-flight repair. `latest_checkpoint` picks the newest complete save in
a directory (distributed-checkpoint dirs with metadata.json, or
paddle.save files), for the training script to resume from.
"""
from __future__ import annotations

import os
import re
import threading
import time


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    def __init__(self, job_id, registry_dir, node_rank, endpoint,
                 np_range=(1, 1), heartbeat_interval=None,
                 timeout=6.0):
        if heartbeat_interval is None:
            # beats must outpace the staleness timeout or live peers flap
            heartbeat_interval = max(0.05, timeout / 4.0)
        self.job_id = job_id
        self.dir = os.path.join(registry_dir, job_id, "nodes")
        os.makedirs(self.dir, exist_ok=True)
        self.node_rank = int(node_rank)
        self.endpoint = endpoint
        self.lo, self.hi = int(np_range[0]), int(np_range[1])
        self.heartbeat_interval = heartbeat_interval
        self.timeout = timeout
        self._stop = threading.Event()
        self._thread = None
        self._last_view = None

    # ------------------------------------------------------------ registry
    def _path(self, rank=None):
        return os.path.join(
            self.dir, str(self.node_rank if rank is None else rank)
        )

    def register(self):
        """Write this node's heartbeat file and start refreshing it."""
        self._write()
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._beat, daemon=True
            )
            self._thread.start()
        return self

    def _write(self):
        tmp = self._path() + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.endpoint)
        os.replace(tmp, self._path())

    def _beat(self):
        while not self._stop.wait(self.heartbeat_interval):
            try:
                self._write()
            except OSError:
                pass

    def deregister(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        try:
            os.remove(self._path())
        except OSError:
            pass

    # ---------------------------------------------------------------- view
    def peers(self):
        """Live peers: [(rank, endpoint)] sorted by rank; a peer whose
        heartbeat is older than ``timeout`` counts as dead."""
        now = time.time()
        out = []
        try:
            names = os.listdir(self.dir)
        except OSError:
            return out
        for name in names:
            if not name.isdigit():
                continue
            p = os.path.join(self.dir, name)
            try:
                st = os.stat(p)
                if now - st.st_mtime > self.timeout:
                    continue
                with open(p) as f:
                    out.append((int(name), f.read().strip()))
            except OSError:
                continue
        return sorted(out)

    def endpoints(self):
        """PADDLE_TRAINER_ENDPOINTS for the CURRENT membership (the
        endpoint-rewrite step of a scale event)."""
        return ",".join(ep for _, ep in self.peers())

    def watch(self):
        """One poll: HOLD while membership is unchanged and within range,
        RESTART when it changed but still >= lo nodes, EXIT when below
        the minimum."""
        view = tuple(self.peers())
        prev, self._last_view = self._last_view, view
        n = len(view)
        if n < self.lo:
            return ElasticStatus.EXIT
        if prev is not None and view != prev:
            return ElasticStatus.RESTART
        return ElasticStatus.HOLD


_STEP_PAT = re.compile(r"(\d+)")


def latest_checkpoint(ckpt_dir):
    """Newest COMPLETE checkpoint under ``ckpt_dir``.

    Discovery is manifest-based for checkpoint-runtime saves
    (``paddle_tpu.checkpoint``): a directory only counts once its
    commit manifest parses, and the step comes FROM the manifest — a
    directory name is never trusted on its own, so a torn save (killed
    mid-write, before the commit rename) can never be picked up.
    Legacy layouts remain discoverable: bare distributed-checkpoint
    dirs need a parsable metadata.json; paddle.save files are plain
    files ordered by the trailing step number in the name (else
    mtime). Returns a path or None."""
    if not os.path.isdir(ckpt_dir):
        return None
    from ....checkpoint.commit import TMP_SUFFIX, read_manifest

    candidates = []
    for name in os.listdir(ckpt_dir):
        p = os.path.join(ckpt_dir, name)
        if os.path.isdir(p):
            if name.endswith(TMP_SUFFIX):
                continue  # in-flight or orphaned save: never committed
            manifest = read_manifest(p)
            if manifest is not None:
                candidates.append(
                    (int(manifest["step"]), os.path.getmtime(p), p)
                )
                continue
            meta = os.path.join(p, "metadata.json")
            try:
                import json

                with open(meta) as f:
                    json.load(f)
            except (OSError, ValueError):
                continue  # torn save: absent or unparsable metadata
        nums = _STEP_PAT.findall(name)
        step = int(nums[-1]) if nums else -1
        candidates.append((step, os.path.getmtime(p), p))
    if not candidates:
        return None
    return max(candidates)[-1]
