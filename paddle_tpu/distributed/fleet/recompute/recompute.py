"""Activation recomputation (gradient checkpointing).

Reference parity: python/paddle/distributed/fleet/recompute/recompute.py
(unverified, mount empty): ``recompute(function, *args)`` re-runs the
forward during backward instead of storing activations;
``recompute_sequential`` splits a Sequential into recomputed segments.

TPU redesign: ``jax.checkpoint`` IS the mechanism — applied to the pure
functional form of the block. On the eager tape the vjp closure holds only
the block inputs (jax.checkpoint discards internals and replays them at
cotangent time); inside a compiled step the outer jit sees the remat
annotation and XLA drops/replays the activations (the memory win the
reference gets from storing segment boundaries only).
"""
from __future__ import annotations

import jax

from ....core import dispatch, random as random_mod, tape
from ....core.tensor import Tensor
from ....nn.layer.layers import Layer


def _tensor_args(args):
    return [a for a in args if isinstance(a, Tensor)]


def recompute(function, *args, **kwargs):
    """Run ``function(*args)`` with activation recomputation.

    ``function``: a Layer or callable over Tensors. Gradients flow to both
    the inputs and (for Layers) the parameters; intermediate activations
    inside the block are rematerialized during backward.
    """
    use_reentrant = kwargs.pop("use_reentrant", True)  # noqa: F841 (parity)
    preserve_rng_state = kwargs.pop("preserve_rng_state", True)
    if kwargs:
        raise TypeError(f"unexpected kwargs {sorted(kwargs)}")

    params: list = []
    buffers: dict = {}
    if isinstance(function, Layer):
        params = list(function.named_parameters())
        buffers = {k: b.value for k, b in function.named_buffers()}

    n_params = len(params)
    tensor_in = _tensor_args(args)
    rng_key = random_mod.next_key() if preserve_rng_state else None

    def pure(*vals):
        pvals = vals[:n_params]
        ivals = vals[n_params:]
        it = iter(ivals)
        call_args = [
            Tensor(next(it)) if isinstance(a, Tensor) else a for a in args
        ]
        import contextlib

        km = (
            random_mod.key_scope(rng_key)
            if rng_key is not None
            else contextlib.nullcontext()
        )
        # snapshot so the layer's concrete values are restored after the
        # traced run — pure() executes under jax.vjp/checkpoint traces,
        # and leaving tracers in parameters would poison every later use
        # of the layer. (Consequence: buffer updates, e.g. BatchNorm
        # running stats, are dropped inside recomputed blocks.)
        if isinstance(function, Layer):
            orig_p = {k: p.value for k, p in params}
            orig_b = {k: b.value for k, b in function.named_buffers()}
        try:
            with tape.trace_scope(), tape.no_grad(), km:
                if isinstance(function, Layer):
                    function.load_functional_state(
                        dict(zip((k for k, _ in params), pvals)), buffers
                    )
                out = function(*call_args)
        finally:
            if isinstance(function, Layer):
                function.load_functional_state(orig_p, orig_b)
        if isinstance(out, (list, tuple)):
            return tuple(
                o.value if isinstance(o, Tensor) else o for o in out
            )
        return out.value if isinstance(out, Tensor) else out

    ckpt = jax.checkpoint(pure)
    all_inputs = [p for _, p in params] + tensor_in
    return dispatch.apply("recompute", ckpt, tuple(all_inputs), cache=False)


def recompute_sequential(ctx, model, *args, **kwargs):
    """Recompute a Sequential in segments (reference:
    paddle.incubate.distributed.fleet.recompute_sequential).

    ctx: {"segments": N, "preserve_rng_state": bool}
    """
    segments = int(ctx.get("segments", 1)) if isinstance(ctx, dict) else int(ctx)
    preserve = (
        ctx.get("preserve_rng_state", True) if isinstance(ctx, dict) else True
    )
    layers = list(model)
    if segments <= 0:
        raise ValueError("segments must be positive")
    per = max(1, len(layers) // segments)
    out = args
    i = 0
    while i < len(layers):
        chunk = layers[i : i + per]
        i += per

        class _Seg(Layer):
            def __init__(self, mods):
                super().__init__()
                for j, m in enumerate(mods):
                    self.add_sublayer(str(j), m)
                self._mods = mods

            def forward(self, *xs):
                y = xs
                for m in self._mods:
                    y = m(*y) if isinstance(y, tuple) else m(y)
                    if not isinstance(y, tuple):
                        y = (y,)
                return y if len(y) > 1 else y[0]

        seg = _Seg(chunk)
        res = recompute(seg, *out, preserve_rng_state=preserve)
        out = res if isinstance(res, tuple) else (res,)
    return out if len(out) > 1 else out[0]
