"""Recompute package (reference: …/fleet/recompute/)."""
from .recompute import recompute, recompute_sequential  # noqa: F401
