"""paddle.distributed.launch — the multi-process launcher.

Reference parity: python/paddle/distributed/launch/ (unverified, mount
empty): ``python -m paddle_tpu.distributed.launch --nnodes ... train.py``
spawns one worker process per host slot, exporting the PADDLE_TRAINER_*
env contract. On TPU one process per HOST (not per chip) is the jax model;
``--nproc_per_node`` defaults to 1 accordingly, and the coordinator address
feeds jax.distributed.initialize.
"""
from .main import launch, main  # noqa: F401
