"""Launcher implementation.

Reference parity: python/paddle/distributed/launch/main.py + the
CollectiveController (controllers/collective.py — unverified, mount
empty): builds the pod, exports PADDLE_TRAINER_ID / PADDLE_TRAINER_ENDPOINTS
/ PADDLE_MASTER, spawns children, tails logs, tears the pod down on a
child crash, and (elastic) restarts from checkpoint.
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time


def _parse_args(argv):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="launch distributed training (one worker per host slot)",
    )
    p.add_argument("--nnodes", type=str, default="1",
                   help="number of nodes, or range lo:hi for elastic")
    p.add_argument("--nproc_per_node", type=int,
                   default=int(os.environ.get("PADDLE_NPROC_PER_NODE", "1")),
                   help="worker processes per node (TPU: 1 per host)")
    p.add_argument("--master", type=str,
                   default=os.environ.get("PADDLE_MASTER", ""),
                   help="coordinator ip:port")
    p.add_argument("--node_rank", type=int,
                   default=int(os.environ.get("PADDLE_NODE_RANK", "0")))
    p.add_argument("--ips", type=str, default="",
                   help="comma-separated node hostnames/IPs, node 0 first "
                        "(defaults to the master host for every node)")
    p.add_argument("--log_dir", type=str, default="log")
    p.add_argument("--gpus", "--devices", dest="devices", type=str, default="")
    p.add_argument("--run_mode", type=str, default="collective",
                   choices=["collective", "ps", "rpc"],
                   help="collective (default), ps (parameter-server), or "
                        "rpc (named-worker RPC group)")
    p.add_argument("--server_num", type=int,
                   default=int(os.environ.get("PADDLE_SERVER_NUM", "1")),
                   help="ps mode: number of server processes")
    p.add_argument("--trainer_num", type=int,
                   default=int(os.environ.get("PADDLE_TRAINER_NUM", "2")),
                   help="ps mode: number of trainer processes")
    p.add_argument("--job_id", type=str, default="default")
    p.add_argument("--max_restart", type=int,
                   default=int(os.environ.get("PADDLE_ELASTIC_MAX_RESTART", "0")))
    p.add_argument("--elastic_registry", type=str,
                   default=os.environ.get("PADDLE_ELASTIC_REGISTRY", ""),
                   help="shared dir for the elastic peer registry "
                        "(default <log_dir>/.elastic)")
    p.add_argument("--elastic_timeout", type=float,
                   default=float(os.environ.get(
                       "PADDLE_ELASTIC_TIMEOUT", "6")),
                   help="heartbeat staleness before a peer counts dead")
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _own_host(args):
    """This node's address: its --ips entry when given, else the master
    host (single-host default)."""
    master = args.master or "127.0.0.1:49175"
    if args.ips:
        hosts = [h.strip() for h in args.ips.split(",")]
        if args.node_rank < len(hosts):
            return hosts[args.node_rank]
    return master.split(":")[0]


def _spawn(args, nnodes, hosts_override=None, node_index=None):
    nproc = args.nproc_per_node
    world = nnodes * nproc
    master = args.master or "127.0.0.1:49175"
    master_host = master.split(":")[0]
    base_port = int(master.split(":")[1]) if ":" in master else 49175
    if hosts_override is not None:
        hosts = hosts_override  # elastic endpoint rewrite (live peers)
    elif args.ips:
        hosts = [h.strip() for h in args.ips.split(",")]
    else:
        hosts = [master_host] * nnodes
    if len(hosts) != nnodes:
        raise SystemExit(
            f"--ips lists {len(hosts)} hosts but --nnodes is {nnodes}"
        )
    endpoints = []
    for n in range(nnodes):
        for i in range(nproc):
            endpoints.append(f"{hosts[n]}:{base_port + n * nproc + i}")

    os.makedirs(args.log_dir, exist_ok=True)
    # after a scale event the surviving nodes are renumbered by their
    # position in the live-peer list (node_index); fresh pods keep the
    # operator-assigned node_rank
    node_pos = args.node_rank if node_index is None else node_index
    procs = []
    for local_rank in range(nproc):
        rank = node_pos * nproc + local_rank
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_LOCAL_RANK": str(local_rank),
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
            "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
            "PADDLE_MASTER": master,
            "FLAGS_selected_tpus": str(local_rank),
        })
        log_path = os.path.join(args.log_dir, f"workerlog.{local_rank}")
        logf = open(log_path, "w")
        cmd = [sys.executable, "-u", args.training_script] + args.training_script_args
        procs.append(
            (subprocess.Popen(cmd, env=env, stdout=logf, stderr=subprocess.STDOUT),
             logf, log_path)
        )
    return procs


def _watch(procs, manager=None):
    """Reference controller behavior: any child crash tears down the pod;
    with an elastic manager attached, peer-membership changes do too
    (returning "membership"/"scale_exit" so launch() can rewrite
    endpoints and respawn, or give up below the minimum)."""
    from ..fleet.elastic import ElasticStatus

    try:
        while True:
            alive = 0
            for proc, _, log_path in procs:
                code = proc.poll()
                if code is None:
                    alive += 1
                elif code != 0:
                    sys.stderr.write(
                        f"worker failed (exit {code}); see {log_path}; "
                        "terminating pod\n"
                    )
                    _kill(procs)
                    return code
            if alive == 0:
                return 0
            if manager is not None:
                status = manager.watch()
                if status == ElasticStatus.RESTART:
                    sys.stderr.write(
                        "elastic: peer membership changed; "
                        "restarting pod with rewritten endpoints\n"
                    )
                    _kill(procs)
                    return "membership"
                if status == ElasticStatus.EXIT:
                    sys.stderr.write(
                        "elastic: live nodes below minimum; exiting\n"
                    )
                    _kill(procs)
                    return "scale_exit"
            time.sleep(0.5)
    except KeyboardInterrupt:
        _kill(procs)
        return 130


def _kill(procs):
    for proc, _, _ in procs:
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
    deadline = time.time() + 5
    for proc, logf, _ in procs:
        try:
            proc.wait(timeout=max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            proc.kill()
        logf.close()


def _spawn_ps(args):
    """PS controller: server + trainer processes with the reference env
    contract (TRAINING_ROLE / PADDLE_PSERVERS_IP_PORT_LIST / ...)."""
    master = args.master or "127.0.0.1:49920"
    host = master.split(":")[0]
    base_port = int(master.split(":")[1]) if ":" in master else 49920
    server_eps = ",".join(
        f"{host}:{base_port + i}" for i in range(args.server_num)
    )
    os.makedirs(args.log_dir, exist_ok=True)
    procs = []

    def child(role, idx):
        env = dict(os.environ)
        env.update({
            "TRAINING_ROLE": role,
            "PADDLE_PSERVERS_IP_PORT_LIST": server_eps,
            "PADDLE_TRAINERS_NUM": str(args.trainer_num),
            "PADDLE_TRAINER_ID": str(idx if role == "TRAINER" else 0),
            "PADDLE_SERVER_ID": str(idx if role == "PSERVER" else 0),
            "PADDLE_MASTER": master,
        })
        tag = f"{role.lower()}.{idx}"
        log_path = os.path.join(args.log_dir, f"workerlog.{tag}")
        logf = open(log_path, "w")
        cmd = [sys.executable, "-u", args.training_script] \
            + args.training_script_args
        procs.append((
            subprocess.Popen(cmd, env=env, stdout=logf,
                             stderr=subprocess.STDOUT),
            logf, log_path,
        ))

    for i in range(args.server_num):
        child("PSERVER", i)
    for i in range(args.trainer_num):
        child("TRAINER", i)
    return procs


def launch(argv=None):
    args = _parse_args(argv if argv is not None else sys.argv[1:])
    if args.run_mode == "ps":
        return _watch(_spawn_ps(args))
    if args.run_mode == "rpc":
        # rpc controller: the collective env contract (PADDLE_TRAINER_ID /
        # PADDLE_TRAINERS_NUM / PADDLE_MASTER) is exactly what
        # distributed.rpc.init_rpc reads for its defaults. Elasticity is
        # a collective-mode feature: a named rpc group cannot be resized
        # in place, so an elastic range is rejected rather than ignored.
        if ":" in args.nnodes:
            raise SystemExit(
                "--run_mode rpc does not support an elastic --nnodes "
                "range (named rpc groups are fixed-size)"
            )
        return _watch(_spawn(args, int(args.nnodes)))
    manager = None
    if ":" in args.nnodes:
        lo, _, hi = args.nnodes.partition(":")
        lo, hi = int(lo), int(hi)
        nnodes = lo
        restarts = args.max_restart or 3
        # elastic mode: join the peer registry so membership changes
        # (a node dying, a replacement appearing) trigger endpoint
        # rewrite + pod restart — the reference's etcd ElasticManager
        from ..fleet.elastic import ElasticManager

        registry = args.elastic_registry or os.path.join(
            args.log_dir, ".elastic"
        )
        manager = ElasticManager(
            args.job_id, registry, args.node_rank,
            endpoint=_own_host(args),
            np_range=(lo, hi), timeout=args.elastic_timeout,
        ).register()
        # quorum wait: ordinary start skew must not make an early node
        # spawn an undersized pod and EXIT below the minimum
        grace = max(10.0, args.elastic_timeout * 5)
        deadline = time.time() + grace
        while len(manager.peers()) < lo and time.time() < deadline:
            time.sleep(0.2)
        if len(manager.peers()) < lo:
            sys.stderr.write(
                f"elastic: only {len(manager.peers())} of the minimum "
                f"{lo} nodes registered within {grace:.0f}s; aborting\n"
            )
            manager.deregister()
            return 1
    else:
        nnodes = int(args.nnodes)
        restarts = args.max_restart
    attempt = 0
    m_restarts = 0
    try:
        while True:
            hosts = None
            node_index = None
            if manager is not None:
                # ONE registry snapshot: both the spawned host list and
                # the watch baseline come from it (a peer dying between
                # two reads would otherwise go unnoticed)
                peers = manager.peers()
                manager._last_view = tuple(peers)
                if peers:
                    nnodes = max(min(len(peers), hi), 1)
                    peers = peers[:nnodes]
                    hosts = [ep for _, ep in peers]
                    ranks = [r for r, _ in peers]
                    if args.node_rank in ranks:
                        node_index = ranks.index(args.node_rank)
            procs = _spawn(args, nnodes, hosts_override=hosts,
                           node_index=node_index)
            pod_started = time.time()
            code = _watch(procs, manager)
            if code == "scale_exit":
                return 1
            if code == "membership":
                if time.time() - pod_started > max(
                    60.0, args.elastic_timeout * 10
                ):
                    # a stable run preceded this event: normal elasticity
                    # (preemption days apart), not flapping
                    m_restarts = 0
                m_restarts += 1
                if m_restarts > max(10, restarts * 3):
                    sys.stderr.write(
                        "elastic: membership flapping "
                        f"({m_restarts} restarts); giving up — check "
                        "--elastic_timeout vs real heartbeat latency\n"
                    )
                    return 1
                sys.stderr.write(
                    "elastic restart (membership change; resume from "
                    "checkpoint)\n"
                )
                continue  # membership restarts have their own cap
            if code == 0 or code == 130 or attempt >= restarts:
                # 130 = operator Ctrl-C: never auto-restart
                return code
            attempt += 1
            sys.stderr.write(
                f"elastic restart {attempt}/{restarts} "
                "(resume from checkpoint)\n"
            )
    finally:
        if manager is not None:
            manager.deregister()


def main():
    sys.exit(launch())


if __name__ == "__main__":
    main()
