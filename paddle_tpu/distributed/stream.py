"""paddle.distributed.stream parity (python/paddle/distributed/
communication/stream/ — unverified): the reference's stream-scoped
collective variants. XLA owns stream scheduling on TPU, so these are
the same collectives with ``sync_op``/``use_calc_stream`` accepted for
signature parity (async tasks are returned when sync_op=False)."""
from .communication import (  # noqa: F401
    all_gather,
    all_reduce,
    alltoall,
    broadcast,
    gather,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    send,
)
