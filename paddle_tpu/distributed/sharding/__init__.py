"""paddle.distributed.sharding parity surface."""
from .group_sharded import (  # noqa: F401
    GroupShardedOptimizerStage2,
    build_placements,
    group_sharded_parallel,
    install_stage1_placements,
    save_group_sharded_model,
    shard_spec_for,
)
