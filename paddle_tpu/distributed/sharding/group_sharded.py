"""Group sharding — ZeRO stages 1/2/3 as sharding placements.

Reference parity: python/paddle/distributed/sharding/group_sharded.py +
…/fleet/meta_parallel/sharding/ (unverified, mount empty):
``group_sharded_parallel(model, optimizer, level)`` with levels
  os      -> ZeRO-1: optimizer state sharded
  os_g    -> ZeRO-2: + gradients sharded (reduce-scatter pattern)
  p_g_os  -> ZeRO-3: + parameters sharded (FSDP)

TPU redesign (SURVEY.md §7: "nearly free via sharding rules"): instead of
the reference's allgather-on-demand buffer machinery, each tier is a
*placement policy* over the ``sharding`` mesh axis:
- stage 1: optimizer accumulators are device_put sharded (and stay so
  through the compiled step via out_shardings pinning);
- stage 2: the compiled step additionally constrains gradients to the
  same sharded layout, which XLA realizes as reduce-scatter + sharded
  update + allgather exactly where needed;
- stage 3: parameter storage itself is sharded; XLA inserts allgathers
  at use sites (and their duals in backward).
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor
from ...parallel import mesh as mesh_mod

_LEVELS = ("os", "os_g", "p_g_os")


def _pick_axis(group=None):
    if group is not None and getattr(group, "mesh_axis", None):
        return group.mesh_axis
    shape = mesh_mod.global_mesh_shape()
    for cand in ("sharding", "dp"):
        if shape.get(cand, 1) > 1:
            return cand
    return "sharding"


def shard_spec_for(shape, axis, degree):
    """Placement for one tensor: shard the first dim that divides the
    degree evenly (weights' big dims ride the sharding axis); tensors
    with no evenly-divisible dim replicate."""
    for d, s in enumerate(shape):
        if s >= degree and s % degree == 0:
            spec = [None] * len(shape)
            spec[d] = axis
            return P(*spec)
    return P()


def install_stage1_placements(optimizer, named_params, axis=None, mesh=None):
    """ZeRO-1: record per-param accumulator placements AND re-place any
    accumulators that already exist (resumed state, prior eager steps)."""
    mesh = mesh or mesh_mod.get_mesh()
    named = list(named_params)
    placements = build_placements(named, axis, mesh)
    acc = dict(getattr(optimizer, "_acc_placements", {}))
    for name, p in named:
        acc[id(p)] = placements[name]
    optimizer._acc_placements = acc
    for key, v in list(optimizer._accumulators.items()):
        sh = acc.get(key[0])
        if sh is not None and getattr(v, "ndim", 0) > 0:
            optimizer._accumulators[key] = jax.device_put(v, sh)
    return placements


def build_placements(named_params, axis=None, mesh=None):
    """name -> NamedSharding for every parameter-shaped tensor."""
    mesh = mesh or mesh_mod.get_mesh()
    axis = axis or _pick_axis()
    degree = mesh_mod.global_mesh_shape().get(axis, 1)
    out = {}
    for name, p in named_params:
        out[name] = NamedSharding(
            mesh, shard_spec_for(tuple(p.shape), axis, degree)
        )
    return out


class GroupShardedOptimizerStage2:
    """Marker/wrapper kept for reference API parity; the placement policy
    is installed by group_sharded_parallel."""

    def __init__(self, params, optim, group=None, **kw):
        self._inner = optim

    def __getattr__(self, name):
        return getattr(self.__dict__["_inner"], name)


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False,
                           buffer_max_size=2**23, segment_size=2**20,
                           sync_comm=False, dp_group=None,
                           exclude_layer=None):
    """Install the ZeRO placement policy for ``level`` on model+optimizer.

    Returns (model, optimizer, scaler) like the reference. The same
    imperative objects are returned — sharding is carried by array
    placements and consumed by CompiledTrainStep/eager ops alike.

    Stage-2 semantics on TPU (an intentional divergence from the
    reference's eager reducer): gradient sharding is a COMPILED-path
    property. Inside ``CompiledTrainStep`` the installed
    ``_grad_placements`` constrain each grad to its owner shard and XLA
    realizes the reduce-scatter + sharded-update pattern; on the eager
    path gradients stay replicated as produced — eager ZeRO-2 gives no
    memory win here (use the compiled trainer, which is the TPU perf
    path anyway). Stage-1 (optimizer state) and stage-3 (parameter)
    placements apply on both paths.
    """
    if level not in _LEVELS:
        raise ValueError(f"level must be one of {_LEVELS}, got {level!r}")
    if offload:
        raise NotImplementedError(
            "offload=True (CPU offload) is not supported on the TPU build"
        )
    mesh = mesh_mod.get_mesh()
    axis = _pick_axis(group)
    degree = mesh_mod.global_mesh_shape().get(axis, 1)
    named = list(model.named_parameters())

    # stage 1: optimizer state sharded
    placements = install_stage1_placements(optimizer, named, axis, mesh)

    # stage 2: gradients sharded (consumed by CompiledTrainStep; the eager
    # path keeps grads as produced — the memory win is a compiled-path
    # property on TPU)
    if level in ("os_g", "p_g_os"):
        optimizer._grad_placements = {
            name: placements[name] for name, _ in named
        }

    # every array in the step must live on the same device set as the
    # sharded optimizer state: params/buffers go onto the mesh too —
    # sharded for stage 3 (FSDP), replicated otherwise
    replicated = NamedSharding(mesh, P())
    for name, p in named:
        p.value = jax.device_put(
            p.value, placements[name] if level == "p_g_os" else replicated
        )
    for _, b in model.named_buffers():
        if getattr(b.value, "ndim", None) is not None:
            b.value = jax.device_put(b.value, replicated)
    if level == "p_g_os":
        optimizer._param_placements = {
            name: placements[name] for name, _ in named
        }

    model._group_sharded_level = level
    model._group_sharded_axis = axis
    model._group_sharded_degree = degree
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    """Gather + save full (unsharded) model/optimizer state (reference
    parity: save_group_sharded_model writes rank-0 full state)."""
    import os

    from ...framework import io as fw_io

    os.makedirs(output, exist_ok=True)
    fw_io.save(
        model.state_dict(), os.path.join(output, "model.pdmodel")
    )
    if optimizer is not None:
        fw_io.save(
            optimizer.state_dict(), os.path.join(output, "model.pdopt")
        )
