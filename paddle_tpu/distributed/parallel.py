"""init_parallel_env / ParallelEnv / DataParallel.

Reference parity: python/paddle/distributed/parallel.py + the C++
EagerReducer (…/collective/reducer.cc — unverified, mount empty).

TPU redesign:
- init_parallel_env -> jax.distributed.initialize (the coordination
  service replaces TCPStore rendezvous) + global mesh construction.
- DataParallel: the *compiled* path needs no reducer at all — fleet's
  trainer shards the batch over the mesh's dp axis and XLA inserts the
  gradient all-reduce (that is the whole point of SPMD). The eager path
  keeps reference semantics with post-backward gradient sync via
  ProcessGroupICI (bucketed: one fused allreduce over flattened grads,
  mirroring EagerReducer's bucketing).
"""
from __future__ import annotations

import os

import numpy as np

import jax

from ..nn.layer.layers import Layer
from . import env as dist_env

_PARALLEL_ENV = {"initialized": False}


class ParallelEnv:
    @property
    def rank(self):
        return dist_env.get_rank()

    @property
    def local_rank(self):
        return int(os.environ.get("PADDLE_LOCAL_RANK", self.rank))

    @property
    def world_size(self):
        return dist_env.get_world_size()

    @property
    def nranks(self):
        return self.world_size

    @property
    def dev_id(self):
        return self.local_rank

    @property
    def device_type(self):
        return "tpu"

    @property
    def current_endpoint(self):
        return dist_env.get_current_endpoint()

    @property
    def trainer_endpoints(self):
        return dist_env.get_trainer_endpoints()


def init_parallel_env():
    """Initialize multi-process coordination + the global device mesh."""
    if _PARALLEL_ENV["initialized"]:
        return ParallelEnv()
    world = dist_env.get_world_size()
    coord = os.environ.get("PADDLE_MASTER") or os.environ.get(
        "MASTER_ADDR_PORT"
    )
    if world > 1 and not jax._src.distributed.global_state.client:
        eps = dist_env.get_trainer_endpoints()
        coordinator = coord or (eps[0] if eps else None)
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=world,
            process_id=dist_env.get_rank(),
        )
    from ..parallel import mesh as mesh_mod

    if not mesh_mod.mesh_defined():
        mesh_mod.init_mesh({"dp": len(jax.devices())})
    _PARALLEL_ENV["initialized"] = True
    return ParallelEnv()


def get_rank(group=None):
    return dist_env.get_rank() if group is None else group.rank


def get_world_size(group=None):
    return dist_env.get_world_size() if group is None else group.nranks


class DataParallel(Layer):
    """Eager data-parallel wrapper with reducer semantics.

    After .backward(), call ``opt.step()`` as usual: gradient sync happens
    lazily on first parameter access via the fused allreduce (or call
    ``sync_gradients()`` explicitly; paddle's reducer does it inside
    backward — here backward is tape-driven, so sync is fused at step
    boundary, same math, one collective).
    """

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self._group = group
        self._hooked = False
        if dist_env.get_world_size() > 1:
            self._register_sync_hooks()

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def _register_sync_hooks(self):
        if self._hooked:
            return
        self._hooked = True
        from .communication import _world_group

        # sync happens at the step boundary (fleet optimizer wrapper calls
        # sync_gradients / user calls apply_collective_grads) — one fused
        # collective, same math as the reference's bucketed reducer
        self._dp_group = self._group or _world_group()
        self._dp_params = [
            p for p in self._layers.parameters() if not p.stop_gradient
        ]

    def sync_gradients(self):
        if dist_env.get_world_size() <= 1:
            return  # hooks (and _dp_params) only exist multi-process
        from .fleet.utils.hybrid_parallel_util import (
            fused_allreduce_gradients,
        )

        fused_allreduce_gradients(self._dp_params, group=self._dp_group)

    # delegate attribute access to the wrapped layers (paddle parity)
    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(self.__dict__["_sub_layers"]["_layers"], name)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, *args, **kwargs):
        return self._layers.set_state_dict(*args, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        self.sync_gradients()
