"""Process-level distributed environment.

Reference parity: the PADDLE_TRAINER_* env contract set by
paddle.distributed.launch (python/paddle/distributed/launch/ — unverified,
mount empty) and consumed by fleet/parallel init. On TPU the same contract
maps onto jax.distributed's process index/count.
"""
from __future__ import annotations

import os


def get_rank():
    v = os.environ.get("PADDLE_TRAINER_ID")
    if v is not None:
        return int(v)
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def get_world_size():
    v = os.environ.get("PADDLE_TRAINERS_NUM")
    if v is not None:
        return int(v)
    try:
        import jax

        return jax.process_count()
    except Exception:
        return 1


def get_trainer_endpoints():
    eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
    return eps.split(",") if eps else []


def get_current_endpoint():
    return os.environ.get("PADDLE_CURRENT_ENDPOINT", "")
