"""paddle.distributed.* communication API.

Reference parity: python/paddle/distributed/communication/ (unverified,
mount empty): module-level collective functions + group management, backed
by ProcessGroupICI.
"""
from __future__ import annotations

from ..core.tensor import Tensor
from . import env as dist_env
from .process_group import ProcessGroup, ProcessGroupICI, ReduceOp, Task  # noqa: F401

_GROUPS: dict = {}
_NEXT_ID = [0]


def _world_group():
    if "world" not in _GROUPS:
        _GROUPS["world"] = ProcessGroup(
            list(range(dist_env.get_world_size())), pg_id=0
        )
    return _GROUPS["world"]


def new_group(ranks=None, backend="ici", timeout=None):
    _NEXT_ID[0] += 1
    g = ProcessGroup(
        ranks if ranks is not None else list(range(dist_env.get_world_size())),
        pg_id=_NEXT_ID[0],
        backend=backend,
    )
    _GROUPS[g.id] = g
    return g


def get_group(gid=0):
    return _GROUPS.get(gid, _world_group())


def destroy_process_group(group=None):
    if group is None:
        _GROUPS.clear()
    else:
        _GROUPS.pop(group.id, None)


def _g(group):
    return group if group is not None else _world_group()


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    return _g(group).all_reduce(tensor, op, sync_op)


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    return _g(group).all_gather(tensor_list, tensor, sync_op)


def all_gather_object(object_list, obj, group=None):
    import pickle

    import numpy as np

    import jax.numpy as jnp

    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    g = _g(group)
    if g.nranks == 1:
        object_list.append(obj)
        return
    # variable length: pad to max via a length-prefix allgather
    from jax.experimental import multihost_utils

    ln = multihost_utils.process_allgather(
        jnp.asarray([payload.size]), tiled=False
    )
    maxlen = int(np.max(np.asarray(ln)))
    padded = np.zeros(maxlen, np.uint8)
    padded[: payload.size] = payload
    data = multihost_utils.process_allgather(jnp.asarray(padded), tiled=False)
    for r in g.ranks:
        n = int(np.asarray(ln)[r][0])
        object_list.append(pickle.loads(bytes(np.asarray(data[r])[:n])))


def broadcast(tensor, src, group=None, sync_op=True):
    g = _g(group)
    return g.broadcast(tensor, g.get_group_rank(src), sync_op)


def reduce(tensor, dst, op=ReduceOp.SUM, group=None, sync_op=True):
    g = _g(group)
    return g.reduce(tensor, g.get_group_rank(dst), op, sync_op)


def reduce_scatter(tensor, tensor_list, op=ReduceOp.SUM, group=None, sync_op=True):
    return _g(group).reduce_scatter(tensor, tensor_list, op, sync_op)


def alltoall(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    return _g(group).alltoall(out_tensor_list, in_tensor_list, sync_op)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    g = _g(group)
    return g.scatter(tensor, tensor_list, g.get_group_rank(src), sync_op)


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    g = _g(group)
    tmp = []
    g.all_gather(tmp, tensor, sync_op)
    if g.rank == g.get_group_rank(dst) and gather_list is not None:
        gather_list.extend(tmp)
    return Task([t.value for t in tmp])


def send(tensor, dst=0, group=None, sync_op=True):
    return _g(group).send(tensor, dst, sync_op)


def recv(tensor, src=0, group=None, sync_op=True):
    return _g(group).recv(tensor, src, sync_op)


def barrier(group=None):
    return _g(group).barrier()


def wait(tensor, group=None, use_calc_stream=True):
    if isinstance(tensor, Tensor) and hasattr(tensor.value, "block_until_ready"):
        tensor.value.block_until_ready()


def is_initialized():
    from .parallel import _PARALLEL_ENV

    return _PARALLEL_ENV["initialized"]


def broadcast_object_list(object_list, src=0, group=None):
    """In-place: rank src's objects replace everyone's list contents
    (reference contract). Single-process groups are a no-op."""
    g = _g(group)
    if g.nranks == 1:
        return
    gathered = []
    all_gather_object(gathered, list(object_list), group)
    src_objs = gathered[g.get_group_rank(src)]
    object_list[:] = src_objs


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    """Each rank receives its slot of rank src's list."""
    g = _g(group)
    if g.nranks == 1:
        out_object_list[:] = [
            (in_object_list or [None])[0]
        ]
        return
    gathered = []
    all_gather_object(gathered, in_object_list or [], group)
    src_objs = gathered[g.get_group_rank(src)]
    out_object_list[:] = [src_objs[g.rank]]


def get_backend(group=None):
    """The collective backend name; ICI/XLA collectives here (the
    reference returns NCCL/GLOO)."""
    return "XCCL_TPU"


def isend(tensor, dst=0, group=None):
    """Async send: returns the task handle (reference returns a task
    whose wait() blocks)."""
    return send(tensor, dst, group, sync_op=False)


def irecv(tensor, src=0, group=None):
    return recv(tensor, src, group, sync_op=False)
