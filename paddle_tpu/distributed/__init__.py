"""paddle.distributed namespace — populated across build stages (SURVEY §7).

Currently: env contract (rank/world size). Comm API, fleet, launch, and the
parallel wrappers land with the distributed foundation stage.
"""
from .env import (  # noqa: F401
    get_current_endpoint,
    get_rank,
    get_trainer_endpoints,
    get_world_size,
)
