"""paddle.distributed namespace.

Reference parity: python/paddle/distributed/__init__.py (unverified, mount
empty). The comm API is ProcessGroupICI-backed (XLA collectives over
ICI/DCN); fleet/topology build the hybrid jax mesh; the compiled parallel
path lives in paddle_tpu.parallel.
"""
from . import auto_parallel  # noqa: F401
from . import checkpoint  # noqa: F401
from . import fleet  # noqa: F401
from . import rpc  # noqa: F401
from .auto_parallel import (  # noqa: F401
    DistModel,
    Engine,
    Partial,
    ProcessMesh,
    Replicate,
    Shard,
    get_placements,
    reshard,
    shard_dataloader,
    shard_layer,
    shard_tensor,
    to_static,
)
from .communication import (  # noqa: F401
    ReduceOp,
    all_gather,
    all_gather_object,
    all_reduce,
    alltoall,
    barrier,
    broadcast,
    broadcast_object_list,
    destroy_process_group,
    gather,
    get_backend,
    get_group,
    irecv,
    is_initialized,
    isend,
    new_group,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    scatter_object_list,
    send,
    wait,
)
from . import stream  # noqa: F401
from .env import (  # noqa: F401
    get_current_endpoint,
    get_trainer_endpoints,
)
from .parallel import (  # noqa: F401
    DataParallel,
    ParallelEnv,
    get_rank,
    get_world_size,
    init_parallel_env,
)
from .process_group import ProcessGroup, ProcessGroupICI  # noqa: F401

# spawn-style helper (reference paddle.distributed.spawn)


def _spawn_entry(env, func, args):
    """Module-level so the 'spawn' start method can pickle it."""
    import os

    os.environ.update(env)
    func(*args)


def spawn(func, args=(), nprocs=1, join=True, daemon=False, **options):
    import multiprocessing as mp

    master = options.get("master", "127.0.0.1:49201")
    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(nprocs):
        env = {
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(nprocs),
            "PADDLE_MASTER": master,
            "PADDLE_TRAINER_ENDPOINTS": ",".join(
                f"127.0.0.1:{49210 + i}" for i in range(nprocs)
            ),
            "PADDLE_CURRENT_ENDPOINT": f"127.0.0.1:{49210 + rank}",
        }
        p = ctx.Process(
            target=_spawn_entry, args=(env, func, args), daemon=daemon
        )
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
    return procs
