"""Parameter-server mode (minimal, reference §2.1 'Parameter server' row).

Reference parity: paddle/fluid/distributed/ps/ + fleet PS runtime
(unverified, mount empty): dedicated server processes host parameter
tables; trainer processes pull fresh parameters, compute gradients on
their own data shards, and push gradients back; the server applies
updates immediately (fully asynchronous SGD — the recommender-system
training mode).

TPU build scope: PS mode exists in the reference for sparse recommender
workloads that don't fit accelerators; none of the BASELINE configs use
it, so this is a faithful SKELETON over paddle_tpu.distributed.rpc —
dense tables, pull/push-grad with server-side SGD/Adam application,
round-robin table sharding across multiple servers, and the fleet role
surface (PaddleCloudRoleMaker env contract, is_server/is_worker,
init_server/run_server/stop_worker). Numpy end to end: PS traffic is
host-side by design.
"""
from __future__ import annotations

import os
import threading

import numpy as np

from .. import rpc


class DenseTable:
    """One parameter tensor + its server-side optimizer state."""

    def __init__(self, name, value, optimizer="sgd", lr=0.01,
                 beta1=0.9, beta2=0.999, eps=1e-8):
        self.name = name
        self.value = np.asarray(value, np.float32)
        self.optimizer = optimizer
        self.lr = lr
        self.beta1, self.beta2, self.eps = beta1, beta2, eps
        self._m = np.zeros_like(self.value)
        self._v = np.zeros_like(self.value)
        self._t = 0
        self._lock = threading.Lock()

    def pull(self):
        with self._lock:
            return self.value.copy()

    def push_grad(self, grad):
        g = np.asarray(grad, np.float32)
        with self._lock:
            if self.optimizer == "adam":
                self._t += 1
                self._m = self.beta1 * self._m + (1 - self.beta1) * g
                self._v = self.beta2 * self._v + (1 - self.beta2) * g * g
                mh = self._m / (1 - self.beta1 ** self._t)
                vh = self._v / (1 - self.beta2 ** self._t)
                self.value -= self.lr * mh / (np.sqrt(vh) + self.eps)
            else:  # async SGD
                self.value -= self.lr * g


class SparseTable:
    """Embedding-style table: rows keyed by int64 feature id, created
    lazily on first access (the reference's sparse PS table contract for
    unbounded id spaces). Per-row SGD or Adagrad (the recommender
    default); duplicate ids in one push accumulate sequentially.
    Row init is deterministic in (table seed, id) so every
    trainer/restart sees identical initial embeddings."""

    def __init__(self, name, dim, optimizer="adagrad", lr=0.05,
                 initializer="uniform", init_range=0.01, seed=0, eps=1e-10):
        self.name = name
        self.dim = int(dim)
        self.optimizer = optimizer
        self.lr = lr
        self.initializer = initializer
        self.init_range = init_range
        self.seed = int(seed)
        self.eps = eps
        self.rows = {}
        self._acc = {}  # adagrad accumulators
        self._lock = threading.Lock()

    def _init_row(self, rid):
        if self.initializer == "zeros":
            return np.zeros(self.dim, np.float32)
        rng = np.random.RandomState(
            (self.seed * 1000003 + int(rid)) % (2 ** 32)
        )
        return (
            (rng.rand(self.dim).astype(np.float32) * 2 - 1)
            * self.init_range
        )

    def pull(self, ids):
        with self._lock:
            out = np.empty((len(ids), self.dim), np.float32)
            for i, rid in enumerate(ids):
                rid = int(rid)
                if rid not in self.rows:
                    self.rows[rid] = self._init_row(rid)
                out[i] = self.rows[rid]
            return out

    def push_grad(self, ids, grads):
        g = np.asarray(grads, np.float32)
        with self._lock:
            for i, rid in enumerate(ids):
                rid = int(rid)
                row = self.rows.setdefault(rid, self._init_row(rid))
                if self.optimizer == "adagrad":
                    acc = self._acc.setdefault(
                        rid, np.zeros(self.dim, np.float32)
                    )
                    acc += g[i] * g[i]
                    row -= self.lr * g[i] / (np.sqrt(acc) + self.eps)
                else:  # async SGD
                    row -= self.lr * g[i]

    def state(self):
        with self._lock:
            ids = sorted(self.rows)
            zero = np.zeros(self.dim, np.float32)
            return {
                "dim": self.dim, "optimizer": self.optimizer,
                "lr": self.lr, "seed": self.seed,
                "ids": np.array(ids, np.int64),
                "rows": np.stack([self.rows[i] for i in ids])
                if ids else np.zeros((0, self.dim), np.float32),
                # adagrad accumulators are part of the training state:
                # omitting them collapses/spikes the effective LR on resume
                "acc": np.stack([self._acc.get(i, zero) for i in ids])
                if ids else np.zeros((0, self.dim), np.float32),
            }

    def load_state(self, st):
        with self._lock:
            self.rows = {
                int(i): np.asarray(r, np.float32)
                for i, r in zip(st["ids"], st["rows"])
            }
            acc = st.get("acc")
            if acc is not None:
                self._acc = {
                    int(i): np.asarray(a, np.float32)
                    for i, a in zip(st["ids"], acc)
                }
            else:
                self._acc = {}


class ParameterServer:
    """Process-global table host (one per PSERVER process)."""

    def __init__(self):
        self.tables = {}
        self.sparse_tables = {}
        self._stop = threading.Event()
        self._create_lock = threading.Lock()
        self._barriers = {}

    def create(self, name, value, **kw):
        # rpc handlers run on a thread pool: the check-then-insert must
        # be atomic or a second create could replace a live table
        with self._create_lock:
            if name not in self.tables:
                self.tables[name] = DenseTable(name, value, **kw)
        return name

    def create_sparse(self, name, dim, **kw):
        with self._create_lock:
            if name not in self.sparse_tables:
                self.sparse_tables[name] = SparseTable(name, dim, **kw)
        return name


_SERVER: ParameterServer | None = None


# ---- RPC-executed functions (run inside the server process) -----------
def _ps_create(name, value, kw):
    _SERVER.create(name, value, **kw)
    return True


def _ps_pull(name):
    return _SERVER.tables[name].pull()


def _ps_push(name, grad):
    _SERVER.tables[name].push_grad(grad)
    return True


def _ps_pull_many(names):
    return {n: _SERVER.tables[n].pull() for n in names}


def _ps_push_many(grads):
    for n, g in grads.items():
        _SERVER.tables[n].push_grad(g)
    return True


def _ps_create_sparse(name, dim, kw):
    _SERVER.create_sparse(name, dim, **kw)
    return True


def _ps_pull_sparse(name, ids):
    return _SERVER.sparse_tables[name].pull(ids)


def _ps_push_sparse(name, ids, grads):
    _SERVER.sparse_tables[name].push_grad(ids, grads)
    return True


def _ps_save(dirname, server_name):
    """Server-side checkpoint: dense values + sparse row maps."""
    os.makedirs(dirname, exist_ok=True)
    path = os.path.join(dirname, f"{server_name}.npz")
    payload = {}
    for n, t in _SERVER.tables.items():
        payload[f"dense:{n}"] = t.pull()
    for n, t in _SERVER.sparse_tables.items():
        st = t.state()
        payload[f"sparse_ids:{n}"] = st["ids"]
        payload[f"sparse_rows:{n}"] = st["rows"]
        payload[f"sparse_acc:{n}"] = st["acc"]
    np.savez(path, **payload)
    return path


def _ps_load(dirname, server_name):
    path = os.path.join(dirname, f"{server_name}.npz")
    data = np.load(path)
    for key in data.files:
        kind, name = key.split(":", 1)
        if kind == "dense" and name in _SERVER.tables:
            _SERVER.tables[name].value = data[key].copy()
        elif kind == "sparse_ids" and name in _SERVER.sparse_tables:
            _SERVER.sparse_tables[name].load_state({
                "ids": data[key],
                "rows": data[f"sparse_rows:{name}"],
                "acc": (
                    data[f"sparse_acc:{name}"]
                    if f"sparse_acc:{name}" in data.files else None
                ),
            })
    return True


def _ps_stop():
    _SERVER._stop.set()
    return True


def _ps_barrier(tag, worker, n):
    """Arrive + poll: returns True once all n workers arrived at tag."""
    with _SERVER._create_lock:
        arrived = _SERVER._barriers.setdefault(tag, set())
        arrived.add(worker)
        return len(arrived) >= n


def _server_names():
    infos = rpc.get_all_worker_infos()
    return [w.name for w in infos if w.name.startswith("ps_server")]


def _shard_of(name):
    import zlib

    # stable across processes (hash() is salted per interpreter)
    servers = _server_names()
    return servers[zlib.crc32(name.encode()) % len(servers)]


# ------------------------------------------------------------ role maker
class PaddleCloudRoleMaker:
    """Reads the reference PS env contract: TRAINING_ROLE
    (PSERVER/TRAINER), PADDLE_PSERVERS_IP_PORT_LIST, PADDLE_TRAINERS_NUM,
    PADDLE_TRAINER_ID, POD_IP/PADDLE_PORT."""

    def __init__(self, is_collective=False, **kw):
        self._is_collective = bool(is_collective)
        self.role = os.environ.get("TRAINING_ROLE", "TRAINER").upper()
        self.server_endpoints = [
            e for e in os.environ.get(
                "PADDLE_PSERVERS_IP_PORT_LIST", ""
            ).split(",") if e
        ]
        self.trainers_num = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
        self.trainer_id = int(os.environ.get("PADDLE_TRAINER_ID", 0))
        self.server_index = int(os.environ.get("PADDLE_SERVER_ID", 0))

    def is_server(self):
        return self.role == "PSERVER"

    def is_worker(self):
        return self.role == "TRAINER"

    def is_first_worker(self):
        return self.is_worker() and self.trainer_id == 0


class PSContext:
    """The fleet-facing PS runtime for one process."""

    def __init__(self, role: PaddleCloudRoleMaker,
                 master_endpoint=None):
        self.role = role
        n_servers = max(len(role.server_endpoints), 1)
        world = n_servers + role.trainers_num
        if role.is_server():
            name = f"ps_server{role.server_index}"
            rank = role.server_index
        else:
            name = f"ps_worker{role.trainer_id}"
            rank = n_servers + role.trainer_id
        master = master_endpoint or os.environ.get(
            "PADDLE_MASTER",
            (role.server_endpoints[0] if role.server_endpoints
             else "127.0.0.1:49920"),
        )
        global _SERVER
        if role.is_server():
            _SERVER = ParameterServer()
        rpc.init_rpc(name, rank=rank, world_size=world,
                     master_endpoint=master)
        self.name = name

    # ---------------------------------------------------------- server
    def run_server(self):
        """Serve until a worker calls stop (reference run_server blocks)."""
        _SERVER._stop.wait()
        rpc.shutdown()

    # ---------------------------------------------------------- worker
    def create_tables(self, named_params, optimizer="sgd", lr=0.01):
        for n, v in named_params.items():
            rpc.rpc_sync(
                _shard_of(n), _ps_create,
                args=(n, np.asarray(v, np.float32),
                      {"optimizer": optimizer, "lr": lr}),
            )

    def pull(self, names):
        by_server = {}
        for n in names:
            by_server.setdefault(_shard_of(n), []).append(n)
        out = {}
        futs = [
            (rpc.rpc_async(s, _ps_pull_many, args=(ns,)))
            for s, ns in by_server.items()
        ]
        for f in futs:
            out.update(f.result())
        return out

    def push(self, grads):
        by_server = {}
        for n, g in grads.items():
            by_server.setdefault(_shard_of(n), {})[n] = np.asarray(g)
        futs = [
            rpc.rpc_async(s, _ps_push_many, args=(gs,))
            for s, gs in by_server.items()
        ]
        for f in futs:
            f.result()

    def create_sparse_table(self, name, dim, optimizer="adagrad", lr=0.05,
                            **kw):
        rpc.rpc_sync(
            _shard_of(name), _ps_create_sparse,
            args=(name, int(dim), {"optimizer": optimizer, "lr": lr, **kw}),
        )

    def pull_sparse(self, name, ids):
        """ids: int sequence -> [len(ids), dim] float32 rows."""
        return rpc.rpc_sync(
            _shard_of(name), _ps_pull_sparse,
            args=(name, np.asarray(ids, np.int64)),
        )

    def push_sparse(self, name, ids, grads):
        rpc.rpc_sync(
            _shard_of(name), _ps_push_sparse,
            args=(name, np.asarray(ids, np.int64),
                  np.asarray(grads, np.float32)),
        )

    def save_persistables(self, dirname):
        """fleet.save_persistables analog: every server snapshots its
        shard (dense + sparse) under dirname."""
        for s in _server_names():
            rpc.rpc_sync(s, _ps_save, args=(dirname, s))

    def load_persistables(self, dirname):
        for s in _server_names():
            rpc.rpc_sync(s, _ps_load, args=(dirname, s))

    def barrier(self, tag="default"):
        """Synchronize all trainers through server 0 (PS-mode analog of
        fleet.barrier_worker — gloo in the reference)."""
        import time

        server = _server_names()[0]
        n = self.role.trainers_num
        self._barrier_gen = getattr(self, "_barrier_gen", 0) + 1
        full_tag = f"{tag}:{self._barrier_gen}"
        while not rpc.rpc_sync(
            server, _ps_barrier, args=(full_tag, self.name, n)
        ):
            time.sleep(0.05)

    def trainer_endpoints(self):
        return [
            f"{w.ip}:{w.port}"
            for w in rpc.get_all_worker_infos()
            if w.name.startswith("ps_worker")
        ]

    def stop_servers(self):
        for s in _server_names():
            rpc.rpc_sync(s, _ps_stop)
        rpc.shutdown()
