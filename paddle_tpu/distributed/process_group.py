"""ProcessGroupICI — eager collective API over XLA collectives.

Reference parity: ProcessGroup/ProcessGroupNCCL (paddle/fluid/distributed/
collective/process_group_nccl.cc — unverified, mount empty). North-star
(BASELINE.json): "replace ProcessGroupNCCL with a ProcessGroupICI so
Fleet's collectives ride the pod interconnect."

TPU-first semantics: inside compiled parallel programs collectives are
mesh-axis ops (paddle_tpu.parallel.collectives) — that is the hot path.
This class provides the *eager* paddle.distributed.* contract:

- multi-process (one process per host, jax.distributed initialized): eager
  collectives run as tiny jitted programs over a process-spanning mesh via
  jax.make_array_from_process_local_data — XLA executes them over ICI/DCN.
- single-process: world_size==1 group ops are identity (paddle behavior
  for a 1-rank group).

Async Task handles are returned for API parity; jax dispatch is already
async, so wait() is a block-until-ready.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    AVG = "mean"
    PROD = "prod"


class Task:
    def __init__(self, values):
        self._values = values

    def wait(self):
        for v in self._values:
            if hasattr(v, "block_until_ready"):
                v.block_until_ready()
        return True

    def is_completed(self):
        return True

    def synchronize(self):
        return self.wait()


class ProcessGroup:
    """A set of ranks. rank==-1 means this process is not a member."""

    def __init__(self, ranks, pg_id=0, backend="ici"):
        from . import env as dist_env

        self.ranks = list(ranks)
        self.nranks = len(self.ranks)
        self.id = pg_id
        self.backend = backend
        me = dist_env.get_rank()
        self.rank = self.ranks.index(me) if me in self.ranks else -1

    @property
    def world_size(self):
        return self.nranks

    def get_group_rank(self, global_rank):
        return self.ranks.index(global_rank) if global_rank in self.ranks else -1

    # -------------------------------------------------------- collectives
    def _member_mesh(self):
        """A 1-axis mesh over this group's processes' addressable devices."""
        devs = []
        for r in self.ranks:
            devs.extend(
                d for d in jax.devices() if d.process_index == r
            )
        import numpy as _np

        from jax.sharding import Mesh

        return Mesh(_np.array(devs), axis_names=("pg",))

    def _cross_process(self, local_value, reducer):
        """Run ``reducer`` over per-process values; returns this rank's out."""
        if self.nranks == 1:
            return local_value
        if self.rank < 0:
            raise RuntimeError(
                "collective called on a process that is not a member of "
                f"group {self.id} (paddle semantics: only members call)"
            )
        from . import env as dist_env

        if self.nranks != dist_env.get_world_size():
            # process_allgather is a WORLD collective; a strict subgroup
            # would deadlock waiting on non-members. Subgroup eager
            # collectives are expressed as mesh-axis collectives on TPU.
            raise NotImplementedError(
                "eager collectives over a strict process subgroup are not "
                "supported on TPU; use mesh-axis collectives "
                "(paddle_tpu.parallel.collectives) inside the compiled step, "
                "or a world-spanning group"
            )
        from jax.experimental import multihost_utils

        gathered = multihost_utils.process_allgather(local_value, tiled=False)
        sub = gathered[np.asarray(self.ranks)]
        return reducer(sub)

    def _check_member(self, group_rank, what):
        if group_rank < 0 or group_rank >= self.nranks:
            raise ValueError(
                f"{what} rank is not a member of process group {self.id} "
                f"(ranks={self.ranks})"
            )

    def all_reduce(self, tensor, op=ReduceOp.SUM, sync_op=True):
        red = {
            ReduceOp.SUM: lambda s: jnp.sum(s, axis=0),
            ReduceOp.AVG: lambda s: jnp.mean(s, axis=0),
            ReduceOp.MAX: lambda s: jnp.max(s, axis=0),
            ReduceOp.MIN: lambda s: jnp.min(s, axis=0),
            ReduceOp.PROD: lambda s: jnp.prod(s, axis=0),
        }[op]
        out = self._cross_process(tensor.value, red)
        tensor.value = out
        return Task([out])

    def all_gather(self, tensor_or_list, tensor=None, sync_op=True):
        if isinstance(tensor_or_list, list):
            out_list, src = tensor_or_list, tensor
            if self.nranks == 1:
                out_list.append(Tensor(src.value))
                return Task([src.value])
            from jax.experimental import multihost_utils

            gathered = multihost_utils.process_allgather(src.value, tiled=False)
            for r in self.ranks:
                out_list.append(Tensor(jnp.asarray(gathered[r])))
            return Task([gathered])
        raise TypeError("all_gather expects (out_list, tensor)")

    def broadcast(self, tensor, src=0, sync_op=True):
        self._check_member(src, "src")
        if self.nranks == 1:
            return Task([tensor.value])
        from jax.experimental import multihost_utils

        gathered = multihost_utils.process_allgather(tensor.value, tiled=False)
        tensor.value = jnp.asarray(gathered[self.ranks[src]])
        return Task([tensor.value])

    def reduce(self, tensor, dst=0, op=ReduceOp.SUM, sync_op=True):
        self._check_member(dst, "dst")
        return self.all_reduce(tensor, op)

    def reduce_scatter(self, tensor, tensor_list, op=ReduceOp.SUM, sync_op=True):
        if self.nranks == 1:
            tensor.value = tensor_list[0].value
            return Task([tensor.value])
        stacked = jnp.stack([t.value for t in tensor_list])
        red = self._cross_process(stacked, lambda s: jnp.sum(s, axis=0))
        tensor.value = red[self.rank]
        return Task([tensor.value])

    def alltoall(self, out_tensor_list, in_tensor_list, sync_op=True):
        if self.nranks == 1:
            for o, i in zip(out_tensor_list, in_tensor_list):
                o._replace_with(Tensor(i.value))
            if not out_tensor_list:
                out_tensor_list.extend(Tensor(i.value) for i in in_tensor_list)
            return Task([t.value for t in in_tensor_list])
        from jax.experimental import multihost_utils

        stacked = jnp.stack([t.value for t in in_tensor_list])
        gathered = multihost_utils.process_allgather(stacked, tiled=False)
        outs = [jnp.asarray(gathered[r][self.rank]) for r in self.ranks]
        del out_tensor_list[:]
        out_tensor_list.extend(Tensor(o) for o in outs)
        return Task(outs)

    def scatter(self, tensor, tensor_list=None, src=0, sync_op=True):
        self._check_member(src, "src")
        if self.nranks == 1:
            if tensor_list:
                tensor.value = tensor_list[0].value
            return Task([tensor.value])
        from jax.experimental import multihost_utils

        if self.rank == src and tensor_list:
            stacked = jnp.stack([t.value for t in tensor_list])
        else:
            stacked = jnp.zeros(
                (self.nranks,) + tuple(tensor.shape), tensor.value.dtype
            )
        gathered = multihost_utils.process_allgather(stacked, tiled=False)
        tensor.value = jnp.asarray(gathered[self.ranks[src]][self.rank])
        return Task([tensor.value])

    def barrier(self, device_id=None):
        if self.nranks == 1:
            return Task([])
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"pg_{self.id}_barrier")
        return Task([])

    def send(self, tensor, dst=0, sync_op=True):
        raise NotImplementedError(
            "eager p2p send/recv is not exposed on TPU; pipeline stages use "
            "compiled ppermute (paddle_tpu.parallel.collectives.ppermute)"
        )

    recv = send


ProcessGroupICI = ProcessGroup
