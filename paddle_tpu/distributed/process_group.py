"""ProcessGroupICI — eager collective API over XLA collectives.

Reference parity: ProcessGroup/ProcessGroupNCCL (paddle/fluid/distributed/
collective/process_group_nccl.cc — unverified, mount empty). North-star
(BASELINE.json): "replace ProcessGroupNCCL with a ProcessGroupICI so
Fleet's collectives ride the pod interconnect."

TPU-first semantics: inside compiled parallel programs collectives are
mesh-axis ops (paddle_tpu.parallel.collectives) — that is the hot path.
This class provides the *eager* paddle.distributed.* contract across three
group flavours:

1. **SPMD axis groups** (single OS process, group tied to a mesh axis —
   what HybridCommunicateGroup creates): if the tensor's array is sharded
   along the group's axis, the collective runs as a cached jitted
   shard_map executable over that axis (a real XLA ICI collective, with
   the per-rank shape semantics of the reference: all_reduce of an
   axis-sharded [n*k,…] array yields the [k,…] reduction replicated along
   the axis). If the array is *replicated* along the axis, every virtual
   rank holds the same value and the collective is computed in closed
   form (sum → n·x, max/min/avg → x, gather → n copies, …).
2. **Multi-process world groups**: eager collectives over
   multihost_utils.process_allgather (XLA over ICI/DCN).
3. **Multi-process strict subgroups**: jitted collectives over a mesh
   spanning only the member processes' devices — every member process
   calls, non-members stay out, so no world-collective deadlock.

Async Task handles are returned for API parity; jax dispatch is already
async, so wait() is a block-until-ready.
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    AVG = "mean"
    PROD = "prod"


_REDUCERS = {
    ReduceOp.SUM: jax.lax.psum,
    ReduceOp.AVG: jax.lax.pmean,
    ReduceOp.MAX: jax.lax.pmax,
    ReduceOp.MIN: jax.lax.pmin,
}

# host-side reducers over a stacked leading axis (one entry per rank)
_JNP_REDUCERS = {
    ReduceOp.SUM: lambda s: jnp.sum(s, axis=0),
    ReduceOp.AVG: lambda s: jnp.mean(s, axis=0),
    ReduceOp.MAX: lambda s: jnp.max(s, axis=0),
    ReduceOp.MIN: lambda s: jnp.min(s, axis=0),
    ReduceOp.PROD: lambda s: jnp.prod(s, axis=0),
}


class Task:
    def __init__(self, values):
        self._values = values

    def wait(self):
        for v in self._values:
            if hasattr(v, "block_until_ready"):
                v.block_until_ready()
        return True

    def is_completed(self):
        return True

    def synchronize(self):
        return self.wait()


def _spec_of(arr):
    """PartitionSpec of a jax array (empty spec if unsharded/unknown)."""
    sh = getattr(arr, "sharding", None)
    if isinstance(sh, NamedSharding):
        return sh.spec
    return P()


def _entry_names(entry):
    if entry is None:
        return ()
    return entry if isinstance(entry, tuple) else (entry,)


def _spec_key(arr, ndim):
    """Hashable full-spec tuple padded to the array's rank."""
    spec = tuple(_spec_of(arr))
    spec = spec + (None,) * (ndim - len(spec))
    return tuple(
        tuple(_entry_names(e)) if _entry_names(e) else None for e in spec
    )


def _axis_dim(arr, axis_name):
    """Which array dim is sharded over ``axis_name`` (None if replicated)."""
    for d, entry in enumerate(_spec_of(arr)):
        if axis_name in _entry_names(entry):
            return d
    return None


def _drop_axis(spec_key, axis):
    """The spec with ``axis`` removed (what the output keeps sharded)."""
    out = []
    for e in spec_key:
        names = tuple(n for n in (e or ()) if n != axis)
        out.append(names if names else None)
    return tuple(out)


@functools.lru_cache(maxsize=512)
def _axis_exec(mesh_epoch_key, axis, kind, spec_key, op, nranks):
    """Cached jitted shard_map executable for one (axis, collective, full
    input spec) family. The input keeps its complete sharding — other mesh
    axes stay sharded in the output; only ``axis`` is reduced/gathered."""
    from ..parallel.mesh import get_mesh

    mesh = get_mesh()
    in_s = P(*spec_key)
    keep = P(*_drop_axis(spec_key, axis))

    if kind == "all_reduce":
        # per-rank shard -> reduced value replicated along axis
        fn = lambda x: _REDUCERS[op](x, axis)
        out_s = keep
    elif kind == "all_gather":
        # per-rank shard -> [nranks, shard...] stack, replicated over axis
        fn = lambda x: jax.lax.all_gather(x, axis, axis=0, tiled=False)
        out_s = P(*((None,) + tuple(_drop_axis(spec_key, axis))))
    elif kind == "broadcast":
        def fn(x, src_idx):
            idx = jax.lax.axis_index(axis)
            masked = jnp.where(idx == src_idx, x, jnp.zeros_like(x))
            return jax.lax.psum(masked, axis)

        in_s = (in_s, P())
        out_s = keep
    else:  # pragma: no cover
        raise ValueError(kind)

    shmapped = jax.shard_map(
        fn, mesh=mesh, in_specs=in_s, out_specs=out_s, check_vma=False
    )
    return jax.jit(shmapped)


class ProcessGroup:
    """A set of ranks. rank==-1 means this process is not a member.

    ``mesh_axis``: for SPMD axis groups (single-process hybrid topology),
    the global-mesh axis this group reduces over; ranks are then virtual
    chip coordinates, not process indices.
    """

    def __init__(self, ranks, pg_id=0, backend="ici", mesh_axis=None):
        from . import env as dist_env

        self.ranks = list(ranks)
        self.nranks = len(self.ranks)
        self.id = pg_id
        self.backend = backend
        self.mesh_axis = mesh_axis
        me = dist_env.get_rank()
        self.rank = self.ranks.index(me) if me in self.ranks else -1
        if (
            mesh_axis is not None
            and self.rank < 0
            and dist_env.get_world_size() == 1
        ):
            # virtual chip-rank groups in single-process SPMD: this process
            # drives rank 0 of every axis group it constructs. (In a
            # multi-process world a non-member must stay rank -1 so the
            # only-members-call guard still fires.)
            self.rank = 0
        # pending eager p2p messages (single-process PP parity path)
        self._p2p_box = {}

    @property
    def world_size(self):
        return self.nranks

    @property
    def process_ids(self):
        return self.ranks

    def get_group_rank(self, global_rank):
        return self.ranks.index(global_rank) if global_rank in self.ranks else -1

    def set_virtual_rank(self, rank):
        """Pick which virtual member this process acts as for eager p2p
        in single-process SPMD groups (where every virtual rank is driven
        by one process pinned to rank 0). Needed only to disambiguate
        recv() when one src has pending sends to several dsts."""
        from . import env as dist_env

        if dist_env.get_world_size() != 1:
            raise RuntimeError(
                "set_virtual_rank applies only to single-process SPMD "
                "groups; in a multi-process world the rank is the "
                "process identity and must not be reassigned"
            )
        if rank < 0 or rank >= self.nranks:
            raise ValueError(f"virtual rank {rank} out of range 0..{self.nranks - 1}")
        self.rank = rank

    # ----------------------------------------------------------- mode query
    def _is_spmd_axis_group(self):
        from . import env as dist_env

        return self.mesh_axis is not None and dist_env.get_world_size() == 1

    def _axis_run(self, kind, arr, op="sum", extra=None):
        """Run a collective over the group's mesh axis on a global array."""
        from ..parallel.mesh import mesh_epoch

        axis = self.mesh_axis
        spec_key = _spec_key(arr, arr.ndim)
        if kind == "all_reduce" and op not in _REDUCERS:
            # no lax prod collective: gather then reduce locally
            stacked = _axis_exec(
                mesh_epoch(), axis, "all_gather", spec_key, "sum",
                self.nranks,
            )(arr)
            return jnp.prod(stacked, axis=0)
        f = _axis_exec(mesh_epoch(), axis, kind, spec_key, op, self.nranks)
        if extra is not None:
            return f(arr, extra)
        return f(arr)

    def _member_mesh(self, ranks=None):
        """A 1-axis mesh with ONE device per member process.

        The eager cross-process path intentionally uses a single
        representative device per process so the gathered array has
        exactly one entry per group rank (multi-chip hosts would
        otherwise yield per-device duplicates); results are host values,
        so the remaining chips are not involved.
        """
        members = self.ranks if ranks is None else ranks
        by_proc = {}
        for d in jax.devices():
            by_proc.setdefault(d.process_index, d)
        devs = [by_proc[r] for r in members]
        return Mesh(np.array(devs), axis_names=("pg",))

    def _subgroup_gather(self, local_value, ranks=None):
        """Gather per-member-process values over the member mesh. Every
        member process calls this; non-members never enter. Returns an
        np.ndarray with one entry per member (group-rank order)."""
        mesh = self._member_mesh(ranks)
        n = len(mesh.devices)
        x = jax.make_array_from_process_local_data(
            NamedSharding(mesh, P("pg")),
            np.asarray(local_value)[None],
            (n,) + tuple(np.shape(local_value)),
        )
        shmapped = jax.shard_map(
            lambda v: jax.lax.all_gather(
                jnp.squeeze(v, 0), "pg", axis=0, tiled=False
            )[None],
            mesh=mesh, in_specs=P("pg"), out_specs=P("pg"),
            check_vma=False,
        )
        out = jax.jit(shmapped)(x)
        return np.asarray(out.addressable_shards[0].data[0])

    def _subgroup_reduce(self, local_value, op):
        """Strict-subgroup reduce = member-mesh gather + local reduce
        (uniform support for every ReduceOp, including PROD)."""
        gathered = self._subgroup_gather(local_value)
        return jnp.asarray(_JNP_REDUCERS[op](jnp.asarray(gathered)))

    def _cross_process(self, local_value, reducer, op=ReduceOp.SUM):
        """Reduce per-process values; returns this rank's result."""
        if self.nranks == 1:
            return local_value
        if self.rank < 0:
            raise RuntimeError(
                "collective called on a process that is not a member of "
                f"group {self.id} (paddle semantics: only members call)"
            )
        from . import env as dist_env

        if self.nranks != dist_env.get_world_size():
            return self._subgroup_reduce(local_value, op)
        from jax.experimental import multihost_utils

        gathered = multihost_utils.process_allgather(local_value, tiled=False)
        sub = gathered[np.asarray(self.ranks)]
        return reducer(sub)

    def _check_member(self, group_rank, what):
        if group_rank < 0 or group_rank >= self.nranks:
            raise ValueError(
                f"{what} rank is not a member of process group {self.id} "
                f"(ranks={self.ranks})"
            )

    # -------------------------------------------------------- collectives
    def all_reduce(self, tensor, op=ReduceOp.SUM, sync_op=True):
        if self.nranks == 1:
            return Task([tensor.value])
        if self._is_spmd_axis_group():
            if _axis_dim(tensor.value, self.mesh_axis) is not None:
                out = self._axis_run("all_reduce", tensor.value, op=op)
            else:
                # replicated along the axis: every virtual rank holds the
                # same value -> closed form
                v = tensor.value
                out = {
                    ReduceOp.SUM: lambda: v * self.nranks,
                    ReduceOp.AVG: lambda: v,
                    ReduceOp.MAX: lambda: v,
                    ReduceOp.MIN: lambda: v,
                    ReduceOp.PROD: lambda: v**self.nranks,
                }[op]()
            tensor.value = out
            return Task([out])
        out = self._cross_process(tensor.value, _JNP_REDUCERS[op], op)
        tensor.value = out
        return Task([out])

    def all_gather(self, tensor_or_list, tensor=None, sync_op=True):
        if not isinstance(tensor_or_list, list):
            raise TypeError("all_gather expects (out_list, tensor)")
        out_list, src = tensor_or_list, tensor
        if self.nranks == 1:
            out_list.append(Tensor(src.value))
            return Task([src.value])
        if self._is_spmd_axis_group():
            dim = _axis_dim(src.value, self.mesh_axis)
            if dim is not None:
                stacked = self._axis_run("all_gather", src.value)
                outs = [jnp.asarray(stacked[i]) for i in range(self.nranks)]
            else:
                outs = [src.value for _ in range(self.nranks)]
            out_list.extend(Tensor(o) for o in outs)
            return Task(outs)
        from . import env as dist_env

        if self.nranks != dist_env.get_world_size():
            gathered = self._subgroup_gather(src.value)
            for i in range(self.nranks):
                out_list.append(Tensor(jnp.asarray(gathered[i])))
            return Task([gathered])
        from jax.experimental import multihost_utils

        gathered = multihost_utils.process_allgather(src.value, tiled=False)
        for r in self.ranks:
            out_list.append(Tensor(jnp.asarray(gathered[r])))
        return Task([gathered])

    def broadcast(self, tensor, src=0, sync_op=True):
        self._check_member(src, "src")
        if self.nranks == 1:
            return Task([tensor.value])
        if self._is_spmd_axis_group():
            dim = _axis_dim(tensor.value, self.mesh_axis)
            if dim is not None:
                out = self._axis_run(
                    "broadcast", tensor.value,
                    extra=jnp.asarray(src, jnp.int32),
                )
                tensor.value = out
            # replicated: already equals src's value on every virtual rank
            return Task([tensor.value])
        from . import env as dist_env

        if self.nranks != dist_env.get_world_size():
            gathered = self._subgroup_gather(tensor.value)
            tensor.value = jnp.asarray(gathered[src])
            return Task([tensor.value])
        from jax.experimental import multihost_utils

        gathered = multihost_utils.process_allgather(tensor.value, tiled=False)
        tensor.value = jnp.asarray(gathered[self.ranks[src]])
        return Task([tensor.value])

    def reduce(self, tensor, dst=0, op=ReduceOp.SUM, sync_op=True):
        self._check_member(dst, "dst")
        return self.all_reduce(tensor, op)

    def reduce_scatter(self, tensor, tensor_list, op=ReduceOp.SUM, sync_op=True):
        if self.nranks == 1:
            tensor.value = tensor_list[0].value
            return Task([tensor.value])
        if self._is_spmd_axis_group():
            self._reject_axis_sharded(tensor_list, "reduce_scatter")
            # every virtual rank holds the same stacked inputs (replicated
            # view); rank r's output = reduce over nranks identical copies
            # of slice r
            stacked = jnp.stack([t.value for t in tensor_list])
            red = {
                ReduceOp.SUM: lambda: stacked * self.nranks,
                ReduceOp.AVG: lambda: stacked,
                ReduceOp.MAX: lambda: stacked,
                ReduceOp.MIN: lambda: stacked,
                ReduceOp.PROD: lambda: stacked**self.nranks,
            }[op]()
            tensor.value = red[self.rank]
            return Task([tensor.value])
        stacked = jnp.stack([t.value for t in tensor_list])
        red = self._cross_process(stacked, _JNP_REDUCERS[op], op)
        tensor.value = red[self.rank]
        return Task([tensor.value])

    def _emit_outputs(self, out_tensor_list, outs):
        """Fill out_tensor_list with ``outs`` (jax arrays), updating any
        caller-held pre-allocated Tensors in place (paddle aliasing)."""
        if len(out_tensor_list) == len(outs):
            for t, o in zip(out_tensor_list, outs):
                t.value = o
        else:
            del out_tensor_list[:]
            out_tensor_list.extend(Tensor(o) for o in outs)

    def alltoall(self, out_tensor_list, in_tensor_list, sync_op=True):
        if self.nranks == 1:
            self._emit_outputs(
                out_tensor_list, [i.value for i in in_tensor_list]
            )
            return Task([t.value for t in in_tensor_list])
        if self._is_spmd_axis_group():
            self._reject_axis_sharded(in_tensor_list, "alltoall")
            # replicated single-process view: out[j] = rank j's
            # in[self.rank]; replicas share the list, so every output is
            # in_tensor_list[self.rank]
            self._emit_outputs(
                out_tensor_list,
                [in_tensor_list[self.rank].value] * self.nranks,
            )
            return Task([t.value for t in out_tensor_list])
        from . import env as dist_env

        stacked = jnp.stack([t.value for t in in_tensor_list])
        if self.nranks != dist_env.get_world_size():
            gathered = self._subgroup_gather(stacked)
            outs = [jnp.asarray(gathered[i][self.rank]) for i in range(self.nranks)]
        else:
            from jax.experimental import multihost_utils

            gathered = multihost_utils.process_allgather(stacked, tiled=False)
            outs = [jnp.asarray(gathered[r][self.rank]) for r in self.ranks]
        self._emit_outputs(out_tensor_list, outs)
        return Task(outs)

    def scatter(self, tensor, tensor_list=None, src=0, sync_op=True):
        self._check_member(src, "src")
        if self.nranks == 1:
            if tensor_list:
                tensor.value = tensor_list[0].value
            return Task([tensor.value])
        if self._is_spmd_axis_group():
            # replicated view: src's list is our list
            if tensor_list:
                self._reject_axis_sharded(tensor_list, "scatter")
                tensor.value = tensor_list[self.rank].value
            return Task([tensor.value])
        from . import env as dist_env

        if self.rank == src and tensor_list:
            stacked = jnp.stack([t.value for t in tensor_list])
        else:
            stacked = jnp.zeros(
                (self.nranks,) + tuple(tensor.shape), tensor.value.dtype
            )
        if self.nranks != dist_env.get_world_size():
            gathered = self._subgroup_gather(stacked)
            tensor.value = jnp.asarray(gathered[src][self.rank])
            return Task([tensor.value])
        from jax.experimental import multihost_utils

        gathered = multihost_utils.process_allgather(stacked, tiled=False)
        tensor.value = jnp.asarray(gathered[self.ranks[src]][self.rank])
        return Task([tensor.value])

    def _reject_axis_sharded(self, tensors, what):
        """Eager list-based collectives on SPMD axis groups operate on the
        replicated per-rank view; an input sharded along the group axis
        means the caller wants the compiled form — fail loudly instead of
        silently applying replica semantics."""
        for t in tensors:
            if _axis_dim(t.value, self.mesh_axis) is not None:
                raise NotImplementedError(
                    f"eager {what} over axis-sharded inputs is not defined "
                    "for the single-process replicated view; use the "
                    "compiled mesh collectives "
                    "(paddle_tpu.parallel.collectives) inside the step"
                )

    def barrier(self, device_id=None):
        from . import env as dist_env

        if self.nranks == 1 or dist_env.get_world_size() == 1:
            return Task([])
        if self.nranks != dist_env.get_world_size():
            # subgroup barrier: a tiny member-mesh collective (only
            # members call -> no world-collective deadlock)
            self._subgroup_gather(np.zeros((), np.int32))
            return Task([])
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"pg_{self.id}_barrier")
        return Task([])

    # --------------------------------------------------------------- p2p
    def send(self, tensor, dst=0, sync_op=True):
        """Eager p2p. Single-process (SPMD/virtual ranks): mailbox with
        paddle pairing semantics — the PP hot path is compiled ppermute;
        this is the API-parity/debug path. Multi-process: pairwise
        collective over a 2-device mesh spanning ONLY the endpoints (both
        endpoints call; other group members are not involved)."""
        self._check_member(dst, "dst")
        from . import env as dist_env

        if dist_env.get_world_size() == 1:
            self._p2p_box.setdefault((self.rank, dst), []).append(
                jnp.asarray(tensor.value)
            )
            return Task([tensor.value])
        pair = sorted([self.ranks[self.rank], self.ranks[dst]])
        self._subgroup_gather(tensor.value, ranks=pair)
        return Task([tensor.value])

    def recv(self, tensor, src=0, sync_op=True):
        self._check_member(src, "src")
        from . import env as dist_env

        if dist_env.get_world_size() == 1:
            # Pair on the (src, dst) the callers named. With SPMD virtual
            # ranks the receiver's own rank is pinned to 0, so fall back
            # to the unique non-empty (src, *) box when (src, self.rank)
            # is empty; use set_virtual_rank() to disambiguate fan-out.
            box = self._p2p_box.get((src, self.rank))
            if not box:
                candidates = [
                    (k, b) for k, b in self._p2p_box.items()
                    if k[0] == src and b
                ]
                if len(candidates) == 1:
                    box = candidates[0][1]
                elif len(candidates) > 1:
                    raise RuntimeError(
                        f"recv(src={src}) is ambiguous in group {self.id}: "
                        f"pending sends to dsts "
                        f"{sorted(k[1] for k, _ in candidates)}; call "
                        "group.set_virtual_rank(dst) before recv to pick one"
                    )
            if not box:
                raise RuntimeError(
                    f"recv(src={src}) with no matching send in group "
                    f"{self.id}; in single-process SPMD, eager p2p is a "
                    "same-process mailbox (compiled pipelines use ppermute)"
                )
            tensor.value = box.pop(0)
            return Task([tensor.value])
        src_proc = self.ranks[src]
        pair = sorted([self.ranks[self.rank], src_proc])
        gathered = self._subgroup_gather(tensor.value, ranks=pair)
        tensor.value = jnp.asarray(gathered[pair.index(src_proc)])
        return Task([tensor.value])


ProcessGroupICI = ProcessGroup
