"""paddle.distributed.checkpoint parity: sharded save/load + reshard.

Reference: python/paddle/distributed/checkpoint/ (unverified, mount
empty). See save_load.py for the TPU design notes.
"""
from .metadata import Metadata, ShardMeta, TensorMeta  # noqa: F401
from .save_load import load_state_dict, save_state_dict  # noqa: F401
