"""Distributed checkpoint metadata.

Reference parity: python/paddle/distributed/checkpoint/metadata.py
(unverified, mount empty): LocalTensorMetadata/LocalTensorIndex/Metadata
recording each saved tensor's global shape and the placement of every
shard file, so load can reshard onto any parallel layout.

TPU form: one JSON document per checkpoint. Each tensor entry records the
global shape/dtype and a list of shards, each with the half-open index
box it covers in the global tensor and the .npy file holding its data.
"""
from __future__ import annotations

import dataclasses
import json
import os


@dataclasses.dataclass
class ShardMeta:
    file: str  # relative .npy path
    box: list  # [[start, stop], ...] per dim (global coordinates)


@dataclasses.dataclass
class TensorMeta:
    shape: list
    dtype: str
    shards: list  # [ShardMeta]


@dataclasses.dataclass
class Metadata:
    tensors: dict  # name -> TensorMeta
    scalars: dict  # name -> python scalar (ints/floats/str/bool/None)
    version: int = 1

    def to_json(self):
        return json.dumps(
            {
                "version": self.version,
                "tensors": {
                    k: {
                        "shape": t.shape,
                        "dtype": t.dtype,
                        "shards": [
                            {"file": s.file, "box": s.box} for s in t.shards
                        ],
                    }
                    for k, t in self.tensors.items()
                },
                "scalars": self.scalars,
            },
            indent=1,
        )

    @classmethod
    def from_json(cls, text):
        d = json.loads(text)
        return cls(
            tensors={
                k: TensorMeta(
                    shape=t["shape"],
                    dtype=t["dtype"],
                    shards=[
                        ShardMeta(file=s["file"], box=s["box"])
                        for s in t["shards"]
                    ],
                )
                for k, t in d["tensors"].items()
            },
            scalars=d.get("scalars", {}),
            version=d.get("version", 1),
        )


METADATA_FILE = "metadata.json"


def metadata_path(dirname):
    return os.path.join(dirname, METADATA_FILE)
