"""Sharded checkpoint save/load with reshard-on-load.

Reference parity: python/paddle/distributed/checkpoint/
{save_state_dict,load_state_dict}.py (unverified, mount empty): each rank
writes only the shards it owns plus a metadata file describing global
shapes and placements; load reads whichever saved shards overlap the
shards the CURRENT layout needs, so a checkpoint written on one mesh
(e.g. dp2 x mp4) restores onto another (dp4 x mp2, a single chip, ...).

TPU design: jax.Arrays already know their sharding, so save walks
``addressable_shards`` (writing each shard once — ``replica_id == 0``
filters replicated copies; in multi-process SPMD each process writes just
its local shards and rank 0 writes metadata after a barrier) and load
builds arrays with ``jax.make_array_from_callback`` against the TARGET
sharding — each device's callback assembles its slice from the
overlapping saved .npy boxes (mmap'd, so only the needed bytes are read).
Optimizer/scheduler scalars ride in the metadata JSON.

State dicts may nest (optimizer state dicts hold dicts/lists); nested
structure is flattened with '/'-joined keys and restored in place.
"""
from __future__ import annotations

import os
import re

import numpy as np

import jax

from ...core.tensor import Tensor
from .fsio import atomic_save_npy, atomic_write_text, fsync_dir
from .metadata import METADATA_FILE, Metadata, ShardMeta, TensorMeta, metadata_path


def _walk(obj, prefix=""):
    if isinstance(obj, dict):
        for k, v in obj.items():
            yield from _walk(v, f"{prefix}{k}/")
    elif isinstance(obj, (list, tuple)):
        for i, v in enumerate(obj):
            yield from _walk(v, f"{prefix}{i}/")
    else:
        yield prefix[:-1], obj


def _sanitize(name):
    return re.sub(r"[^A-Za-z0-9_.-]", "_", name)


def _is_array_leaf(v):
    return isinstance(v, Tensor) or isinstance(v, jax.Array) or (
        isinstance(v, np.ndarray) and v.ndim > 0
    )


def _value(v):
    return v.value if isinstance(v, Tensor) else v


def save_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0):
    """Write a sharded checkpoint of ``state_dict`` (possibly nested) to
    directory ``path``. Every process writes its own shards; the
    coordinator writes metadata.

    Every file is written atomically (temp name, fsync, rename — see
    fsio.py): a crash mid-save can leave the directory incomplete but
    never a HALF-written .npy or metadata file, which is the primitive
    the ``paddle_tpu.checkpoint`` commit protocol builds on. Returns
    ``{filename: {"crc32": int, "bytes": int}}`` for the files THIS
    process wrote, so callers can assemble a commit manifest without
    re-reading them."""
    os.makedirs(path, exist_ok=True)
    proc = jax.process_index()
    files = {}
    tensors, scalars = {}, {}
    for name, leaf in _walk(state_dict):
        if not _is_array_leaf(leaf):
            if leaf is None or isinstance(leaf, (int, float, str, bool)):
                scalars[name] = leaf
            else:
                scalars[name] = float(np.asarray(leaf))
            continue
        arr = _value(leaf)
        if isinstance(arr, np.ndarray):
            arr = jax.numpy.asarray(arr)
        shards = []
        for i, sh in enumerate(arr.addressable_shards):
            if sh.replica_id != 0:
                continue  # replicated copy: some other shard writes it
            box = [
                [s.start or 0, s.stop if s.stop is not None else dim]
                for s, dim in zip(sh.index, arr.shape)
            ]
            fname = f"{_sanitize(name)}.p{proc}.s{i}.npy"
            crc, nbytes = atomic_save_npy(
                os.path.join(path, fname), np.asarray(sh.data)
            )
            files[fname] = {"crc32": crc, "bytes": nbytes}
            shards.append(ShardMeta(file=fname, box=box))
        tensors[name] = TensorMeta(
            shape=list(arr.shape), dtype=str(arr.dtype), shards=shards
        )

    if jax.process_count() > 1:
        # all shards must hit storage before metadata declares them; the
        # multi-process metadata merge happens via the shared filesystem:
        # every process wrote disjoint replica-0 shards, rank 0's view of
        # tensor shapes/dtypes is authoritative
        from ...distributed import communication as comm

        comm.barrier()
    if proc == coordinator_rank or jax.process_count() == 1:
        meta = Metadata(tensors=tensors, scalars=scalars)
        # atomic publish: metadata existence is the checkpoint's
        # completeness marker (latest_checkpoint relies on it), written
        # LAST so it never declares shards that are not on disk yet
        crc, nbytes = atomic_write_text(metadata_path(path), meta.to_json())
        files[METADATA_FILE] = {"crc32": crc, "bytes": nbytes}
        fsync_dir(path)
    return files


class _ShardReader:
    """mmap'd lazy reader assembling arbitrary boxes from saved shards."""

    def __init__(self, path, tmeta):
        self.path = path
        self.meta = tmeta
        self._files = {}

    def _data(self, fname):
        if fname not in self._files:
            self._files[fname] = np.load(
                os.path.join(self.path, fname), mmap_mode="r"
            )
        return self._files[fname]

    def read(self, index):
        """index: tuple of slices (global coords) -> assembled ndarray."""
        shape = self.meta.shape
        want = [
            [s.start or 0, s.stop if s.stop is not None else dim]
            for s, dim in zip(index, shape)
        ]
        out_shape = [b - a for a, b in want]
        out = np.empty(out_shape, dtype=np.dtype(self.meta.dtype))
        filled = 0
        for sh in self.meta.shards:
            inter = [
                [max(wa, ba), min(wb, bb)]
                for (wa, wb), (ba, bb) in zip(want, sh.box)
            ]
            if any(a >= b for a, b in inter):
                continue
            src = self._data(sh.file)[tuple(
                slice(a - ba, b - ba)
                for (a, b), (ba, _bb) in zip(inter, sh.box)
            )]
            out[tuple(
                slice(a - wa, b - wa)
                for (a, b), (wa, _wb) in zip(inter, want)
            )] = src
            filled += int(np.prod([b - a for a, b in inter]))
        if filled != int(np.prod(out_shape)):
            raise ValueError(
                f"checkpoint shards do not cover requested box {want} "
                f"(covered {filled} of {int(np.prod(out_shape))} elements)"
            )
        return out


def load_state_dict(state_dict, path, process_group=None):
    """Fill ``state_dict`` (possibly nested) IN PLACE from the checkpoint
    at ``path``, resharding every tensor onto its CURRENT placement (the
    sharding its array carries right now — typically installed by the
    fleet/TP/MoE layers of the model being restored)."""
    with open(metadata_path(path)) as f:
        meta = Metadata.from_json(f.read())

    missing = []
    for name, leaf in _walk(state_dict):
        if not _is_array_leaf(leaf):
            continue
        tmeta = meta.tensors.get(name)
        if tmeta is None:
            missing.append(name)
            continue
        arr = _value(leaf)
        if isinstance(arr, np.ndarray):
            arr = jax.numpy.asarray(arr)
        if list(arr.shape) != list(tmeta.shape):
            raise ValueError(
                f"{name}: checkpoint shape {tmeta.shape} != "
                f"target shape {list(arr.shape)}"
            )
        reader = _ShardReader(path, tmeta)
        target_dtype = arr.dtype
        new = jax.make_array_from_callback(
            tuple(tmeta.shape), arr.sharding,
            lambda idx, r=reader, d=target_dtype: r.read(idx).astype(d),
        )
        if isinstance(leaf, Tensor):
            leaf.value = new
        else:
            _assign_nested(state_dict, name, new)
    if missing:
        raise KeyError(
            f"checkpoint at {path} is missing tensors: {missing[:5]}"
            + ("..." if len(missing) > 5 else "")
        )
    # restore scalars in place
    for name, value in meta.scalars.items():
        try:
            _assign_nested(state_dict, name, value)
        except (KeyError, IndexError, TypeError):
            pass  # scalar slot absent from the target dict: skip


def _assign_nested(obj, slash_key, value):
    parts = slash_key.split("/")
    for p in parts[:-1]:
        obj = obj[int(p)] if isinstance(obj, (list, tuple)) else obj[p]
    last = parts[-1]
    if isinstance(obj, (list, tuple)):
        obj[int(last)] = value
    else:
        obj[last] = value
