"""Durable file primitives for checkpointing: write-temp, fsync, rename.

Reference parity: python/paddle/distributed/checkpoint/ (unverified,
mount empty) writes files in place; the fault-tolerant runtime in
``paddle_tpu.checkpoint`` layers an atomic commit protocol on top and
that protocol only holds if every INDIVIDUAL file write is already
atomic — a file either has its complete contents or does not exist.
These helpers are that primitive: write to a ``.inflight`` temp name in
the same directory, flush + fsync the file, ``os.replace`` onto the
final name, and (for commit points) fsync the parent directory so the
rename itself is durable.

Every writer also returns a CRC32 + byte count computed WHILE the bytes
stream through, so callers get checksums for the commit manifest
without re-reading what they just wrote.
"""
from __future__ import annotations

import os
import zlib

import numpy as np

INFLIGHT_SUFFIX = ".inflight"


def fsync_dir(dirname):
    """fsync a directory so a just-performed rename/create in it is
    durable (no-op on platforms that cannot open directories)."""
    try:
        fd = os.open(dirname, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class _CRC32Writer:
    """File-object wrapper accumulating CRC32/size of everything written
    (np.save and json dumps stream through it unchanged)."""

    def __init__(self, f):
        self._f = f
        self.crc32 = 0
        self.nbytes = 0

    def write(self, data):
        if isinstance(data, str):
            data = data.encode("utf-8")
        self._f.write(data)
        self.crc32 = zlib.crc32(data, self.crc32)
        self.nbytes += len(data)
        return len(data)


def _atomic_write(path, emit):
    """Run ``emit(crc_writer)`` against ``path + INFLIGHT_SUFFIX``, fsync,
    rename into place. Returns (crc32, nbytes)."""
    tmp = path + INFLIGHT_SUFFIX
    with open(tmp, "wb") as f:
        w = _CRC32Writer(f)
        emit(w)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return w.crc32, w.nbytes


def atomic_save_npy(path, array):
    """np.save ``array`` to ``path`` atomically; returns (crc32, nbytes)
    of the serialized .npy stream."""
    arr = np.asarray(array)
    return _atomic_write(path, lambda w: np.save(w, arr))


def atomic_write_text(path, text):
    """Write ``text`` to ``path`` atomically; returns (crc32, nbytes)."""
    return _atomic_write(path, lambda w: w.write(text))


def crc32_file(path, chunk_size=1 << 20):
    """CRC32 + size of an existing file (the verify side of the
    manifest's checksums)."""
    crc, n = 0, 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(chunk_size)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
            n += len(chunk)
    return crc, n
