"""Auto-parallel Engine: annotated eager model -> compiled distributed step.

Reference parity: python/paddle/distributed/auto_parallel/static/
{engine,planner_v2,partitioner,reshard}.py and the dist.to_static /
DistModel API (unverified, mount empty). The reference builds a planned
static program: a planner assigns per-op process meshes, a partitioner
splits the graph per rank, and a resharder inserts communication.

TPU redesign: all three roles collapse into XLA's GSPMD pass. The user's
``shard_tensor``/``shard_layer`` annotations put NamedShardings on the
parameter arrays; ``shard_dataloader`` puts them on the inputs; the
whole train step is jitted once (reusing CompiledTrainStep), and GSPMD
propagates placements through every op, inserting collectives (the
"reshard on the fly") wherever annotations conflict — e.g. a
dp-sharded activation meeting an mp-sharded weight becomes an
all-gather/matmul/reduce-scatter sequence chosen by the compiler. The
planner's cost model is XLA's own.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor
from . import _as_jax_mesh

__all__ = ["DistModel", "Engine", "ShardDataloader", "shard_dataloader",
           "to_static"]


def _as_tensor_list(x):
    if isinstance(x, (list, tuple)):
        return [v if isinstance(v, Tensor) else Tensor(jnp.asarray(
            v.numpy() if hasattr(v, "numpy") else v
        )) for v in x]
    return _as_tensor_list([x])


class ShardDataloader:
    """Wrap an iterable of (inputs, labels) batches, placing every array
    on ``mesh`` with its batch dim sharded over ``shard_dims`` (reference:
    dist.shard_dataloader). ``shard_dims=None`` replicates (pure mp)."""

    def __init__(self, dataloader, meshes, shard_dims=None, input_keys=None):
        self._loader = dataloader
        mesh = meshes[0] if isinstance(meshes, (list, tuple)) else meshes
        self._mesh = _as_jax_mesh(mesh)
        self._shard_dims = shard_dims
        # reference-signature parity only: dict batches are placed
        # wholesale here; Engine(input_keys=...) routes them to net/loss
        self._input_keys = input_keys

    def _place(self, v):
        arr = jnp.asarray(
            v.value if isinstance(v, Tensor)
            else (v.numpy() if hasattr(v, "numpy") else v)
        )
        if self._shard_dims is None or arr.ndim == 0:
            spec = P(*([None] * arr.ndim))  # scalars: replicate
        else:
            axes = (
                self._shard_dims if isinstance(self._shard_dims, (list, tuple))
                else [self._shard_dims]
            )
            spec = P(tuple(axes) if len(axes) > 1 else axes[0])
        return Tensor(jax.device_put(arr, NamedSharding(self._mesh, spec)))

    def _place_struct(self, batch):
        if isinstance(batch, dict):
            return {k: self._place(v) for k, v in batch.items()}
        if isinstance(batch, (list, tuple)):
            return type(batch)(self._place_struct(v) for v in batch)
        return self._place(batch)

    def __iter__(self):
        for batch in self._loader:
            yield self._place_struct(batch)

    def __len__(self):
        return len(self._loader)


def shard_dataloader(dataloader, meshes, shard_dims=None, is_dataset=False,
                     input_keys=None):
    return ShardDataloader(dataloader, meshes, shard_dims, input_keys)


class DistModel:
    """Callable train/eval step over an annotated model (dist.to_static).

    ``dist_model(*inputs, label)`` returns the loss in ``train()`` /
    ``eval()`` mode, or the network outputs in ``predict()`` mode. The
    train path is ONE whole-step jit (forward, backward, reshard
    collectives, optimizer update) via CompiledTrainStep.
    """

    def __init__(self, layer, loader=None, loss=None, optimizer=None,
                 strategy=None, metrics=None):
        self.network = layer
        self._loss = loss
        self._optimizer = optimizer
        self._metrics = metrics or []
        self._strategy = strategy
        self._mode = "train"
        self._train_step = None

    # ------------------------------------------------------------- modes
    def train(self):
        if self._loss is None or self._optimizer is None:
            raise ValueError(
                "DistModel.train() needs both loss and optimizer "
                "(pass them to dist.to_static / Engine)"
            )
        self._mode = "train"
        self.network.train()
        return self

    def eval(self):
        self._mode = "eval"
        self.network.eval()
        return self

    def predict(self):
        self._mode = "predict"
        self.network.eval()
        return self

    @property
    def mode(self):
        return self._mode

    # -------------------------------------------------------------- call
    def _split_args(self, args):
        """(inputs..., label) or ([inputs], [labels]) -> (ins, lbls)."""
        if (
            len(args) == 2
            and isinstance(args[0], (list, tuple))
            and isinstance(args[1], (list, tuple))
        ):
            return _as_tensor_list(args[0]), _as_tensor_list(args[1])
        if len(args) < 2:
            raise ValueError(
                "DistModel expects (*inputs, label) — at least an input "
                f"and a label, got {len(args)} argument(s)"
            )
        return _as_tensor_list(list(args[:-1])), _as_tensor_list(args[-1])

    def __call__(self, *args):
        if self._mode == "predict":
            from ...core import tape

            with tape.no_grad():
                out = self.network(*_as_tensor_list(list(args)))
            return out

        if self._loss is None:
            raise ValueError(
                f"DistModel in '{self._mode}' mode needs a loss function "
                "(pass loss= to dist.to_static / Engine)"
            )
        inputs, labels = self._split_args(args)
        if self._mode == "train":
            if self._optimizer is None:
                raise ValueError(
                    "DistModel.train step needs an optimizer (pass "
                    "optimizer= to dist.to_static / Engine)"
                )
            if self._train_step is None:
                from ...jit.trainer import CompiledTrainStep

                self._train_step = CompiledTrainStep(
                    self.network, self._loss, self._optimizer
                )
            loss, _ = self._train_step(inputs, labels)
            return loss
        # eval: forward + loss, no update
        from ...core import tape

        with tape.no_grad():
            out = self.network(*inputs)
            outs = out if isinstance(out, (list, tuple)) else [out]
            return self._loss(*(list(outs) + labels))

    def state_dict(self, *a, **k):
        return self.network.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self.network.set_state_dict(*a, **k)

    def parameters(self, *a, **k):
        return self.network.parameters(*a, **k)

    def dist_main_program(self, mode=None):  # reference introspection API
        return None


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None):
    """Annotated eager Layer -> DistModel running a compiled distributed
    step (reference: dist.to_static). The loader is accepted for
    signature parity; pass batches to the returned DistModel directly."""
    return DistModel(layer, loader, loss, optimizer, strategy)


class Engine:
    """fit/evaluate/predict driver over DistModel (reference:
    auto_parallel.Engine). ``fit`` iterates a (Shard)DataLoader-style
    iterable of (inputs, labels) batches; annotations on the model's
    parameters decide the distribution, GSPMD the communication."""

    def __init__(self, model, loss=None, optimizer=None, metrics=None,
                 strategy=None, input_keys=None, label_keys=None):
        self._dist = DistModel(model, None, loss, optimizer, strategy,
                               metrics)
        self._input_keys = input_keys
        self._label_keys = label_keys

    @property
    def model(self):
        return self._dist

    def _split_batch(self, batch, for_predict=False):
        """(inputs, labels) pair or a dict routed by input/label_keys.
        In predict mode labels are optional and a bare batch is treated
        as inputs."""
        if isinstance(batch, dict):
            if not self._input_keys:
                raise ValueError(
                    "dict batches need Engine(input_keys=[...]"
                    + ("" if for_predict else ", label_keys=[...]")
                    + ") to say which entries feed the network"
                    + ("" if for_predict else " vs. the loss")
                )
            if not for_predict and not self._label_keys:
                raise ValueError(
                    "dict batches in fit/evaluate need "
                    "Engine(label_keys=[...]) naming the loss targets"
                )
            inputs = [batch[k] for k in self._input_keys]
            labels = [batch[k] for k in (self._label_keys or [])]
            return inputs, labels
        if isinstance(batch, (list, tuple)) and len(batch) == 2:
            inputs, labels = batch
            return (
                inputs if isinstance(inputs, (list, tuple)) else [inputs],
                labels if isinstance(labels, (list, tuple)) else [labels],
            )
        if for_predict:
            # bare inputs (no labels) are fine for prediction
            return (
                list(batch) if isinstance(batch, (list, tuple))
                else [batch]
            ), []
        raise ValueError(
            "Engine expects (inputs, labels) pair batches (wrap "
            "multiple inputs in a list: ([x1, x2], y)), or dict "
            f"batches with input_keys/label_keys; got "
            f"{type(batch).__name__} of length "
            f"{len(batch) if hasattr(batch, '__len__') else '?'}"
        )

    def _run_loop(self, data, steps=None):
        """One pass over ``data`` in the current mode; loss values stay on
        device until the end (no per-step host sync — async dispatch
        keeps the next step enqueued while the TPU runs this one)."""
        losses = []
        for step_i, batch in enumerate(data):
            if steps is not None and step_i >= steps:
                break
            inputs, labels = self._split_batch(batch)
            losses.append(self._dist(inputs, labels))
        return [float(np.asarray(l.numpy())) for l in losses]

    def fit(self, train_data, epochs=1, steps_per_epoch=None, log_freq=0,
            verbose=0):
        self._dist.train()
        history = []
        for _ in range(int(epochs)):
            history.extend(self._run_loop(train_data, steps_per_epoch))
        return history

    def evaluate(self, eval_data, steps=None):
        self._dist.eval()
        losses = self._run_loop(eval_data, steps)
        return {"loss": float(np.mean(losses)) if losses else None}

    def predict(self, test_data, steps=None):
        """``test_data`` yields (inputs, labels) pairs (labels ignored),
        bare inputs, or dicts routed by ``input_keys``."""
        self._dist.predict()
        outs = []
        for step_i, batch in enumerate(test_data):
            if steps is not None and step_i >= steps:
                break
            inputs, _ = self._split_batch(batch, for_predict=True)
            outs.append(self._dist(*inputs))
        return outs
