"""Semi-automatic parallelism: shard_tensor / reshard / placements.

Reference parity: python/paddle/distributed/auto_parallel/ (unverified,
mount empty): ProcessMesh, Shard/Replicate/Partial placements,
dist.shard_tensor, dist.reshard, dist.shard_layer — the API that lets a
user annotate a handful of tensors and have the framework derive the
rest.

TPU redesign: this is the thinnest layer in the whole build, because the
reference's semi-auto machinery (SPMD rules per op, reshard planners,
partitioners) IS XLA's GSPMD pass. A placements list maps directly onto a
jax NamedSharding PartitionSpec; shard_tensor places an array, reshard
stamps a (differentiable) sharding constraint, and every derived
placement/reshard decision is made by the compiler during whole-step jit
— the north-star seam (SURVEY.md §2.3 semi-auto row).
"""
from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor


class Placement:
    def __eq__(self, other):
        return type(self) is type(other) and self.__dict__ == other.__dict__

    def __hash__(self):
        return hash((type(self).__name__, tuple(sorted(self.__dict__.items()))))


class Shard(Placement):
    """This mesh dimension splits tensor dim ``dim``."""

    def __init__(self, dim):
        self.dim = int(dim)

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim

    def is_replicated(self):
        return False

    def is_partial(self):
        return False


class Replicate(Placement):
    def __repr__(self):
        return "Replicate()"

    def is_shard(self, dim=None):
        return False

    def is_replicated(self):
        return True

    def is_partial(self):
        return False


class Partial(Placement):
    """Pending reduction over this mesh dim. Only produced INSIDE
    computations (a row-parallel matmul's unreduced output); GSPMD
    tracks/resolves partials automatically, so materializing one eagerly
    is not meaningful."""

    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type

    def __repr__(self):
        return f"Partial({self.reduce_type})"

    def is_shard(self, dim=None):
        return False

    def is_replicated(self):
        return False

    def is_partial(self):
        return True


class ProcessMesh:
    """An N-D arrangement of devices with named dims.

    ``mesh`` is an array-like of global device ids (as in the reference);
    ids index ``jax.devices()``.
    """

    def __init__(self, mesh, dim_names=None, shape=None, process_ids=None):
        arr = np.asarray(mesh)
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        if len(dim_names) != arr.ndim:
            raise ValueError(
                f"{arr.ndim}-d mesh needs {arr.ndim} dim_names, got "
                f"{list(dim_names)}"
            )
        devs = np.asarray(jax.devices(), dtype=object)
        self._jax_mesh = Mesh(devs[arr], axis_names=tuple(dim_names))
        self._shape = list(arr.shape)
        self._dim_names = list(dim_names)
        self._process_ids = [int(i) for i in arr.reshape(-1)]

    @property
    def shape(self):
        return self._shape

    @property
    def dim_names(self):
        return self._dim_names

    @property
    def process_ids(self):
        return self._process_ids

    @property
    def mesh(self):
        return self._jax_mesh

    def __eq__(self, other):
        return (
            isinstance(other, ProcessMesh)
            and self._shape == other._shape
            and self._dim_names == other._dim_names
            and self._process_ids == other._process_ids
        )

    def __hash__(self):
        return hash((
            tuple(self._shape), tuple(self._dim_names),
            tuple(self._process_ids),
        ))

    def __repr__(self):
        return (
            f"ProcessMesh(shape={self._shape}, "
            f"dim_names={self._dim_names})"
        )


def _as_jax_mesh(mesh):
    if isinstance(mesh, ProcessMesh):
        return mesh.mesh
    if isinstance(mesh, Mesh):
        return mesh
    raise TypeError(f"expected ProcessMesh or jax Mesh, got {type(mesh)}")


def placements_to_spec(placements, ndim, mesh):
    """[per-mesh-dim Placement] -> PartitionSpec (per-tensor-dim axes)."""
    jm = _as_jax_mesh(mesh)
    names = jm.axis_names
    if len(placements) != len(names):
        raise ValueError(
            f"need one placement per mesh dim ({len(names)}), got "
            f"{len(placements)}"
        )
    per_dim = [[] for _ in range(ndim)]
    for axis_name, pl in zip(names, placements):
        if isinstance(pl, Shard):
            if not -ndim <= pl.dim < ndim:
                raise ValueError(
                    f"Shard(dim={pl.dim}) out of range for a {ndim}-d "
                    "tensor"
                )
            per_dim[pl.dim % ndim].append(axis_name)
        elif isinstance(pl, Partial):
            raise NotImplementedError(
                "Partial placements arise inside computations and are "
                "resolved by GSPMD; they cannot be materialized by "
                "shard_tensor/reshard"
            )
        elif not isinstance(pl, Replicate):
            raise TypeError(f"unknown placement {pl!r}")
    return P(*(
        tuple(axes) if len(axes) > 1 else (axes[0] if axes else None)
        for axes in per_dim
    ))


def spec_to_placements(sharding, ndim, mesh=None):
    """Inverse of placements_to_spec (for introspection/get_placements)."""
    if not isinstance(sharding, NamedSharding):
        if mesh is None:
            raise ValueError(
                "tensor carries no NamedSharding; pass `mesh` to get its "
                "(fully replicated) placements on that mesh"
            )
        jm = _as_jax_mesh(mesh)
        return [Replicate() for _ in jm.axis_names]
    spec = list(sharding.spec) + [None] * (ndim - len(sharding.spec))
    out = {name: Replicate() for name in sharding.mesh.axis_names}
    for dim, entry in enumerate(spec):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        for a in axes:
            out[a] = Shard(dim)
    return [out[name] for name in sharding.mesh.axis_names]


def shard_tensor(data, mesh, placements, dtype=None, place=None,
                 stop_gradient=None):
    """Place ``data`` on ``mesh`` with ``placements`` and return the
    distributed Tensor (construction-time API; inside compute graphs use
    ``reshard``, which is autograd-transparent). ``place`` is accepted for
    reference-signature parity and ignored (the mesh IS the placement)."""
    t = data if isinstance(data, Tensor) else Tensor(jax.numpy.asarray(data))
    jm = _as_jax_mesh(mesh)
    spec = placements_to_spec(placements, len(t.shape), jm)
    val = t.value
    if dtype is not None:
        from ...core.dtypes import convert_dtype

        val = val.astype(convert_dtype(dtype))
    val = jax.device_put(val, NamedSharding(jm, spec))
    out = Tensor(
        val,
        stop_gradient=(
            t.stop_gradient if stop_gradient is None else stop_gradient
        ),
    )
    return out


def reshard(x, mesh, placements):
    """Re-place a tensor (differentiable: the VJP of a sharding
    constraint is the constraint's transpose, derived by jax)."""
    from ...core import dispatch

    jm = _as_jax_mesh(mesh)
    spec = placements_to_spec(placements, len(x.shape), jm)

    def _re(v):
        return jax.lax.with_sharding_constraint(v, NamedSharding(jm, spec))

    # per-call closure: cache=False so _JIT_CACHE doesn't grow per call
    return dispatch.apply("reshard", _re, (x,), cache=False)


def get_placements(t, mesh=None):
    """Current placements of a Tensor (reference: dist_tensor.placements)."""
    return spec_to_placements(
        getattr(t.value, "sharding", None), len(t.shape), mesh
    )


def shard_layer(layer, process_mesh, shard_fn=None, input_fn=None,
                output_fn=None):
    """Shard a Layer's parameters in place.

    shard_fn(name, layer, process_mesh) decides each sublayer's param
    placements (default: replicate everything on the mesh);
    input_fn(inputs, process_mesh) / output_fn(outputs, process_mesh)
    re-place activations around forward (registered as pre/post hooks,
    matching the reference). Reference: dist.shard_layer.
    """
    jm = _as_jax_mesh(process_mesh)
    if shard_fn is None:
        def shard_fn(name, sublayer, pm):  # noqa: ANN001
            for p in sublayer.parameters(include_sublayers=False):
                p.value = jax.device_put(
                    p.value,
                    NamedSharding(jm, P(*([None] * len(p.shape)))),
                )

    for name, sub in layer.named_sublayers(include_self=True):
        shard_fn(name, sub, process_mesh)
    if input_fn is not None:
        layer.register_forward_pre_hook(
            lambda lyr, inputs: input_fn(inputs, process_mesh)
        )
    if output_fn is not None:
        layer.register_forward_post_hook(
            lambda lyr, inputs, outputs: output_fn(outputs, process_mesh)
        )
    return layer


# imported last (engine.py reads names defined above)
from .engine import (  # noqa: E402,F401
    DistModel,
    Engine,
    ShardDataloader,
    shard_dataloader,
    to_static,
)
