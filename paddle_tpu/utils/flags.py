"""Global runtime flags.

Reference parity: the FLAGS_* registry (paddle/phi/core/flags.cc,
PHI_DEFINE_EXPORTED_* — unverified, mount empty): env-settable, queryable
via get_flags, settable via paddle.set_flags. The TPU-meaningful flags are
implemented (nan/inf checking, deterministic ops, memory fraction maps to
XLA's preallocation env), the rest accepted and stored for compatibility.
"""
from __future__ import annotations

import os
import threading

_LOCK = threading.Lock()

# name -> default (env var FLAGS_<name> overrides at import)
_DEFAULTS = {
    "FLAGS_check_nan_inf": False,
    "FLAGS_check_nan_inf_level": 0,
    "FLAGS_cudnn_deterministic": False,  # accepted; maps to XLA determinism
    "FLAGS_embedding_deterministic": 0,
    "FLAGS_fraction_of_gpu_memory_to_use": 0.92,
    "FLAGS_allocator_strategy": "auto_growth",
    "FLAGS_use_stream_safe_cuda_allocator": True,
    "FLAGS_benchmark": False,
    "FLAGS_eager_delete_tensor_gb": 0.0,
    "FLAGS_max_inplace_grad_add": 0,
    "FLAGS_log_level": 0,
}

_FLAGS: dict = {}


def _coerce(default, raw):
    if isinstance(default, bool):
        return str(raw).lower() in ("1", "true", "yes")
    if isinstance(default, int):
        return int(raw)
    if isinstance(default, float):
        return float(raw)
    return raw


def _init():
    for name, default in _DEFAULTS.items():
        raw = os.environ.get(name)
        _FLAGS[name] = _coerce(default, raw) if raw is not None else default


_init()


def set_flags(flags: dict):
    """paddle.set_flags parity."""
    with _LOCK:
        for k, v in flags.items():
            if k in _DEFAULTS:
                _FLAGS[k] = _coerce(_DEFAULTS[k], v) if not isinstance(
                    v, type(_DEFAULTS[k])
                ) else v
            else:
                _FLAGS[k] = v


def get_flags(flags):
    """paddle.get_flags parity: str or list -> dict."""
    if isinstance(flags, str):
        flags = [flags]
    return {k: _FLAGS.get(k) for k in flags}


def flag(name, default=None):
    return _FLAGS.get(name, default)
