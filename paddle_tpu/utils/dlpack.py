"""paddle.utils.dlpack parity (python/paddle/utils/dlpack.py —
unverified): zero-copy tensor exchange via the DLPack protocol, backed
by jax's dlpack bridge.

Modern DLPack is capsule-less: ``to_dlpack`` returns a protocol object
(implements ``__dlpack__``/``__dlpack_device__``) that torch/numpy/cupy
``from_dlpack`` consume directly; ``from_dlpack`` accepts any such
provider (e.g. a torch tensor)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor


def to_dlpack(x):
    """Tensor -> DLPack provider object."""
    return x.value if isinstance(x, Tensor) else jnp.asarray(x)


def from_dlpack(dlpack):
    """DLPack provider (anything with __dlpack__, e.g. a torch tensor
    or the result of to_dlpack) -> Tensor."""
    return Tensor(jax.dlpack.from_dlpack(dlpack))
