"""paddle.utils parity surface + framework utilities."""
from . import flags  # noqa: F401
from .flags import get_flags, set_flags  # noqa: F401


def try_import(module_name, err_msg=None):
    import importlib

    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(err_msg or f"{module_name} is required") from e


def run_check():
    """paddle.utils.run_check parity: sanity-check the install + device."""
    import numpy as np

    import paddle_tpu as paddle

    x = paddle.ones([2, 2])
    y = paddle.matmul(x, x)
    assert np.allclose(y.numpy(), 2 * np.ones((2, 2)))
    dev = paddle.get_device()
    print(f"paddle_tpu is installed successfully! device={dev}")
    return True
