"""paddle.utils parity surface + framework utilities."""
from . import dlpack  # noqa: F401
from . import flags  # noqa: F401
from .flags import get_flags, set_flags  # noqa: F401


def try_import(module_name, err_msg=None):
    import importlib

    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(err_msg or f"{module_name} is required") from e


def run_check():
    """paddle.utils.run_check parity: sanity-check the install + device."""
    import numpy as np

    import paddle_tpu as paddle

    x = paddle.ones([2, 2])
    y = paddle.matmul(x, x)
    assert np.allclose(y.numpy(), 2 * np.ones((2, 2)))
    dev = paddle.get_device()
    print(f"paddle_tpu is installed successfully and works fine on {dev}.")
    return True


def deprecated(update_to="", since="", reason="", level=0):
    """Decorator: warn (level<=1) or raise (level==2) on call
    (reference: python/paddle/utils/deprecated.py)."""
    import functools
    import warnings

    def decorator(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kw):
            msg = (
                f"API {fn.__module__}.{fn.__name__} is deprecated"
                + (f" since {since}" if since else "")
                + (f", use {update_to} instead" if update_to else "")
                + (f". Reason: {reason}" if reason else "")
            )
            if level == 2:
                raise RuntimeError(msg)
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kw)

        return wrapper

    return decorator


class _UniqueNameGenerator:
    def __init__(self, prefix=""):
        self._prefix = prefix
        self._counters = {}

    def __call__(self, key="tmp"):
        n = self._counters.get(key, 0)
        self._counters[key] = n + 1
        return f"{self._prefix}{key}_{n}"


class _UniqueNameModule:
    """paddle.utils.unique_name parity: generate/guard/switch."""

    def __init__(self):
        self._gen = _UniqueNameGenerator()

    def generate(self, key="tmp"):
        return self._gen(key)

    def switch(self, new_generator=None):
        old = self._gen
        if isinstance(new_generator, str):  # reference: str prefix
            new_generator = _UniqueNameGenerator(new_generator)
        self._gen = new_generator or _UniqueNameGenerator()
        return old

    def guard(self, new_generator=None):
        import contextlib

        @contextlib.contextmanager
        def _guard():
            old = self.switch(new_generator)
            try:
                yield
            finally:
                self._gen = old

        return _guard()


unique_name = _UniqueNameModule()
