"""paddle.summary / paddle.flops (reference: python/paddle/hapi/
{model_summary,dynamic_flops}.py — unverified).

One real forward pass on zeros with forward-post hooks records per-layer
output shapes; FLOPs use the standard per-layer formulas for the common
layer types (matmul-dominated counts — the quantities the MXU executes).
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer


def _make_input(size, dtype):
    if isinstance(size, (list, tuple)) and size and isinstance(
        size[0], (list, tuple)
    ):
        # per-input dtype list (reference API shape) or one shared dtype
        if isinstance(dtype, (list, tuple)):
            if len(dtype) != len(size):
                raise ValueError(
                    f"summary: {len(size)} input sizes but {len(dtype)} "
                    "dtypes"
                )
            return [_make_input(s, d) for s, d in zip(size, dtype)]
        return [_make_input(s, dtype) for s in size]
    if isinstance(dtype, (list, tuple)):
        dtype = dtype[0]
    shape = [int(1 if s is None else s) for s in size]
    return Tensor(jnp.zeros(shape, dtype or jnp.float32))


def _shapes(out):
    if isinstance(out, Tensor):
        return list(out.shape)
    if isinstance(out, (list, tuple)) and out:
        return _shapes(out[0])
    return []


def _num_params(layer):
    return sum(
        int(np.prod(p.shape)) for p in layer.parameters(include_sublayers=False)
    ) if hasattr(layer, "parameters") else 0


def _layer_flops(layer, inputs, output):
    """Per-call FLOPs for the standard layer types (multiply-adds x2)."""
    name = type(layer).__name__
    out_shape = _shapes(output)
    out_elems = int(np.prod(out_shape)) if out_shape else 0
    if name == "Linear":
        in_f = int(layer.weight.shape[0])
        return 2 * out_elems * in_f
    if name.startswith("Conv") and hasattr(layer, "weight"):
        w = layer.weight.shape  # [out_c, in_c/groups, *k]
        per_out = 2 * int(np.prod(w[1:]))
        return out_elems * per_out
    if name in ("BatchNorm1D", "BatchNorm2D", "BatchNorm3D", "LayerNorm",
                "GroupNorm", "InstanceNorm2D"):
        return 2 * out_elems
    if name in ("ReLU", "ReLU6", "GELU", "Sigmoid", "Tanh", "Hardswish",
                "Hardsigmoid", "Softmax", "Swish", "SiLU"):
        return out_elems
    if name.endswith("Pool1D") or name.endswith("Pool2D") or name.endswith(
        "Pool3D"
    ):
        return out_elems
    return 0


def _walk(net, x, want_flops):
    rows = []
    hooks = []

    def make_hook(lname):
        def hook(layer, inputs, output):
            rows.append({
                "name": lname,
                "type": type(layer).__name__,
                "output_shape": _shapes(output),
                "params": _num_params(layer),
                "flops": (
                    _layer_flops(layer, inputs, output) if want_flops else 0
                ),
                "inputs": inputs,
                "output": output,
            })

        return hook

    for lname, sub in net.named_sublayers():
        if isinstance(sub, Layer) and not list(sub.sublayers()):
            hooks.append(sub.register_forward_post_hook(make_hook(lname)))
    was_training = getattr(net, "training", False)
    net.eval()
    try:
        if isinstance(x, list):
            net(*x)
        else:
            net(x)
    finally:
        for h in hooks:
            h.remove()
        if was_training:
            net.train()
    return rows


def summary(net, input_size=None, dtypes=None, input=None):
    """Per-layer table of output shapes + param counts; returns the
    {'total_params', 'trainable_params'} dict like the reference."""
    if input is None:
        if input_size is None:
            raise ValueError("summary: provide input_size or input")
        input = _make_input(input_size, dtypes)
    rows = _walk(net, input, want_flops=False)
    width = max([len(r["name"]) + len(r["type"]) for r in rows] + [20]) + 4
    lines = [
        "-" * (width + 40),
        f"{'Layer (type)':<{width}}{'Output Shape':<22}{'Param #':>12}",
        "=" * (width + 40),
    ]
    for r in rows:
        label = f"{r['name']} ({r['type']})"
        lines.append(
            f"{label:<{width}}{str(r['output_shape']):<22}"
            f"{r['params']:>12,}"
        )
    total = int(sum(np.prod(p.shape) for p in net.parameters()))
    trainable = int(sum(
        np.prod(p.shape) for p in net.parameters() if not p.stop_gradient
    ))
    lines += [
        "=" * (width + 40),
        f"Total params: {total:,}",
        f"Trainable params: {trainable:,}",
        f"Non-trainable params: {total - trainable:,}",
        "-" * (width + 40),
    ]
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": trainable}


def flops(net, input_size=None, inputs=None, custom_ops=None,
          print_detail=False):
    """Total forward FLOPs (2x multiply-adds) for one input batch."""
    if inputs is None:
        if input_size is None:
            raise ValueError("flops: provide input_size or inputs")
        inputs = _make_input(input_size, None)
    rows = _walk(net, inputs, want_flops=True)
    if custom_ops:
        by_name = dict(net.named_sublayers())
        for r in rows:
            layer = by_name.get(r["name"])
            fn = custom_ops.get(type(layer)) if layer is not None else None
            if fn is not None:
                # reference count_op signature: fn(layer, inputs, output)
                r["flops"] = int(fn(layer, r["inputs"], r["output"]))
    total = int(sum(r["flops"] for r in rows))
    if print_detail:
        for r in rows:
            print(
                f"{r['name']:<40}{r['type']:<18}"
                f"{str(r['output_shape']):<22}{r['flops']:>16,}"
            )
        print(f"Total FLOPs: {total:,}")
    return total
