"""paddle.Model — Keras-style high-level API.

Reference parity: python/paddle/hapi/model.py (unverified, mount empty):
prepare/fit/evaluate/predict/save/load + callbacks + metrics, dygraph
adapter semantics. TPU note: the eager step here is the correctness path;
``prepare(..., jit_compile=True)`` (default True once the step compiler
landed) swaps in a whole-step jitted trainer from paddle_tpu.jit for the
performance path.
"""
from __future__ import annotations

import os
import warnings
from collections.abc import Mapping as _Mapping

import numpy as np

from ..core.tensor import Tensor
from ..io import DataLoader
from ..metric.metrics import Metric
from . import callbacks as cbks_mod


def _to_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _tensorize(x):
    if isinstance(x, Tensor):
        return x
    import jax.numpy as jnp

    arr = np.asarray(x)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return Tensor(jnp.asarray(arr))


class _LazyLogs(_Mapping):
    """Per-step logs whose values materialize on first READ.

    The fit hot loop must not synchronize with the device every step —
    over a remote-tunnel TPU a single ``float(loss)`` is a full round
    trip that serializes the pipeline (measured: the whole of config
    #1's 1.2 s/step host overhead). Callbacks decide when values are
    actually needed (nothing reads under verbose=0; ProgBar's per-step
    handler is written to not touch the logs off its log_freq cadence),
    so the mapping drains the deferred metric updates and fetches the
    device loss only when someone looks.

    A ``Mapping`` rather than a dict subclass on purpose: ``dict(logs)``
    / ``{**logs}`` on a dict SUBCLASS take CPython's fast path that
    copies the raw storage without calling the overridden accessors —
    an unmaterialized snapshot would be silently empty. On a Mapping
    those constructions go through keys()/__getitem__ and materialize.
    """

    def __init__(self, drain):
        self._d = {}
        self._drain = drain

    def _mat(self):
        d, self._drain = self._drain, None
        if d is not None:
            d(self._d)

    def __getitem__(self, k):
        self._mat()
        return self._d[k]

    def __iter__(self):
        self._mat()
        return iter(self._d)

    def __len__(self):
        self._mat()
        return len(self._d)

    def __repr__(self):
        self._mat()
        return repr(self._d)


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self.stop_training = False
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._amp_level = None
        self._jit_step = None
        self._jit_enabled = False
        self._accumulating = False
        self._accumulate_grad_batches = 1
        self._pending_accum = False
        self._pending_metrics = []
        self._inputs_spec = _to_list(inputs) if inputs is not None else None
        self._labels_spec = _to_list(labels) if labels is not None else None

    # ------------------------------------------------------------- prepare
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, jit_compile=None):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = _to_list(metrics)
        for m in self._metrics:
            assert isinstance(m, Metric), f"metrics must be Metric, got {m}"
        if isinstance(amp_configs, str):
            self._amp_level = amp_configs
        elif isinstance(amp_configs, dict):
            self._amp_level = amp_configs.get("level", "O1")
        self._jit_enabled = bool(jit_compile)
        return self

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    # --------------------------------------------------------------- steps
    def _compute_loss(self, outputs, labels):
        outs = _to_list(outputs)
        lbls = _to_list(labels)
        if callable(self._loss):
            return self._loss(*(outs + lbls))
        raise RuntimeError("prepare() must be called with a loss for training")

    def train_batch(self, inputs, labels=None, update=True):
        # jit fast path shared with fit (_fit_step); this public entry
        # materializes eagerly — per-step floats are its contract
        res = self._fit_step(inputs, labels, update)
        if res is not None:
            loss, outputs, lbls = res
            metrics = []
            for m in self._metrics:
                m_in = m.compute(*(_to_list(outputs) + lbls))
                metrics.append(m.update(*_to_list(m_in)))
            out_loss = [float(np.asarray(loss.numpy()))]
            return (out_loss, metrics) if metrics else out_loss

        import time

        _t0 = time.perf_counter()
        self.network.train()
        inputs = [_tensorize(x) for x in _to_list(inputs)]
        labels = [_tensorize(y) for y in _to_list(labels)]

        from ..amp import auto_cast

        with auto_cast(enable=self._amp_level in ("O1", "O2"),
                       level=self._amp_level or "O1"):
            outputs = self.network(*inputs)
            loss = self._compute_loss(outputs, labels)
        if self._accumulating:
            # average (not sum) over the accumulation window
            (loss / float(self._accumulate_grad_batches)).backward()
        else:
            loss.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
            self._pending_accum = False
            self._accum_count = 0
            # training telemetry (eager path; the jit path meters inside
            # CompiledTrainStep). Loss stays a device ref — the meter's
            # lazy gauge fetches it on scrape, not here.
            try:
                from .. import observability as obs

                meter = obs.get_step_meter()
                meter.auto_configure(self.network)
                examples, tokens = obs.batch_geometry(
                    [getattr(x, "value", x) for x in inputs]
                )
                meter.observe_step(
                    time.perf_counter() - _t0, examples=examples,
                    tokens=tokens, loss=loss.value,
                )
            except Exception:
                pass
        elif self._accumulating:
            self._pending_accum = True
            self._accum_count = getattr(self, "_accum_count", 0) + 1
        metrics = []
        for m in self._metrics:
            m_in = m.compute(*(_to_list(outputs) + labels))
            metrics.append(m.update(*_to_list(m_in)))
        out_loss = [float(np.asarray(loss.numpy()))]
        return (out_loss, metrics) if metrics else out_loss

    def _jit_train_batch(self, inputs, labels):
        """Whole-step compiled path; falls back to eager when unsupported."""
        if self._jit_step is None:
            try:
                from ..jit.trainer import CompiledTrainStep

                self._jit_step = CompiledTrainStep(
                    self.network, self._compute_loss_fn(), self._optimizer,
                    amp_level=self._amp_level,
                )
            except NotImplementedError:
                self._jit_enabled = False
                return None, None
            s = getattr(self, "_sentinel", None)
            if s is not None:
                self._jit_step.attach_sentinel(s)
            w = getattr(self, "_watchdog", None)
            if w is not None:
                w.attach(self._jit_step)
        loss, outputs = self._jit_step(inputs, labels)
        return outputs, loss

    def _compute_loss_fn(self):
        loss = self._loss
        if not callable(loss):
            raise NotImplementedError("jit path requires a callable loss")
        return loss

    # ------------------------------------------------- fit fast path
    # Deferred-sync stepping: the compiled step is dispatched, metric
    # inputs stay as device refs, and nothing fetches from the device
    # until a callback reads the logs (or the pending window fills /
    # the epoch ends). Device compute, the next batch's host->device
    # transfer, and the DataLoader's collation all overlap.
    _PENDING_MAX = 64  # drain bound: caps device refs held per window

    def _fit_step(self, inputs, labels, update):
        """Sync-free step for fit's hot loop. Returns (loss_dev,
        outputs, labels) or None when the batch must go through the
        eager train_batch (accumulation, jit off, jit fallback)."""
        if not (self._jit_enabled and update and not self._accumulating):
            return None
        self.network.train()
        inputs = [_tensorize(x) for x in _to_list(inputs)]
        labels = [_tensorize(y) for y in _to_list(labels)]
        outputs, loss = self._jit_train_batch(inputs, labels)
        if outputs is None:
            return None  # jit unsupported: caller reruns eagerly
        return loss, outputs, labels

    def _drain_pending_metrics(self):
        pending, self._pending_metrics = self._pending_metrics, []
        for outputs, labels in pending:
            for m in self._metrics:
                m_in = m.compute(*(_to_list(outputs) + labels))
                m.update(*_to_list(m_in))

    def _lazy_logs(self, loss):
        def drain(d):
            self._drain_pending_metrics()
            d["loss"] = float(np.asarray(loss.numpy()))
            for m in self._metrics:
                n, val = m.name(), m.accumulate()
                if isinstance(n, list):
                    vals = val if isinstance(val, list) else [val]
                    for nn, vv in zip(n, vals):
                        d[nn] = vv
                else:
                    d[n] = val

        return _LazyLogs(drain)

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = [_tensorize(x) for x in _to_list(inputs)]
        labels = [_tensorize(y) for y in _to_list(labels)]
        outputs = self.network(*inputs)
        metrics = []
        losses = []
        if self._loss is not None and labels:
            loss = self._compute_loss(outputs, labels)
            losses = [float(np.asarray(loss.numpy()))]
        for m in self._metrics:
            m_in = m.compute(*(_to_list(outputs) + labels))
            metrics.append(m.update(*_to_list(m_in)))
        return (losses, metrics) if metrics else losses

    def predict_batch(self, inputs):
        self.network.eval()
        inputs = [_tensorize(x) for x in _to_list(inputs)]
        outputs = self.network(*inputs)
        return [o.numpy() for o in _to_list(outputs)]

    # ----------------------------------------------------------------- fit
    def _make_loader(self, data, batch_size, shuffle, num_workers, drop_last):
        if data is None or isinstance(data, DataLoader):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          num_workers=num_workers, drop_last=drop_last)

    def _split_batch(self, batch):
        if isinstance(batch, (list, tuple)):
            if len(batch) >= 2:
                return list(batch[:-1]), [batch[-1]]
            return [batch[0]], []
        return [batch], []

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None, checkpoint=None,
            sentinel=None):
        assert train_data is not None
        if checkpoint is not None or sentinel is not None:
            cb = callbacks if isinstance(callbacks, (list, tuple)) else (
                [callbacks] if callbacks is not None else []
            )
            callbacks = list(cb)
        if checkpoint is not None:
            # fault-tolerant path: a checkpoint.CheckpointManager rides
            # the callback stream (per-step policy, async atomic saves,
            # drained at train end)
            callbacks.append(
                cbks_mod.FaultTolerantCheckpoint(checkpoint)
            )
        if sentinel is not None:
            # resilience path: a training.AnomalySentinel attaches to
            # the compiled step; a rollback inside fit continues with
            # the NEXT batch (a loader cannot rewind — see
            # callbacks.ResilientTraining for the semantics)
            callbacks.append(cbks_mod.ResilientTraining(sentinel))
        loader = self._make_loader(train_data, batch_size, shuffle,
                                   num_workers, drop_last)
        eval_loader = self._make_loader(eval_data, batch_size, False,
                                        num_workers, False)
        try:
            steps = len(loader)
        except TypeError:
            steps = None
        cbks = cbks_mod.config_callbacks(
            callbacks, model=self, batch_size=batch_size, epochs=epochs,
            steps=steps, log_freq=log_freq, verbose=verbose,
            save_freq=save_freq, save_dir=save_dir, metrics=self._metrics_name(),
        )
        self.stop_training = False
        self._accumulating = accumulate_grad_batches > 1
        self._accumulate_grad_batches = max(1, accumulate_grad_batches)
        self._pending_accum = False
        self._accum_count = 0
        cbks.on_train_begin()
        it = 0
        self._pending_metrics = []
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            accum = 0
            for step, batch in enumerate(loader):
                cbks.on_train_batch_begin(step)
                inputs, labels = self._split_batch(batch)
                accum += 1
                update = accum % max(1, accumulate_grad_batches) == 0
                try:
                    res = self._fit_step(inputs, labels, update)
                except Exception as e:
                    from ..training.resilience import RollbackAndReplay

                    if isinstance(e, RollbackAndReplay):
                        # rollback-without-replay: params/optimizer/RNG
                        # are back at the last commit; the loader can't
                        # rewind, so continue with the next batch
                        continue
                    raise
                if res is not None:
                    loss, outputs, lbls = res
                    if self._metrics:
                        self._pending_metrics.append((outputs, lbls))
                        if len(self._pending_metrics) >= self._PENDING_MAX:
                            self._drain_pending_metrics()
                    logs = self._lazy_logs(loss)
                else:
                    out = self.train_batch(inputs, labels, update=update)
                    logs = self._merge_logs(out)
                cbks.on_train_batch_end(step, logs)
                it += 1
                if num_iters is not None and it >= num_iters:
                    self.stop_training = True
                    break
            if self._pending_accum:
                # flush a trailing partial accumulation window so its
                # grads don't leak into the next epoch's first update.
                # Losses were scaled by 1/N but only k<N batches landed;
                # rescale grads by N/k so the flush is a true average.
                k = max(1, getattr(self, "_accum_count", 1))
                n = self._accumulate_grad_batches
                if k < n:
                    rescale = float(n) / float(k)
                    for p in self.network.parameters():
                        if p.grad is not None:
                            p.grad.value = p.grad.value * rescale
                self._optimizer.step()
                self._optimizer.clear_grad()
                self._pending_accum = False
                self._accum_count = 0
            if isinstance(logs, _LazyLogs):
                logs._mat()  # epoch boundary: flush metrics + fetch loss
            else:
                self._drain_pending_metrics()
            cbks.on_epoch_end(epoch, logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                self._run_eval(eval_loader, cbks)
            if self.stop_training:
                break
        cbks.on_train_end(logs)
        # accumulation is a per-fit setting; a later direct train_batch()
        # must not inherit the 1/N loss scaling
        self._accumulating = False
        self._accumulate_grad_batches = 1
        return self

    def _metrics_name(self):
        names = ["loss"]
        for m in self._metrics:
            n = m.name()
            names += n if isinstance(n, list) else [n]
        return names

    def _merge_logs(self, out):
        logs = {}
        if isinstance(out, tuple):
            losses, metrics = out
        else:
            losses, metrics = out, []
        if losses:
            logs["loss"] = losses[0] if len(losses) == 1 else losses
        for m, val in zip(self._metrics, metrics):
            n = m.name()
            if isinstance(n, list):
                vals = val if isinstance(val, list) else [val]
                for nn, vv in zip(n, vals):
                    logs[nn] = vv
            else:
                logs[n] = val
        return logs

    def _run_eval(self, eval_loader, cbks):
        cbks.on_eval_begin()
        for m in self._metrics:
            m.reset()
        logs = {}
        loss_sum, n_total = 0.0, 0
        for step, batch in enumerate(eval_loader):
            cbks.on_eval_batch_begin(step)
            inputs, labels = self._split_batch(batch)
            out = self.eval_batch(inputs, labels)
            logs = self._merge_logs(out)
            n = (
                inputs[0].shape[0]
                if inputs and hasattr(inputs[0], "shape") and inputs[0].shape
                else 1
            )
            if "loss" in logs:
                loss_sum += float(logs["loss"]) * n
                n_total += n
            cbks.on_eval_batch_end(step, logs)
        final = {}
        if n_total:
            # sample-weighted mean over the dataset (not the last batch)
            final["loss"] = loss_sum / n_total
        for m in self._metrics:
            n = m.name()
            acc = m.accumulate()
            if isinstance(n, list):
                accs = acc if isinstance(acc, list) else [acc]
                final.update(dict(zip(n, accs)))
            else:
                final[n] = acc
        cbks.on_eval_end(final)
        return final

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        loader = self._make_loader(eval_data, batch_size, False, num_workers,
                                   False)
        cbks = cbks_mod.config_callbacks(
            callbacks, model=self, batch_size=batch_size, verbose=verbose,
            log_freq=log_freq, metrics=self._metrics_name(), mode="eval",
        )
        return self._run_eval(loader, cbks)

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        loader = self._make_loader(test_data, batch_size, False, num_workers,
                                   False)
        outputs = []
        for batch in loader:
            inputs, _ = (
                self._split_batch(batch)
                if isinstance(batch, (list, tuple)) and len(batch) > 1
                else (_to_list(batch), [])
            )
            outputs.append(self.predict_batch(inputs))
        # transpose [steps][n_out] -> [n_out][steps]
        grouped = list(zip(*outputs))
        if stack_outputs:
            return [np.concatenate(g, axis=0) for g in grouped]
        return [list(g) for g in grouped]

    # ------------------------------------------------------------ save/load
    def save(self, path, training=True):
        from ..framework.io import save as fsave

        if not training:
            from .. import jit

            jit.save(self.network, path, input_spec=self._inputs_spec)
            return
        fsave(self.network.state_dict(), path + ".pdparams")
        if self._optimizer is not None:
            fsave(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load as fload

        state = fload(path + ".pdparams" if not path.endswith(".pdparams") else path)
        missing, unexpected = self.network.set_state_dict(state)
        if (missing or unexpected) and not skip_mismatch:
            if missing:
                warnings.warn(f"missing keys in checkpoint: {missing}")
            if unexpected:
                warnings.warn(f"unexpected keys in checkpoint: {unexpected}")
        opt_path = path + ".pdopt"
        if (
            not reset_optimizer
            and self._optimizer is not None
            and os.path.exists(opt_path)
        ):
            self._optimizer.set_state_dict(fload(opt_path))

    def summary(self, input_size=None, dtype=None):
        lines = [repr(self.network)]
        total = sum(p.size for p in self.network.parameters())
        trainable = sum(
            p.size for p in self.network.parameters() if not p.stop_gradient
        )
        lines.append(f"Total params: {total}")
        lines.append(f"Trainable params: {trainable}")
        s = "\n".join(lines)
        print(s)
        return {"total_params": total, "trainable_params": trainable}
