"""paddle.hapi — high-level training API (python/paddle/hapi/ parity)."""
from . import callbacks  # noqa: F401
from .model import Model  # noqa: F401
