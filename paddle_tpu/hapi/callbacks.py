"""hapi callbacks.

Reference parity: python/paddle/hapi/callbacks.py (unverified, mount empty):
Callback/CallbackList, ProgBarLogger, ModelCheckpoint, EarlyStopping,
LRScheduler, VisualDL (no-op stub here — visualdl is not in the image).
"""
from __future__ import annotations

import numbers
import os
import sys
import time


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass

    def on_predict_batch_begin(self, step, logs=None):
        pass

    def on_predict_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def dispatch(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)

            return dispatch
        raise AttributeError(name)


def _fmt(v):
    if isinstance(v, numbers.Number):
        return f"{v:.4f}"
    if isinstance(v, (list, tuple)):
        return "[" + ", ".join(_fmt(x) for x in v) + "]"
    return str(v)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get("epochs")
        self._t0 = time.time()

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._step = 0
        if self.verbose and self.epochs:
            print(f"Epoch {epoch + 1}/{self.epochs}")

    def on_train_batch_end(self, step, logs=None):
        # NOTE: no `logs or {}` here — truth-testing materializes a
        # lazy logs mapping (device sync); only touch it ON the
        # log_freq cadence so the sync-free fit path stays sync-free
        self._step += 1
        if self.verbose and self._step % self.log_freq == 0:
            items = " - ".join(
                f"{k}: {_fmt(v)}" for k, v in (logs or {}).items()
            )
            total = self.steps if self.steps is not None else "?"
            print(f"step {self._step}/{total} - {items}")
            sys.stdout.flush()

    def on_epoch_end(self, epoch, logs=None):
        logs = logs or {}
        if self.verbose:
            items = " - ".join(f"{k}: {_fmt(v)}" for k, v in logs.items())
            print(f"Epoch {epoch + 1} done - {items}")

    def on_eval_begin(self, logs=None):
        if self.verbose:
            print("Eval begin...")

    def on_eval_end(self, logs=None):
        logs = logs or {}
        if self.verbose:
            items = " - ".join(f"{k}: {_fmt(v)}" for k, v in logs.items())
            print(f"Eval done - {items}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class FaultTolerantCheckpoint(Callback):
    """Drive a ``checkpoint.CheckpointManager`` from the fit loop.

    Unlike :class:`ModelCheckpoint` (epoch-granular ``model.save``),
    this is the fault-tolerance path: per-STEP policy checks, async
    atomic saves, and an end-of-training drain so the last commit
    lands. The manager is bound to the fitted network/optimizer at
    train begin if it was constructed bare. Saves key off the global
    optimizer step so resume semantics match the compiled trainer's.
    """

    def __init__(self, manager):
        super().__init__()
        self.manager = manager
        self._it = 0

    def on_train_begin(self, logs=None):
        self.manager.bind(
            self.model.network, getattr(self.model, "_optimizer", None)
        )

    def _global_step(self):
        opt = getattr(self.model, "_optimizer", None)
        n = getattr(opt, "_step_count", 0) if opt is not None else 0
        return n or self._it

    def on_train_batch_end(self, step, logs=None):
        # no logs read here: the sync-free fit path stays sync-free
        # (the manager snapshots device refs, it never fetches)
        self._it += 1
        self.manager.on_step(self._global_step())

    def on_train_end(self, logs=None):
        self.manager.finalize()


class ResilientTraining(Callback):
    """Attach the resilient-training runtime to a fitted model.

    Wires a ``training.AnomalySentinel`` (and optionally a
    ``training.TrainWatchdog``) into the model's compiled train step
    as soon as it exists — ``Model.fit(sentinel=...)`` is sugar for
    appending this callback. The sentinel's skip/abort rungs work
    as in the raw trainer; ROLLBACK inside ``fit`` is
    rollback-without-replay: a DataLoader cannot rewind, so the fit
    loop restores the last committed checkpoint and continues with the
    NEXT batch (the batches between commit and anomaly are lost, the
    run is not). For bit-identical replay semantics drive the trainer
    with ``training.run_resilient`` instead.

    Works only on the jit fast path (``prepare(jit_compile=True)``):
    the eager path applies its optimizer update before any loss value
    exists to judge, so there is nothing for the ladder to undo there.
    """

    def __init__(self, sentinel, watchdog=None):
        super().__init__()
        self.sentinel = sentinel
        self.watchdog = watchdog

    def on_train_begin(self, logs=None):
        # the compiled step is built lazily on the first fit step; the
        # model attaches these the moment it constructs the trainer
        self.model._sentinel = self.sentinel
        self.model._watchdog = self.watchdog
        jit_step = getattr(self.model, "_jit_step", None)
        if jit_step is not None:
            jit_step.attach_sentinel(self.sentinel)
            if self.watchdog is not None:
                self.watchdog.attach(jit_step)
        if self.watchdog is not None:
            self.watchdog.start()

    def on_train_end(self, logs=None):
        if self.watchdog is not None:
            self.watchdog.stop()


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "min" if "loss" in monitor else "max"
        self.mode = mode
        self.best = None
        self.wait = 0
        self.stopped_epoch = 0

    def _better(self, cur, best):
        if self.mode == "min":
            return cur < best - self.min_delta
        return cur > best + self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        if self.best is None or self._better(cur, self.best):
            self.best = cur
            self.wait = 0
            if self.save_best_model and self.params.get("save_dir"):
                self.model.save(os.path.join(self.params["save_dir"], "best_model"))
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True
                if self.verbose:
                    print(f"Early stopping: best {self.monitor}={self.best}")


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler per batch or per epoch."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        assert by_step != by_epoch
        self.by_step = by_step

    def _sched(self):
        from ..optimizer.lr import LRScheduler as Sched

        opt = getattr(self.model, "_optimizer", None)
        if opt is not None and isinstance(opt._lr, Sched):
            return opt._lr
        return None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if not self.by_step and s is not None:
            s.step()


class VisualDL(Callback):
    """Stub: visualdl is not available in this image; scalars are appended
    to a plain log file so training curves remain inspectable.

    ``log_freq``: write (and therefore READ the logs) every N steps.
    Reading per-step logs materializes the sync-free fit path's lazy
    values — a host<->device round trip — so per-step scalars cost
    throughput on a tunnel-attached TPU; raise log_freq to amortize.
    """

    def __init__(self, log_dir="./log", log_freq=1):
        super().__init__()
        self.log_dir = log_dir
        self.log_freq = int(log_freq)
        self._step = 0

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        if self._step % self.log_freq != 0:
            return  # no logs read off-cadence: lazy values stay lazy
        os.makedirs(self.log_dir, exist_ok=True)
        with open(os.path.join(self.log_dir, "scalars.txt"), "a") as f:
            for k, v in (logs or {}).items():
                if isinstance(v, numbers.Number):
                    f.write(f"{self._step}\t{k}\t{v}\n")


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, log_freq=1, verbose=2, save_freq=1,
                     save_dir=None, metrics=None, mode="train"):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    if not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks = cbks + [ModelCheckpoint(save_freq, save_dir)]
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks = cbks + [LRScheduler()]
    lst = CallbackList(cbks)
    lst.set_model(model)
    lst.set_params({
        "batch_size": batch_size,
        "epochs": epochs,
        "steps": steps,
        "verbose": verbose,
        "metrics": metrics or [],
        "save_dir": save_dir,
    })
    return lst


class ReduceLROnPlateau(Callback):
    """Scale the LR down when a monitored metric stops improving
    (reference: python/paddle/hapi/callbacks.py ReduceLROnPlateau)."""

    def __init__(self, monitor="loss", factor=0.1, patience=10,
                 verbose=1, mode="auto", min_delta=1e-4, cooldown=0,
                 min_lr=0.0):
        super().__init__()
        self.monitor = monitor
        self.factor = float(factor)
        self.patience = int(patience)
        self.verbose = verbose
        self.min_delta = abs(float(min_delta))
        self.cooldown = int(cooldown)
        self.min_lr = float(min_lr)
        if mode == "max" or (mode == "auto" and "acc" in monitor):
            self._better = lambda cur, best: cur > best + self.min_delta
            self._best = -float("inf")
        else:
            self._better = lambda cur, best: cur < best - self.min_delta
            self._best = float("inf")
        self._wait = 0
        self._cooldown_left = 0

    def _current(self, logs):
        v = (logs or {}).get(self.monitor)
        if isinstance(v, (list, tuple)):
            v = v[0]
        return None if v is None else float(v)

    def _step(self, logs):
        cur = self._current(logs)
        if cur is None:
            return
        if self._cooldown_left > 0:
            # cooldown suppresses patience counting entirely
            self._cooldown_left -= 1
            self._wait = 0
            if self._better(cur, self._best):
                self._best = cur
            return
        if self._better(cur, self._best):
            self._best = cur
            self._wait = 0
            return
        self._wait += 1
        if self._wait >= self.patience:
            opt = getattr(self.model, "_optimizer", None)
            if opt is None:
                return
            lr_obj = opt._lr
            if hasattr(lr_obj, "last_lr"):  # LRScheduler
                new = max(float(lr_obj.last_lr) * self.factor, self.min_lr)
                lr_obj.last_lr = new
                if hasattr(lr_obj, "base_lr"):
                    lr_obj.base_lr = new
            else:
                new = max(float(lr_obj) * self.factor, self.min_lr)
                opt._lr = new
            if self.verbose:
                print(f"ReduceLROnPlateau: lr -> {new:.3e}")
            self._wait = 0
            self._cooldown_left = self.cooldown

    # At most ONE patience step per epoch. fit() fires on_epoch_end
    # (train logs) and then, with eval_data, on_eval_end (eval logs);
    # eval is the authoritative signal, so epoch-end stashes its logs
    # and eval-end either overrides or the stash flushes at the next
    # epoch boundary / train end.
    def on_epoch_end(self, epoch, logs=None):
        self._flush()  # previous epoch's stash, if eval never consumed it
        self._pending = dict(logs or {})

    def on_eval_end(self, logs=None):
        self._pending = dict(logs or {})
        self._flush()

    def on_train_end(self, logs=None):
        self._flush()

    def _flush(self):
        pending = getattr(self, "_pending", None)
        if pending is not None:
            self._pending = None
            self._step(pending)
