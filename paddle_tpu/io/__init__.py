"""Data pipeline: Dataset / DataLoader / samplers.

Reference parity: python/paddle/io/ (unverified, mount empty). The
reference's multiprocess C++ reader ops are replaced by a background
prefetch thread pool feeding pinned numpy batches; on TPU the host→device
transfer is overlapped by jax's async dispatch. DistributedBatchSampler
keeps the exact rank-sharding semantics Fleet relies on.
"""
from .dataset import (  # noqa: F401
    ChainDataset,
    ComposeDataset,
    ConcatDataset,
    Dataset,
    IterableDataset,
    Subset,
    TensorDataset,
    random_split,
)
from .dataloader import DataLoader, default_collate_fn  # noqa: F401
from .worker import WorkerInfo, get_worker_info  # noqa: F401
from .sampler import (  # noqa: F401
    BatchSampler,
    DistributedBatchSampler,
    RandomSampler,
    Sampler,
    SequenceSampler,
    SubsetRandomSampler,
    WeightedRandomSampler,
)
