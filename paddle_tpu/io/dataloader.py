"""DataLoader with multiprocess workers over shared-memory rings.

Reference parity: python/paddle/io/dataloader/ + the C++ reader ops and
shared-memory queues (paddle/fluid/operators/reader/ — unverified, mount
empty). Two worker modes, as in the reference:

- ``num_workers>0, use_shared_memory=True`` (default): SPAWNED worker
  processes (fresh jax-free interpreters — see worker.py for why fork is
  unsafe here) fetch+collate numpy batches and push them through
  per-worker C shared-memory SPSC rings (paddle_tpu/native/shm_ring.c);
  the parent reads zero-copy views and converts to device arrays. True
  parallelism for Python-heavy datasets (decode/augment), matching the
  reference's multiprocess loader. Requires map-style picklable datasets
  returning numpy; falls back to the thread pool when a C compiler is
  unavailable, the dataset won't pickle, or workers fail to start.
- ``use_shared_memory=False``: a thread pool (numpy collation releases
  the GIL for the heavy copies) plus a bounded prefetch queue.
"""
from __future__ import annotations

import os
import queue
import threading
import uuid
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..core.tensor import Tensor
from .dataset import IterableDataset
from .sampler import BatchSampler, DistributedBatchSampler  # noqa: F401


def default_collate_fn(batch):
    """Stack a list of samples into batched Tensors (paddle semantics)."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        import jax.numpy as jnp

        return Tensor(jnp.stack([s.value for s in batch]))
    if isinstance(sample, np.ndarray):
        return _to_tensor(np.stack(batch))
    if isinstance(sample, (int, np.integer)):
        return _to_tensor(np.asarray(batch, dtype=np.int64))
    if isinstance(sample, (float, np.floating)):
        return _to_tensor(np.asarray(batch, dtype=np.float32))
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return tuple(default_collate_fn(list(col)) for col in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    if isinstance(sample, (str, bytes)):
        return list(batch)
    # PIL images and other array-likes
    return _to_tensor(np.stack([np.asarray(s) for s in batch]))


def _to_tensor(arr):
    import jax.numpy as jnp

    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return Tensor(jnp.asarray(arr))


class DataLoader:
    def __init__(
        self,
        dataset,
        feed_list=None,
        places=None,
        return_list=True,
        batch_sampler=None,
        batch_size=1,
        shuffle=False,
        drop_last=False,
        collate_fn=None,
        num_workers=0,
        use_buffer_reader=True,
        prefetch_factor=2,
        use_shared_memory=True,
        timeout=0,
        worker_init_fn=None,
        persistent_workers=False,
    ):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self._user_collate = collate_fn
        self.num_workers = max(0, int(num_workers))
        self.prefetch_factor = max(2, int(prefetch_factor))
        self.use_shared_memory = bool(use_shared_memory)
        self.timeout = float(timeout)
        self.worker_init_fn = worker_init_fn
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            if batch_size is None:
                self.batch_sampler = None
                self.batch_size = None
            else:
                self.batch_sampler = BatchSampler(
                    dataset=dataset,
                    shuffle=shuffle,
                    batch_size=batch_size,
                    drop_last=drop_last,
                )

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset DataLoader has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    # ------------------------------------------------------------ iteration
    def _fetch(self, indices):
        samples = [self.dataset[i] for i in indices]
        return self.collate_fn(samples)

    def _iter_single(self):
        if self._iterable:
            batch = []
            for sample in self.dataset:
                batch.append(sample)
                if len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
            return
        if self.batch_sampler is None:
            for i in range(len(self.dataset)):
                yield self.collate_fn([self.dataset[i]])
            return
        for indices in self.batch_sampler:
            yield self._fetch(indices)

    def _iter_prefetch(self, batches=None):
        """Thread-pool fetch + bounded queue: overlaps host data work with
        device compute (jax dispatch is already async on the device side).
        ``batches`` overrides the sampler (the multiprocess path passes
        its already-materialized index list when falling back, since a
        one-shot sampler iterator is consumed by then)."""
        if batches is None and (self._iterable or self.batch_sampler is None):
            yield from self._iter_single()
            return
        sentinel = object()
        q: queue.Queue = queue.Queue(self.prefetch_factor * self.num_workers)
        pool = ThreadPoolExecutor(max_workers=self.num_workers)
        # reference contract: get_worker_info() is non-None whenever
        # num_workers>0. The thread pool shares one process, so expose a
        # single logical worker (id 0) for the iteration's duration;
        # refcounted so nested/concurrent loader iterations don't clobber
        # each other (last exit clears it). Approximation: the info is
        # process-global, so the main thread also sees it mid-iteration.
        from . import worker as worker_mod

        if self.num_workers > 0:
            with worker_mod._FALLBACK_LOCK:
                if worker_mod._FALLBACK_DEPTH[0] == 0:
                    worker_mod._WORKER_INFO = worker_mod.WorkerInfo(
                        0, self.num_workers, self.dataset, 0
                    )
                worker_mod._FALLBACK_DEPTH[0] += 1
            reset_info = True
        else:
            reset_info = False

        def producer():
            try:
                futures = []
                depth = self.prefetch_factor * self.num_workers
                it = iter(self.batch_sampler if batches is None else batches)
                for indices in it:
                    futures.append(pool.submit(self._fetch, indices))
                    if len(futures) >= depth:
                        q.put(futures.pop(0))
                for f in futures:
                    q.put(f)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is sentinel:
                    break
                yield item.result()
        finally:
            pool.shutdown(wait=False, cancel_futures=True)
            if reset_info:
                with worker_mod._FALLBACK_LOCK:
                    worker_mod._FALLBACK_DEPTH[0] -= 1
                    if worker_mod._FALLBACK_DEPTH[0] == 0:
                        worker_mod._WORKER_INFO = None

    def _iter_multiprocess(self):
        """Spawned workers + per-worker shm rings (see module docstring).
        Batch i comes from worker i % W; reading rings round-robin keeps
        the reference's deterministic order."""
        import pickle
        import subprocess
        import sys
        import tempfile

        from ..native import ShmRing
        from .worker import deserialize_batch

        batches = list(self.batch_sampler)
        w = min(self.num_workers, max(1, len(batches)))
        ring_mb = int(os.environ.get("FLAGS_dataloader_shm_mb", 64))
        rings, procs = [], []
        per_worker = [batches[i::w] for i in range(w)]
        # base for WorkerInfo.seed (reference: per-epoch base + worker id)
        import random as _random

        base_seed = _random.randint(0, 2 ** 31 - 1)
        # numpy-producing collate in the worker; Tensor conversion here
        worker_collate = self._user_collate
        timeout_ms = int(self.timeout * 1000) if self.timeout > 0 else -1

        worker_py = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "worker.py"
        )
        bootstrap = (
            "import importlib.util, sys; "
            f"spec = importlib.util.spec_from_file_location('ptw', {worker_py!r}); "
            "m = importlib.util.module_from_spec(spec); "
            "sys.modules['ptw'] = m; "
            # alias under the package name so a dataset's
            # `from paddle_tpu.io import get_worker_info` resolves to the
            # instance whose _WORKER_INFO worker_loop installs
            "sys.modules['paddle_tpu.io.worker'] = m; "
            "spec.loader.exec_module(m); m.spawn_main()"
        )
        # child env: forward the parent's sys.path so the pickled
        # dataset's defining module resolves, but jax-free by
        # construction — the axon sitecustomize entry (which imports jax
        # at interpreter start) is stripped
        env = dict(os.environ)
        parent_paths = [
            p if p else os.getcwd()
            for p in sys.path
            if "axon_site" not in (p or "")
        ]
        env["PYTHONPATH"] = os.pathsep.join(
            dict.fromkeys(parent_paths)  # de-dupe, keep order
        )
        env["JAX_PLATFORMS"] = "cpu"

        payload_files = []
        try:
            for i in range(w):
                name = f"/pt_dl_{os.getpid()}_{uuid.uuid4().hex[:8]}_{i}"
                rings.append(
                    ShmRing(name, capacity=ring_mb << 20, create=True)
                )
            for i in range(w):
                pf = tempfile.NamedTemporaryFile(
                    suffix=".pkl", delete=False
                )
                payload_files.append(pf.name)
                main_mod = sys.modules.get("__main__")
                main_script = getattr(main_mod, "__file__", None)
                if main_script and not str(main_script).endswith(".py"):
                    main_script = None
                try:
                    try:
                        inner = pickle.dumps(
                            (rings[i].name.decode(), self.dataset,
                             worker_collate, per_worker[i], i,
                             self.worker_init_fn, w, base_seed),
                            protocol=pickle.HIGHEST_PROTOCOL,
                        )
                        pickle.dump((main_script, inner), pf)
                    except Exception:
                        # unpicklable dataset/collate: thread-pool fallback
                        self._teardown_workers(rings, procs)
                        rings, procs = [], []
                        sys.stderr.write(
                            "paddle_tpu DataLoader: dataset/collate_fn "
                            "not picklable for spawned workers; falling "
                            "back to the thread-pool loader\n"
                        )
                        yield from self._iter_prefetch(batches)
                        return
                finally:
                    pf.close()
                procs.append(subprocess.Popen(
                    [sys.executable, "-c", bootstrap, pf.name], env=env,
                ))

            # startup handshake: every worker must deliver its HELLO
            # record promptly (covers interpreter startup failures and
            # any residual environment weirdness); on timeout, degrade
            # to the thread pool instead of hanging
            hello_s = float(os.environ.get(
                "FLAGS_dataloader_worker_start_timeout", "30"))
            try:
                for i, r in enumerate(rings):
                    waited = 0.0
                    while True:  # 500ms steps: catch fast-dying workers
                        try:
                            v = r.next_view(500)
                            break
                        except TimeoutError:
                            waited += 0.5
                            if (procs[i].poll() is not None
                                    or waited >= hello_s):
                                raise
                    if v is None or bytes(memoryview(v)) != b"HELLO":
                        raise TimeoutError("bad handshake")
                    r.advance()
            except TimeoutError:
                self._teardown_workers(rings, procs)
                rings, procs = [], []
                sys.stderr.write(
                    "paddle_tpu DataLoader: worker startup handshake "
                    "failed or timed out; falling back to the "
                    "thread-pool loader for this epoch\n"
                )
                yield from self._iter_prefetch(batches)
                return

            import jax

            copy_leaf = jax.default_backend() == "cpu"
            converted = []
            # type parity with the other paths: default collation yields
            # Tensors; a custom collate_fn's arrays stay numpy (exactly
            # what the thread-pool fallback would yield)
            raw_leaves = self._user_collate is not None

            def to_leaf(np_view):
                if raw_leaves:
                    return np.array(np_view)  # own the bytes: ring recycles
                # CPU backend may alias host buffers; copy before the
                # ring slot is recycled. Accelerator backends DMA out of
                # the view — we block on the transfer before advance().
                arr = np.array(np_view) if copy_leaf else np_view
                t = _to_tensor(np.asarray(arr))
                converted.append(t)
                return t

            def next_view_checked(ring, wi):
                """Bounded-wait read + child liveness check: a worker
                killed hard (segfault/OOM) can't close its ring, so a
                pure blocking read would hang forever."""
                waited = 0.0
                while True:
                    step_ms = 500 if timeout_ms < 0 else min(
                        500, timeout_ms
                    )
                    try:
                        return ring.next_view(step_ms)
                    except TimeoutError:
                        waited += step_ms / 1000.0
                        status = procs[wi].poll()
                        if status is not None and not ring.closed:
                            raise RuntimeError(
                                f"DataLoader worker {wi} died "
                                f"(status {status}) without closing its "
                                "ring — likely a hard crash (segfault/"
                                "OOM) in dataset.__getitem__"
                            ) from None
                        if timeout_ms >= 0 and waited * 1000 >= timeout_ms:
                            raise

            for bi in range(len(batches)):
                ring = rings[bi % w]
                view = next_view_checked(ring, bi % w)
                if view is None:
                    raise RuntimeError(
                        f"DataLoader worker {bi % w} ended early "
                        "(ring closed before all batches arrived)"
                    )
                raw = memoryview(view)
                if bytes(raw[:4]) == b"\xff\xff\xff\xff":
                    import pickle

                    _, tb = pickle.loads(bytes(raw[4:]))
                    raise RuntimeError(
                        f"DataLoader worker {bi % w} failed:\n{tb}"
                    )
                converted.clear()
                batch = deserialize_batch(view, to_leaf)
                if not copy_leaf and converted:
                    # the device copies must finish before the worker may
                    # recycle this ring slot
                    jax.block_until_ready([t.value for t in converted])
                ring.advance()
                yield batch
        finally:
            self._teardown_workers(rings, procs)
            for pf_name in payload_files:
                try:
                    os.unlink(pf_name)
                except OSError:
                    pass

    @staticmethod
    def _teardown_workers(rings, procs):
        import subprocess

        for r in rings:
            try:
                r.close()
            except Exception:
                pass
        for proc in procs:
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
        for r in rings:
            try:
                r.detach()
                r.unlink()
            except Exception:
                pass

    def _can_multiprocess(self):
        from ..native import get_lib

        return (
            self.use_shared_memory
            and not self._iterable
            and self.batch_sampler is not None
            and get_lib() is not None
        )

    def __iter__(self):
        if self.num_workers > 0:
            if self._can_multiprocess():
                return self._iter_multiprocess()
            return self._iter_prefetch()
        return self._iter_single()
