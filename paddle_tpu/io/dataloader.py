"""DataLoader with background prefetch.

Reference parity: python/paddle/io/dataloader/ + the C++ reader ops
(paddle/fluid/operators/reader/ — unverified, mount empty). The reference
forks worker processes and moves batches through shared-memory queues; here
worker parallelism is a thread pool (numpy collation releases the GIL for
the heavy copies) plus a bounded prefetch queue, and the optional native
accelerated path (paddle_tpu/native) provides a C shared-memory ring buffer
for multiprocess loading.
"""
from __future__ import annotations

import queue
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..core.tensor import Tensor
from .dataset import IterableDataset
from .sampler import BatchSampler, DistributedBatchSampler  # noqa: F401


def default_collate_fn(batch):
    """Stack a list of samples into batched Tensors (paddle semantics)."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        import jax.numpy as jnp

        return Tensor(jnp.stack([s.value for s in batch]))
    if isinstance(sample, np.ndarray):
        return _to_tensor(np.stack(batch))
    if isinstance(sample, (int, np.integer)):
        return _to_tensor(np.asarray(batch, dtype=np.int64))
    if isinstance(sample, (float, np.floating)):
        return _to_tensor(np.asarray(batch, dtype=np.float32))
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return tuple(default_collate_fn(list(col)) for col in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    if isinstance(sample, (str, bytes)):
        return list(batch)
    # PIL images and other array-likes
    return _to_tensor(np.stack([np.asarray(s) for s in batch]))


def _to_tensor(arr):
    import jax.numpy as jnp

    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return Tensor(jnp.asarray(arr))


class DataLoader:
    def __init__(
        self,
        dataset,
        feed_list=None,
        places=None,
        return_list=True,
        batch_sampler=None,
        batch_size=1,
        shuffle=False,
        drop_last=False,
        collate_fn=None,
        num_workers=0,
        use_buffer_reader=True,
        prefetch_factor=2,
        use_shared_memory=True,
        timeout=0,
        worker_init_fn=None,
        persistent_workers=False,
    ):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = max(0, int(num_workers))
        self.prefetch_factor = max(2, int(prefetch_factor))
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            if batch_size is None:
                self.batch_sampler = None
                self.batch_size = None
            else:
                self.batch_sampler = BatchSampler(
                    dataset=dataset,
                    shuffle=shuffle,
                    batch_size=batch_size,
                    drop_last=drop_last,
                )

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset DataLoader has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    # ------------------------------------------------------------ iteration
    def _fetch(self, indices):
        samples = [self.dataset[i] for i in indices]
        return self.collate_fn(samples)

    def _iter_single(self):
        if self._iterable:
            batch = []
            for sample in self.dataset:
                batch.append(sample)
                if len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
            return
        if self.batch_sampler is None:
            for i in range(len(self.dataset)):
                yield self.collate_fn([self.dataset[i]])
            return
        for indices in self.batch_sampler:
            yield self._fetch(indices)

    def _iter_prefetch(self):
        """Thread-pool fetch + bounded queue: overlaps host data work with
        device compute (jax dispatch is already async on the device side)."""
        if self._iterable or self.batch_sampler is None:
            yield from self._iter_single()
            return
        sentinel = object()
        q: queue.Queue = queue.Queue(self.prefetch_factor * self.num_workers)
        pool = ThreadPoolExecutor(max_workers=self.num_workers)

        def producer():
            try:
                futures = []
                depth = self.prefetch_factor * self.num_workers
                it = iter(self.batch_sampler)
                for indices in it:
                    futures.append(pool.submit(self._fetch, indices))
                    if len(futures) >= depth:
                        q.put(futures.pop(0))
                for f in futures:
                    q.put(f)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is sentinel:
                    break
                yield item.result()
        finally:
            pool.shutdown(wait=False, cancel_futures=True)

    def __iter__(self):
        if self.num_workers > 0:
            return self._iter_prefetch()
        return self._iter_single()
