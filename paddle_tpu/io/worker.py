"""Multiprocess DataLoader workers over shared-memory rings.

Reference parity: python/paddle/io/dataloader/worker.py + the shared-
memory queue transport (unverified, mount empty): forked worker
processes fetch+collate batches and pass them to the parent without
pickling the payload.

TPU design notes:
- Workers are FORKED, inheriting the dataset in-memory; they must stay
  jax-free (jax runtimes do not survive fork), so worker-side collation
  is numpy-only and the parent converts the zero-copy views to device
  arrays (the host->device DMA reads straight out of the shared segment).
- Batch i is produced by worker i % num_workers and the parent reads
  rings round-robin, preserving the reference's deterministic ordering.
- Record format: [u32 magic][u32 header_len][pickled (spec, leaf_meta)]
  [64-aligned raw array bytes...]. Only the structure is pickled; the
  array payload is memcpy'd once in the worker and viewed in the parent.
"""
from __future__ import annotations

import os
import pickle
import struct
import sys

import numpy as np

_MAGIC = 0x50445452  # "PDTR"
_ALIGN = 64


def _align(n):
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


def collate_numpy(batch):
    """default_collate_fn semantics with numpy leaves (worker-side)."""
    sample = batch[0]
    if hasattr(sample, "value") and hasattr(sample, "stop_gradient"):
        # catch paddle Tensors BEFORE the np.asarray fallback would
        # invoke Tensor.__array__ -> jax inside the forked child
        raise TypeError(
            "multiprocess DataLoader workers must produce numpy, not "
            "paddle Tensors (jax does not survive fork); return numpy "
            "from the dataset or use use_shared_memory=False"
        )
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    if isinstance(sample, (list, tuple)):
        return tuple(
            collate_numpy(list(col)) for col in zip(*batch)
        )
    if isinstance(sample, dict):
        return {k: collate_numpy([d[k] for d in batch]) for k in sample}
    if isinstance(sample, (str, bytes)):
        return list(batch)
    return np.stack([np.asarray(s) for s in batch])


def serialize_batch(batch):
    """-> one bytes record: pickled structure + raw aligned array bytes."""
    leaves = []

    def enc(x):
        if hasattr(x, "value") and hasattr(x, "stop_gradient"):
            raise TypeError(
                "multiprocess DataLoader workers must produce numpy, not "
                "paddle Tensors (jax does not survive fork); return numpy "
                "from the dataset/collate_fn or use num_workers with "
                "use_shared_memory=False"
            )
        if isinstance(x, np.ndarray):
            leaves.append(np.ascontiguousarray(x))
            return ("a", len(leaves) - 1)
        if isinstance(x, tuple):
            return ("t", [enc(v) for v in x])
        if isinstance(x, list):
            return ("l", [enc(v) for v in x])
        if isinstance(x, dict):
            return ("d", {k: enc(v) for k, v in x.items()})
        return ("o", x)

    spec = enc(batch)
    meta = [(l.dtype.str, l.shape, l.nbytes) for l in leaves]
    header = pickle.dumps((spec, meta), protocol=pickle.HIGHEST_PROTOCOL)
    off = _align(8 + len(header))
    offsets = []
    for l in leaves:
        offsets.append(off)
        off = _align(off + l.nbytes)
    buf = bytearray(off)
    struct.pack_into("<II", buf, 0, _MAGIC, len(header))
    buf[8 : 8 + len(header)] = header
    for l, o in zip(leaves, offsets):
        buf[o : o + l.nbytes] = l.tobytes()  # one worker-side copy
    return bytes(buf)


def deserialize_batch(view, to_leaf):
    """Rebuild the structure from a record view; array leaves become
    ``to_leaf(np_view)`` where np_view is ZERO-COPY into the ring."""
    magic, hlen = struct.unpack_from("<II", view, 0)
    if magic != _MAGIC:
        raise ValueError("corrupt DataLoader record")
    spec, meta = pickle.loads(bytes(memoryview(view)[8 : 8 + hlen]))
    off = _align(8 + hlen)
    arrays = []
    for dtype, shape, nbytes in meta:
        arr = np.frombuffer(view, dtype=np.dtype(dtype), count=int(
            np.prod(shape)) if shape else 1, offset=off).reshape(shape)
        arrays.append(arr)
        off = _align(off + nbytes)

    def dec(node):
        kind = node[0]
        if kind == "a":
            return to_leaf(arrays[node[1]])
        if kind == "t":
            return tuple(dec(v) for v in node[1])
        if kind == "l":
            return [dec(v) for v in node[1]]
        if kind == "d":
            return {k: dec(v) for k, v in node[1].items()}
        return node[1]

    return dec(spec)


def worker_loop(ring_name, dataset, collate_fn, index_batches, worker_id,
                worker_init_fn=None):
    """Child-process entry: fetch assigned batches in order, write to the
    per-worker ring, close the ring when done (or on error, after
    shipping the exception). NOTHING may escape this function — an
    exception unwinding into the fork caller would run the PARENT's
    cleanup inside the child (unlinking shared rings) and then continue
    executing the training script as a duplicate process."""
    try:
        from ..native import ShmRing

        ring = ShmRing(ring_name, create=False)
    except BaseException:
        os._exit(1)
    try:
        if worker_init_fn is not None:
            worker_init_fn(worker_id)
        for indices in index_batches:
            samples = [dataset[i] for i in indices]
            batch = (collate_fn or collate_numpy)(samples)
            ring.write(serialize_batch(batch))
        ring.close()
    except BrokenPipeError:
        pass  # parent tore down mid-epoch
    except BaseException as e:  # ship the failure to the parent
        try:
            import traceback

            msg = pickle.dumps(
                ("error", f"{type(e).__name__}: {e}\n"
                 + "".join(traceback.format_exc()))
            )
            ring.write(b"\xff\xff\xff\xff" + msg)
            ring.close()
        except Exception:
            pass
        os._exit(1)
    finally:
        ring.detach()
    os._exit(0)
