"""Multiprocess DataLoader workers over shared-memory rings.

Reference parity: python/paddle/io/dataloader/worker.py + the shared-
memory queue transport (unverified, mount empty): forked worker
processes fetch+collate batches and pass them to the parent without
pickling the payload.

TPU design notes:
- Workers are SPAWNED (fork+exec of a fresh interpreter), not forked:
  the training process is heavily multithreaded (jax/XLA thread pools),
  and a bare fork() inherits their locked mutexes — measured deadlocks,
  sometimes after the child had already produced batches. The spawn
  bootstrap loads ONLY numpy + this module (the axon sitecustomize jax
  import is stripped from the child's PYTHONPATH), so workers can never
  touch jax; the dataset/collate_fn/indices ship via one pickle file.
- Batch i is produced by worker i % num_workers and the parent reads
  rings round-robin, preserving the reference's deterministic ordering.
- Record format: [u32 magic][u32 header_len][pickled (spec, leaf_meta)]
  [64-aligned raw array bytes...]. Only the structure is pickled; the
  array payload is memcpy'd once in the worker and viewed in the parent.
"""
from __future__ import annotations

import os
import pickle
import struct
import sys

import numpy as np

_MAGIC = 0x50445452  # "PDTR"
_ALIGN = 64


def _align(n):
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


def _is_paddle_tensor(x):
    return hasattr(x, "value") and hasattr(x, "stop_gradient")


def collate_numpy(batch):
    """default_collate_fn semantics with numpy leaves (worker-side).
    Paddle-Tensor samples are materialized to numpy — safe in a SPAWNED
    worker (its private jax runtime was created in this process, on CPU)."""
    sample = batch[0]
    if _is_paddle_tensor(sample):
        return np.stack([np.asarray(s.numpy()) for s in batch])
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    if isinstance(sample, (list, tuple)):
        return tuple(
            collate_numpy(list(col)) for col in zip(*batch)
        )
    if isinstance(sample, dict):
        return {k: collate_numpy([d[k] for d in batch]) for k in sample}
    if isinstance(sample, (str, bytes)):
        return list(batch)
    return np.stack([np.asarray(s) for s in batch])


def serialize_batch(batch):
    """-> one bytes record: pickled structure + raw aligned array bytes."""
    leaves = []

    def enc(x):
        if _is_paddle_tensor(x):
            x = np.asarray(x.numpy())
        if isinstance(x, np.ndarray):
            leaves.append(np.ascontiguousarray(x))
            return ("a", len(leaves) - 1)
        if isinstance(x, tuple):
            return ("t", [enc(v) for v in x])
        if isinstance(x, list):
            return ("l", [enc(v) for v in x])
        if isinstance(x, dict):
            return ("d", {k: enc(v) for k, v in x.items()})
        return ("o", x)

    spec = enc(batch)
    meta = [(l.dtype.str, l.shape, l.nbytes) for l in leaves]
    header = pickle.dumps((spec, meta), protocol=pickle.HIGHEST_PROTOCOL)
    off = _align(8 + len(header))
    offsets = []
    for l in leaves:
        offsets.append(off)
        off = _align(off + l.nbytes)
    buf = bytearray(off)
    struct.pack_into("<II", buf, 0, _MAGIC, len(header))
    buf[8 : 8 + len(header)] = header
    for l, o in zip(leaves, offsets):
        buf[o : o + l.nbytes] = l.tobytes()  # one worker-side copy
    return bytes(buf)


def deserialize_batch(view, to_leaf):
    """Rebuild the structure from a record view; array leaves become
    ``to_leaf(np_view)`` where np_view is ZERO-COPY into the ring."""
    magic, hlen = struct.unpack_from("<II", view, 0)
    if magic != _MAGIC:
        raise ValueError("corrupt DataLoader record")
    spec, meta = pickle.loads(bytes(memoryview(view)[8 : 8 + hlen]))
    off = _align(8 + hlen)
    arrays = []
    for dtype, shape, nbytes in meta:
        arr = np.frombuffer(view, dtype=np.dtype(dtype), count=int(
            np.prod(shape)) if shape else 1, offset=off).reshape(shape)
        arrays.append(arr)
        off = _align(off + nbytes)

    def dec(node):
        kind = node[0]
        if kind == "a":
            return to_leaf(arrays[node[1]])
        if kind == "t":
            return tuple(dec(v) for v in node[1])
        if kind == "l":
            return [dec(v) for v in node[1]]
        if kind == "d":
            return {k: dec(v) for k, v in node[1].items()}
        return node[1]

    return dec(spec)


def _load_shmring():
    """ShmRing class, resolvable both in-package and from the spawn
    bootstrap (where this module is loaded by file path with no parent
    package — importing paddle_tpu/__init__ would drag in jax)."""
    try:
        from ..native import ShmRing

        return ShmRing
    except ImportError:
        import importlib.util

        p = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), os.pardir,
            "native", "__init__.py",
        )
        spec = importlib.util.spec_from_file_location(
            "paddle_tpu_native_standalone", p
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod.ShmRing


def spawn_main():
    """Entry point of a SPAWNED worker: argv[1] is a pickle file holding
    (main_script, inner) where inner unpickles to worker_loop's
    positional args: (ring_name, dataset, collate_fn, index_batches,
    worker_id, worker_init_fn, num_workers, base_seed).

    Datasets/collate_fns defined in the training script itself pickle as
    ``__main__.X``; like multiprocessing's spawn, the parent's main
    script is re-imported here under ``__mp_main__`` and aliased to
    ``__main__`` so those names resolve. The script runs with
    __name__ != "__main__", so the standard ``if __name__ == "__main__"``
    guard keeps its training entry from re-executing."""
    # the outer payload holds (main_script, inner_pickle): the alias must
    # be installed BEFORE the inner args (which may reference __main__
    # classes) are unpickled
    with open(sys.argv[1], "rb") as f:
        main_script, blob = pickle.load(f)
    if main_script and os.path.exists(main_script):
        try:
            import importlib.util

            spec = importlib.util.spec_from_file_location(
                "__mp_main__", main_script
            )
            m = importlib.util.module_from_spec(spec)
            sys.modules["__mp_main__"] = m
            spec.loader.exec_module(m)
            sys.modules["__main__"] = m
        except BaseException:
            pass  # unpickle below will fail with a shipped error if needed
    worker_loop(*pickle.loads(blob))


class WorkerInfo:
    """paddle.io.get_worker_info() payload (reference:
    python/paddle/io/dataloader/worker.py WorkerInfo — unverified).
    ``seed`` follows the reference contract: base_seed + worker id, for
    per-worker RNG seeding in datasets/worker_init_fn."""

    def __init__(self, id, num_workers, dataset, seed=None):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = (0 if seed is None else seed) + id

    def __repr__(self):
        return (
            f"WorkerInfo(id={self.id}, num_workers={self.num_workers}, "
            f"seed={self.seed})"
        )


_WORKER_INFO = None
# thread-pool fallback refcount (see dataloader._iter_prefetch)
import threading as _threading  # noqa: E402

_FALLBACK_LOCK = _threading.Lock()
_FALLBACK_DEPTH = [0]


def get_worker_info():
    """Inside a DataLoader worker process: that worker's WorkerInfo
    (id / num_workers / dataset); in the main process: None."""
    return _WORKER_INFO


def worker_loop(ring_name, dataset, collate_fn, index_batches, worker_id,
                worker_init_fn=None, num_workers=None, base_seed=None):
    """Worker-process entry: fetch assigned batches in order, write to
    the per-worker ring, close the ring when done (or on error, after
    shipping the exception). NOTHING may escape this function — it
    always terminates the process via os._exit."""
    try:
        ShmRing = _load_shmring()

        ring = ShmRing(ring_name, create=False)
    except BaseException:
        os._exit(1)
    try:
        # startup handshake: fork-from-a-threaded-parent can deadlock the
        # child before it runs a single line (inherited locked mutexes —
        # jax is multithreaded); the parent waits for this record with a
        # timeout and falls back to the thread pool if it never arrives
        ring.write(b"HELLO")
        global _WORKER_INFO
        _WORKER_INFO = WorkerInfo(worker_id, num_workers, dataset, base_seed)
        if worker_init_fn is not None:
            worker_init_fn(worker_id)
        for indices in index_batches:
            samples = [dataset[i] for i in indices]
            batch = (collate_fn or collate_numpy)(samples)
            ring.write(serialize_batch(batch))
        ring.close()
    except BrokenPipeError:
        pass  # parent tore down mid-epoch
    except BaseException as e:  # ship the failure to the parent
        try:
            import traceback

            msg = pickle.dumps(
                ("error", f"{type(e).__name__}: {e}\n"
                 + "".join(traceback.format_exc()))
            )
            ring.write(b"\xff\xff\xff\xff" + msg)
            ring.close()
        except Exception:
            pass
        os._exit(1)
    finally:
        ring.detach()
    os._exit(0)
