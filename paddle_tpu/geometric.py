"""paddle.geometric parity (python/paddle/geometric/ — unverified):
segment reductions + message-passing helpers over the segment kernels
(scatter-add lowers to XLA scatter on TPU)."""
from .core import dispatch
from .ops.tail import (  # noqa: F401
    _segment_n,
    _segment_reduce,
    segment_max,
    segment_mean,
    segment_min,
    segment_sum,
)


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather source-node features along edges and reduce at the
    destination (the reference's basic graph message passing).
    ``out_size`` fixes the number of output rows (nodes); without it the
    size is inferred as max(dst_index)+1, which truncates trailing
    isolated nodes."""
    from .ops.manipulation import gather

    if reduce_op not in ("sum", "mean", "max", "min"):
        raise ValueError(f"send_u_recv: unknown reduce_op {reduce_op!r}")
    n = int(out_size) if out_size is not None else _segment_n(dst_index)
    return dispatch.apply(
        f"segment_{reduce_op}", _segment_reduce,
        (gather(x, src_index), dst_index), {"n": n, "how": reduce_op},
    )
