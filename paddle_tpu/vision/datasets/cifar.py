"""Cifar10/100 with offline synthetic fallback (see mnist.py rationale).
Reference parity: python/paddle/vision/datasets/cifar.py (unverified)."""
from __future__ import annotations

import os
import pickle
import tarfile
import warnings

import numpy as np

from ...io.dataset import Dataset

_CACHE = os.path.expanduser("~/.cache/paddle/dataset/cifar")


def _synthetic(n, num_classes, sample_seed):
    tmpl_rng = np.random.RandomState(12345)  # shared across train/test
    templates = tmpl_rng.rand(num_classes, 32, 32, 3) * 255
    rng = np.random.RandomState(sample_seed)
    labels = rng.randint(0, num_classes, n).astype(np.int64)
    noise = rng.rand(n, 32, 32, 3) * 64
    images = np.clip(templates[labels] * 0.75 + noise, 0, 255).astype(np.uint8)
    return images, labels


class Cifar10(Dataset):
    _num_classes = 10
    _archive = "cifar-10-python.tar.gz"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        assert mode in ("train", "test")
        self.mode = mode
        self.transform = transform
        data_file = data_file or os.path.join(_CACHE, self._archive)
        if os.path.exists(data_file):
            self.images, self.labels = self._load_archive(data_file, mode)
        else:
            warnings.warn(
                f"{type(self).__name__}: {data_file} not found and no "
                "network egress — using deterministic synthetic stand-in."
            )
            n = 10000 if mode == "train" else 2000
            self.images, self.labels = _synthetic(
                n, self._num_classes, sample_seed=42 + (mode == "test")
            )

    def _load_archive(self, path, mode):
        images, labels = [], []
        want = "data_batch" if mode == "train" else "test_batch"
        if self._num_classes == 100:
            want = "train" if mode == "train" else "test"
        with tarfile.open(path) as tf:
            for member in tf.getmembers():
                if want in member.name:
                    d = pickle.load(tf.extractfile(member), encoding="bytes")
                    images.append(d[b"data"])
                    key = b"labels" if b"labels" in d else b"fine_labels"
                    labels.extend(d[key])
        arr = np.concatenate(images).reshape(-1, 3, 32, 32)
        return (
            np.transpose(arr, (0, 2, 3, 1)).astype(np.uint8),
            np.asarray(labels, dtype=np.int64),
        )

    def __getitem__(self, idx):
        img, label = self.images[idx], self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = np.transpose(img.astype(np.float32), (2, 0, 1))
        return img, np.int64(label)

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    _num_classes = 100
    _archive = "cifar-100-python.tar.gz"
