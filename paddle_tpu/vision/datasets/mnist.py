"""MNIST / FashionMNIST.

Reference parity: python/paddle/vision/datasets/mnist.py (unverified,
mount empty). This environment has zero egress, so when the idx files are
absent a deterministic SYNTHETIC dataset with learnable per-class structure
is generated instead (clearly warned). Real files, if present at
``image_path``/``label_path`` or the default cache dir, are parsed in the
standard idx format.
"""
from __future__ import annotations

import gzip
import os
import struct
import warnings

import numpy as np

from ...io.dataset import Dataset

_CACHE = os.path.expanduser("~/.cache/paddle/dataset/mnist")


def _read_idx_images(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        assert magic == 2051, f"bad magic {magic}"
        data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.reshape(n, rows, cols)


def _read_idx_labels(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        assert magic == 2049, f"bad magic {magic}"
        return np.frombuffer(f.read(), dtype=np.uint8)


def _synthetic_digits(n, num_classes=10, template_seed=0, sample_seed=0,
                      size=28):
    """Deterministic class-structured images: each class is a fixed random
    template (shared across train/test) + per-sample noise. Learnable by
    LeNet in an epoch, and train/test measure true generalization."""
    tmpl_rng = np.random.RandomState(template_seed)
    templates = tmpl_rng.rand(num_classes, size, size) * 255
    rng = np.random.RandomState(sample_seed)
    labels = rng.randint(0, num_classes, n).astype(np.int64)
    noise = rng.rand(n, size, size) * 64
    images = np.clip(templates[labels] * 0.75 + noise, 0, 255).astype(np.uint8)
    return images, labels


class MNIST(Dataset):
    NAME = "mnist"
    _synth_seed = 0

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        assert mode in ("train", "test")
        self.mode = mode
        self.transform = transform
        n_default = 60000 if mode == "train" else 10000
        image_path = image_path or self._default_path(mode, "images")
        label_path = label_path or self._default_path(mode, "labels")
        if os.path.exists(image_path) and os.path.exists(label_path):
            self.images = _read_idx_images(image_path)
            self.labels = _read_idx_labels(label_path).astype(np.int64)
        else:
            warnings.warn(
                f"{type(self).__name__}: dataset files not found at "
                f"{image_path} and no network egress is available — using a "
                "deterministic synthetic stand-in (class-structured noise)."
            )
            n = min(n_default, 12000 if mode == "train" else 2000)
            self.images, self.labels = _synthetic_digits(
                n,
                template_seed=self._synth_seed,
                sample_seed=self._synth_seed + (0 if mode == "train" else 1),
            )

    def _default_path(self, mode, kind):
        prefix = "train" if mode == "train" else "t10k"
        suffix = "idx3-ubyte.gz" if kind == "images" else "idx1-ubyte.gz"
        return os.path.join(
            _CACHE.replace("mnist", self.NAME), f"{prefix}-{kind}-{suffix}"
        )

    def __getitem__(self, idx):
        img = self.images[idx]
        label = self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32)[None, :, :]
        return img, np.int64(label)

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"
    _synth_seed = 100
