"""DatasetFolder / ImageFolder (python/paddle/vision/datasets/folder.py
parity — unverified)."""
from __future__ import annotations

import os

import numpy as np

from ...io.dataset import Dataset

IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".npy")


def _default_loader(path):
    if path.endswith(".npy"):
        return np.load(path)
    try:
        from PIL import Image

        with Image.open(path) as img:
            return np.asarray(img.convert("RGB"))
    except ImportError as e:
        raise RuntimeError(f"cannot load {path}: PIL unavailable") from e


class DatasetFolder(Dataset):
    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or _default_loader
        extensions = extensions or IMG_EXTENSIONS
        classes = sorted(
            d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))
        )
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _, files in sorted(os.walk(cdir)):
                for fname in sorted(files):
                    path = os.path.join(dirpath, fname)
                    ok = (
                        is_valid_file(path)
                        if is_valid_file
                        else fname.lower().endswith(extensions)
                    )
                    if ok:
                        self.samples.append((path, self.class_to_idx[c]))
        self.transform = transform

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(target)

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or _default_loader
        extensions = extensions or IMG_EXTENSIONS
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fname in sorted(files):
                path = os.path.join(dirpath, fname)
                ok = (
                    is_valid_file(path)
                    if is_valid_file
                    else fname.lower().endswith(extensions)
                )
                if ok:
                    self.samples.append(path)
        self.transform = transform

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)
