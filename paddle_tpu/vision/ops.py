"""paddle.vision.ops (python/paddle/vision/ops.py parity — unverified):
detection primitives. All are pure-jnp compositions through
core.dispatch; nms uses a fixed-trip lax.while loop (static shapes for
XLA), roi_align/deform_conv2d are bilinear gathers that lower to XLA
gather/matmul — TPU-friendly, no dynamic shapes.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dispatch
from ..core.tensor import Tensor

__all__ = [
    "nms",
    "roi_align",
    "roi_pool",
    "deform_conv2d",
    "DeformConv2D",
    "box_coder",
]


def _iou_matrix(boxes):
    x1, y1, x2, y2 = (boxes[:, i] for i in range(4))
    area = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
    ix1 = jnp.maximum(x1[:, None], x1[None, :])
    iy1 = jnp.maximum(y1[:, None], y1[None, :])
    ix2 = jnp.minimum(x2[:, None], x2[None, :])
    iy2 = jnp.minimum(y2[:, None], y2[None, :])
    inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
    union = area[:, None] + area[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _nms(boxes, scores, *, iou_threshold, top_k):
    n = boxes.shape[0]
    order = jnp.argsort(-scores)
    iou = _iou_matrix(boxes)[order][:, order]
    # keep[i] = no higher-scored kept box overlaps i beyond threshold
    suppressed = jnp.zeros((n,), jnp.bool_)

    def body(i, suppressed):
        over = iou[i] > iou_threshold
        newly = over & (jnp.arange(n) > i) & ~suppressed[i]
        return suppressed | newly

    suppressed = jax.lax.fori_loop(0, n, body, suppressed)
    keep_sorted = ~suppressed
    kept_idx = jnp.where(
        keep_sorted, jnp.arange(n), n
    )
    kept_idx = jnp.sort(kept_idx)[:top_k]
    return order[jnp.where(kept_idx < n, kept_idx, 0)], (kept_idx < n)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """Hard NMS. Returns kept box indices (descending score order).

    With ``category_idxs``, suppression is done per category by offsetting
    boxes so different categories never overlap (the standard trick).
    """
    n = int(boxes.shape[0])
    if scores is None:
        scores = Tensor(jnp.arange(n, 0, -1, dtype=jnp.float32))
    top_k = n if top_k is None else min(int(top_k), n)
    if category_idxs is not None:
        import numpy as _np

        bv = _np.asarray(boxes.numpy())
        # shift each category into a disjoint coordinate band; span must
        # cover the full extent (negative coords included)
        span = float(bv.max() - bv.min()) + 1.0
        if not isinstance(category_idxs, Tensor):
            category_idxs = Tensor(jnp.asarray(category_idxs))
        offs = category_idxs.value.astype(jnp.float32)[:, None] * span
        boxes = Tensor(boxes.value + offs)
    idx, valid = dispatch.apply(
        "nms", _nms, (boxes, scores),
        {"iou_threshold": float(iou_threshold), "top_k": top_k},
        nondiff=True,
    )
    # compact to the valid prefix (host-side, like the reference's
    # dynamic-shaped output)
    import numpy as np

    iv = np.asarray(idx.numpy())
    vv = np.asarray(valid.numpy())
    return Tensor(jnp.asarray(iv[vv].astype(np.int64)))


def _bilinear_gather(feat, y, x):
    """feat [C, H, W]; y/x arbitrary same-shape index grids (float)."""
    h, w = feat.shape[-2], feat.shape[-1]
    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    y1, x1 = y0 + 1, x0 + 1
    wy1 = y - y0
    wx1 = x - x0
    wy0, wx0 = 1.0 - wy1, 1.0 - wx1

    def at(yi, xi):
        inb = (yi >= 0) & (yi <= h - 1) & (xi >= 0) & (xi <= w - 1)
        yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        return feat[:, yc, xc] * inb.astype(feat.dtype)

    return (
        at(y0, x0) * (wy0 * wx0) + at(y0, x1) * (wy0 * wx1)
        + at(y1, x0) * (wy1 * wx0) + at(y1, x1) * (wy1 * wx1)
    )


def _roi_align(feat, rois, roi_batch_idx, *, out_h, out_w, spatial_scale,
               sampling_ratio, aligned):
    off = 0.5 if aligned else 0.0

    def one_roi(bi, roi):
        fm = feat[bi]
        x1, y1, x2, y2 = roi * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1e-6 if aligned else 1.0)
        rh = jnp.maximum(y2 - y1, 1e-6 if aligned else 1.0)
        bin_h = rh / out_h
        bin_w = rw / out_w
        ratio = sampling_ratio if sampling_ratio > 0 else 2
        iy = (jnp.arange(ratio) + 0.5) / ratio
        gy = (
            y1 + bin_h * (jnp.arange(out_h)[:, None] + iy[None, :])
        ).reshape(-1)
        gx = (
            x1 + bin_w * (jnp.arange(out_w)[:, None] + iy[None, :])
        ).reshape(-1)
        yy = jnp.repeat(gy, gx.shape[0])
        xx = jnp.tile(gx, gy.shape[0])
        vals = _bilinear_gather(fm, yy, xx)  # [C, (out_h*r)*(out_w*r)]
        c = vals.shape[0]
        vals = vals.reshape(c, out_h, ratio, out_w, ratio)
        return jnp.mean(vals, axis=(2, 4))

    return jax.vmap(one_roi)(roi_batch_idx, rois)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign (bilinear bin sampling + average).

    TPU deviation from the reference: with ``sampling_ratio=-1`` the
    reference adapts the grid per ROI (ceil(roi_size/out_size) samples),
    which is data-dependent — impossible under XLA's static shapes. Here
    -1 means a fixed 2x2 grid per bin (detection-head scale ROIs);
    pass an explicit ``sampling_ratio`` for exact reference parity at
    that ratio.
    """
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    bn = [int(v) for v in (
        boxes_num.tolist() if isinstance(boxes_num, Tensor) else boxes_num
    )]
    batch_idx = jnp.concatenate([
        jnp.full((c,), i, jnp.int32) for i, c in enumerate(bn)
    ]) if bn else jnp.zeros((0,), jnp.int32)
    return dispatch.apply(
        "roi_align", _roi_align, (x, boxes, Tensor(batch_idx)),
        {"out_h": int(output_size[0]), "out_w": int(output_size[1]),
         "spatial_scale": float(spatial_scale),
         "sampling_ratio": int(sampling_ratio), "aligned": bool(aligned)},
    )


def _roi_pool(feat, rois, roi_batch_idx, *, out_h, out_w, spatial_scale):
    h, w = feat.shape[-2], feat.shape[-1]

    def one_roi(bi, roi):
        fm = feat[bi]
        x1 = jnp.round(roi[0] * spatial_scale)
        y1 = jnp.round(roi[1] * spatial_scale)
        x2 = jnp.round(roi[2] * spatial_scale)
        y2 = jnp.round(roi[3] * spatial_scale)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        bin_h = rh / out_h
        bin_w = rw / out_w
        ys = jnp.arange(h, dtype=fm.dtype)
        xs = jnp.arange(w, dtype=fm.dtype)

        def one_bin(py, px):
            hs = jnp.floor(y1 + py * bin_h)
            he = jnp.ceil(y1 + (py + 1) * bin_h)
            ws_ = jnp.floor(x1 + px * bin_w)
            we = jnp.ceil(x1 + (px + 1) * bin_w)
            mask = (
                ((ys >= hs) & (ys < he))[:, None]
                & ((xs >= ws_) & (xs < we))[None, :]
            )
            neg = jnp.asarray(-jnp.inf, fm.dtype)
            vals = jnp.where(mask[None], fm, neg)
            mx = jnp.max(vals, axis=(-2, -1))
            return jnp.where(jnp.isfinite(mx), mx, 0.0)

        py = jnp.arange(out_h)
        px = jnp.arange(out_w)
        return jax.vmap(
            lambda a: jax.vmap(lambda b: one_bin(a, b))(px)
        )(py).transpose(2, 0, 1)

    return jax.vmap(one_roi)(roi_batch_idx, rois)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    bn = [int(v) for v in (
        boxes_num.tolist() if isinstance(boxes_num, Tensor) else boxes_num
    )]
    batch_idx = jnp.concatenate([
        jnp.full((c,), i, jnp.int32) for i, c in enumerate(bn)
    ]) if bn else jnp.zeros((0,), jnp.int32)
    return dispatch.apply(
        "roi_pool", _roi_pool, (x, boxes, Tensor(batch_idx)),
        {"out_h": int(output_size[0]), "out_w": int(output_size[1]),
         "spatial_scale": float(spatial_scale)},
    )


def _deform_conv2d(x, offset, weight, mask, bias, *, stride, padding,
                   dilation, groups, deform_groups):
    n, cin, h, w = x.shape
    cout, cin_g, kh, kw = weight.shape
    sh, sw = stride
    ph, pw = padding
    dh, dw = dilation
    out_h = (h + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    out_w = (w + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    # base sampling grid per output position and kernel tap
    base_y = (
        jnp.arange(out_h)[:, None] * sh - ph
        + jnp.arange(kh)[None, :] * dh
    )  # [out_h, kh]
    base_x = (
        jnp.arange(out_w)[:, None] * sw - pw
        + jnp.arange(kw)[None, :] * dw
    )  # [out_w, kw]
    # offset: [N, 2*dg*kh*kw, out_h, out_w] (y then x per tap)
    off = offset.reshape(n, deform_groups, kh * kw, 2, out_h, out_w)
    if mask is not None:
        mk = mask.reshape(n, deform_groups, kh * kw, out_h, out_w)
    cpg = cin // deform_groups  # channels per deform group

    def per_sample(xs, offs, mks):
        # xs [cin,h,w]; offs [dg,kh*kw,2,out_h,out_w]
        def per_dg(feat, o, m):
            # feat [cpg,h,w]; o [kh*kw,2,out_h,out_w]
            def per_tap(t):
                ky, kx = t // kw, t % kw
                yy = base_y[:, ky][:, None] + o[t, 0]
                xx = base_x[:, kx][None, :] + o[t, 1]
                v = _bilinear_gather(feat, yy, xx)  # [cpg,out_h,out_w]
                if m is not None:
                    v = v * m[t]
                return v

            return jax.vmap(per_tap)(jnp.arange(kh * kw))

        taps = jax.vmap(per_dg)(
            xs.reshape(deform_groups, cpg, h, w), offs,
            mks if mks is not None else None,
        )  # [dg, kh*kw, cpg, out_h, out_w]
        # -> channel-major (dg, cpg, tap) to match the weight layout
        return taps.transpose(0, 2, 1, 3, 4).reshape(
            deform_groups * cpg * kh * kw, out_h, out_w
        )

    if mask is not None:
        cols = jax.vmap(per_sample)(x, off, mk)
    else:
        cols = jax.vmap(lambda a, b: per_sample(a, b, None))(x, off)
    # cols [N, cin*kh*kw, out_h, out_w], channel-major (dg, cpg, tap)
    cols = cols.reshape(n, cin, kh * kw, out_h, out_w)
    wmat = weight.reshape(groups, cout // groups, cin_g * kh * kw)
    cols_g = cols.reshape(n, groups, cin_g, kh * kw, out_h, out_w).reshape(
        n, groups, cin_g * kh * kw, out_h * out_w
    )
    out = jnp.einsum("gok,ngkp->ngop", wmat, cols_g).reshape(
        n, cout, out_h, out_w
    )
    if bias is not None:
        out = out + bias[None, :, None, None]
    return out


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    def pair(v):
        return (int(v), int(v)) if isinstance(v, int) else tuple(
            int(a) for a in v
        )

    args = (x, offset, weight, mask, bias)
    return dispatch.apply(
        "deform_conv2d", _deform_conv2d, args,
        {"stride": pair(stride), "padding": pair(padding),
         "dilation": pair(dilation), "groups": int(groups),
         "deform_groups": int(deformable_groups)},
    )


from ..nn.layer.layers import Layer as _Layer  # noqa: E402


class DeformConv2D(_Layer):
    """Layer wrapper over deform_conv2d (paddle.vision.ops.DeformConv2D)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        ks = (
            (kernel_size, kernel_size)
            if isinstance(kernel_size, int) else tuple(kernel_size)
        )
        self._cfg = dict(
            stride=stride, padding=padding, dilation=dilation,
            deformable_groups=deformable_groups, groups=groups,
        )
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, ks[0], ks[1]],
            attr=weight_attr,
        )
        self.bias = (
            None if bias_attr is False else self.create_parameter(
                [out_channels], attr=bias_attr, is_bias=True
            )
        )

    def forward(self, x, offset, mask=None):
        return deform_conv2d(
            x, offset, self.weight, self.bias, mask=mask, **self._cfg
        )


def _box_coder_encode(prior, prior_var, target, *, norm):
    pw = prior[:, 2] - prior[:, 0] + (0.0 if norm else 1.0)
    ph = prior[:, 3] - prior[:, 1] + (0.0 if norm else 1.0)
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph * 0.5
    tw = target[:, 2] - target[:, 0] + (0.0 if norm else 1.0)
    th = target[:, 3] - target[:, 1] + (0.0 if norm else 1.0)
    tcx = target[:, 0] + tw * 0.5
    tcy = target[:, 1] + th * 0.5
    out = jnp.stack([
        (tcx - pcx) / pw, (tcy - pcy) / ph, jnp.log(tw / pw),
        jnp.log(th / ph),
    ], axis=1)
    if prior_var is not None:
        out = out / prior_var
    return out


def _box_coder_decode(prior, prior_var, code, *, norm, axis):
    if axis == 1:
        prior = prior[None, :, :]
        if prior_var is not None:
            prior_var = prior_var[None, :, :]
    else:
        prior = prior[:, None, :]
        if prior_var is not None:
            prior_var = prior_var[:, None, :]
    pw = prior[..., 2] - prior[..., 0] + (0.0 if norm else 1.0)
    ph = prior[..., 3] - prior[..., 1] + (0.0 if norm else 1.0)
    pcx = prior[..., 0] + pw * 0.5
    pcy = prior[..., 1] + ph * 0.5
    if prior_var is not None:
        code = code * prior_var
    cx = code[..., 0] * pw + pcx
    cy = code[..., 1] * ph + pcy
    w = jnp.exp(code[..., 2]) * pw
    h = jnp.exp(code[..., 3]) * ph
    sub = 0.0 if norm else 1.0
    return jnp.stack([
        cx - w * 0.5, cy - h * 0.5, cx + w * 0.5 - sub, cy + h * 0.5 - sub,
    ], axis=-1)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, axis=0,
              name=None):
    pv = prior_box_var
    if pv is not None and not isinstance(pv, Tensor):
        pv = Tensor(jnp.asarray(pv, jnp.float32))
    if code_type == "encode_center_size":
        return dispatch.apply(
            "box_coder_encode", _box_coder_encode,
            (prior_box, pv, target_box), {"norm": bool(box_normalized)},
        )
    if code_type == "decode_center_size":
        return dispatch.apply(
            "box_coder_decode", _box_coder_decode,
            (prior_box, pv, target_box),
            {"norm": bool(box_normalized), "axis": int(axis)},
        )
    raise ValueError(f"box_coder: unknown code_type {code_type!r}")
