"""DenseNet (python/paddle/vision/models/densenet.py parity —
unverified): dense blocks with channel concat, transition down-samples."""
from __future__ import annotations

from ... import nn
from ...ops.manipulation import concat, flatten


class DenseLayer(nn.Layer):
    def __init__(self, in_c, growth_rate, bn_size, dropout):
        super().__init__()
        self.bn1 = nn.BatchNorm2D(in_c)
        self.relu = nn.ReLU()
        self.conv1 = nn.Conv2D(in_c, bn_size * growth_rate, 1,
                               bias_attr=False)
        self.bn2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3,
                               padding=1, bias_attr=False)
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        out = self.conv1(self.relu(self.bn1(x)))
        out = self.conv2(self.relu(self.bn2(out)))
        if self.dropout is not None:
            out = self.dropout(out)
        return concat([x, out], axis=1)


class DenseBlock(nn.Sequential):
    def __init__(self, num_layers, in_c, growth_rate, bn_size, dropout):
        super().__init__(*[
            DenseLayer(in_c + i * growth_rate, growth_rate, bn_size, dropout)
            for i in range(num_layers)
        ])


class Transition(nn.Sequential):
    def __init__(self, in_c, out_c):
        super().__init__(
            nn.BatchNorm2D(in_c),
            nn.ReLU(),
            nn.Conv2D(in_c, out_c, 1, bias_attr=False),
            nn.AvgPool2D(2, stride=2),
        )


_CFG = {
    121: (6, 12, 24, 16),
    161: (6, 12, 36, 24),
    169: (6, 12, 32, 32),
    201: (6, 12, 48, 32),
    264: (6, 12, 64, 48),
}


class DenseNet(nn.Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        block_cfg = _CFG[layers]
        growth_rate = 48 if layers == 161 else 32
        init_c = 96 if layers == 161 else 64
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            nn.Conv2D(3, init_c, 7, stride=2, padding=3, bias_attr=False),
            nn.BatchNorm2D(init_c),
            nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1),
        )
        blocks = []
        c = init_c
        for i, n in enumerate(block_cfg):
            blocks.append(DenseBlock(n, c, growth_rate, bn_size, dropout))
            c = c + n * growth_rate
            if i != len(block_cfg) - 1:
                blocks.append(Transition(c, c // 2))
                c = c // 2
        self.blocks = nn.Sequential(*blocks)
        self.bn_final = nn.BatchNorm2D(c)
        self.relu = nn.ReLU()
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(c, num_classes)

    def forward(self, x):
        x = self.relu(self.bn_final(self.blocks(self.stem(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(flatten(x, 1))
        return x


def _densenet(layers, **kwargs):
    return DenseNet(layers=layers, **kwargs)


def densenet121(pretrained=False, **kwargs):
    return _densenet(121, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _densenet(161, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _densenet(169, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _densenet(201, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return _densenet(264, **kwargs)
