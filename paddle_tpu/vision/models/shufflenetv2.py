"""ShuffleNetV2 (python/paddle/vision/models/shufflenetv2.py parity —
unverified): channel-split residual units with channel shuffle."""
from __future__ import annotations

from ... import nn
from ...nn import functional as F
from ...ops.manipulation import concat, flatten, split


class ConvBNAct(nn.Sequential):
    def __init__(self, in_c, out_c, kernel, stride, groups=1, act="relu"):
        layers = [
            nn.Conv2D(in_c, out_c, kernel, stride=stride,
                      padding=(kernel - 1) // 2, groups=groups,
                      bias_attr=False),
            nn.BatchNorm2D(out_c),
        ]
        if act == "relu":
            layers.append(nn.ReLU())
        elif act == "swish":
            layers.append(nn.Swish())
        super().__init__(*layers)


class InvertedResidualUnit(nn.Layer):
    """stride-1 unit: split channels, transform one half, shuffle."""

    def __init__(self, channels, act):
        super().__init__()
        half = channels // 2
        self.branch = nn.Sequential(
            ConvBNAct(half, half, 1, 1, act=act),
            ConvBNAct(half, half, 3, 1, groups=half, act=None),
            ConvBNAct(half, half, 1, 1, act=act),
        )

    def forward(self, x):
        x1, x2 = split(x, 2, axis=1)
        out = concat([x1, self.branch(x2)], axis=1)
        return F.channel_shuffle(out, 2)


class InvertedResidualDS(nn.Layer):
    """stride-2 down-sampling unit: both branches transformed."""

    def __init__(self, in_c, out_c, act):
        super().__init__()
        half = out_c // 2
        self.branch1 = nn.Sequential(
            ConvBNAct(in_c, in_c, 3, 2, groups=in_c, act=None),
            ConvBNAct(in_c, half, 1, 1, act=act),
        )
        self.branch2 = nn.Sequential(
            ConvBNAct(in_c, half, 1, 1, act=act),
            ConvBNAct(half, half, 3, 2, groups=half, act=None),
            ConvBNAct(half, half, 1, 1, act=act),
        )

    def forward(self, x):
        out = concat([self.branch1(x), self.branch2(x)], axis=1)
        return F.channel_shuffle(out, 2)


_STAGE_REPEATS = (4, 8, 4)
_STAGE_OUT = {
    0.25: (24, 24, 48, 96, 512),
    0.33: (24, 32, 64, 128, 512),
    0.5: (24, 48, 96, 192, 1024),
    1.0: (24, 116, 232, 464, 1024),
    1.5: (24, 176, 352, 704, 1024),
    2.0: (24, 244, 488, 976, 2048),
}


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        chans = _STAGE_OUT[scale]
        self.stem = nn.Sequential(
            ConvBNAct(3, chans[0], 3, 2, act=act),
            nn.MaxPool2D(3, stride=2, padding=1),
        )
        stages = []
        in_c = chans[0]
        for stage_i, repeats in enumerate(_STAGE_REPEATS):
            out_c = chans[stage_i + 1]
            stages.append(InvertedResidualDS(in_c, out_c, act))
            for _ in range(repeats - 1):
                stages.append(InvertedResidualUnit(out_c, act))
            in_c = out_c
        self.stages = nn.Sequential(*stages)
        self.last_conv = ConvBNAct(in_c, chans[-1], 1, 1, act=act)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(chans[-1], num_classes)

    def forward(self, x):
        x = self.last_conv(self.stages(self.stem(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(flatten(x, 1))
        return x


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.25, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.33, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=0.5, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.0, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.5, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=2.0, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return ShuffleNetV2(scale=1.0, act="swish", **kwargs)
