"""MobileNetV1 (python/paddle/vision/models/mobilenetv1.py parity —
unverified): depthwise-separable conv stacks. Depthwise convs lower to
XLA grouped convolutions, which TPU handles natively."""
from __future__ import annotations

from ... import nn


class ConvBNLayer(nn.Sequential):
    def __init__(self, in_c, out_c, kernel, stride, padding, groups=1):
        super().__init__(
            nn.Conv2D(in_c, out_c, kernel, stride=stride, padding=padding,
                      groups=groups, bias_attr=False),
            nn.BatchNorm2D(out_c),
            nn.ReLU(),
        )


class DepthwiseSeparable(nn.Sequential):
    def __init__(self, in_c, mid_c, out_c, stride, scale):
        super().__init__(
            ConvBNLayer(int(in_c * scale), int(mid_c * scale), 3, stride, 1,
                        groups=int(in_c * scale)),
            ConvBNLayer(int(mid_c * scale), int(out_c * scale), 1, 1, 0),
        )


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool

        cfg = [
            # in, mid, out, stride
            (32, 32, 64, 1),
            (64, 64, 128, 2),
            (128, 128, 128, 1),
            (128, 128, 256, 2),
            (256, 256, 256, 1),
            (256, 256, 512, 2),
            *[(512, 512, 512, 1)] * 5,
            (512, 512, 1024, 2),
            (1024, 1024, 1024, 1),
        ]
        self.conv1 = ConvBNLayer(3, int(32 * scale), 3, 2, 1)
        self.blocks = nn.Sequential(*[
            DepthwiseSeparable(i, m, o, s, scale) for i, m, o, s in cfg
        ])
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(int(1024 * scale), num_classes)

    def forward(self, x):
        x = self.blocks(self.conv1(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ...ops.manipulation import flatten

            x = self.fc(flatten(x, 1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)
