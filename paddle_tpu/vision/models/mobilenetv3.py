"""MobileNetV3 small/large (python/paddle/vision/models/mobilenetv3.py
parity — unverified): inverted residuals + squeeze-excite, hardswish."""
from __future__ import annotations

from ... import nn
from .mobilenetv2 import _make_divisible


class SqueezeExcite(nn.Layer):
    def __init__(self, channels, reduction=4):
        super().__init__()
        squeeze = _make_divisible(channels // reduction)
        self.pool = nn.AdaptiveAvgPool2D((1, 1))
        self.fc1 = nn.Conv2D(channels, squeeze, 1)
        self.relu = nn.ReLU()
        self.fc2 = nn.Conv2D(squeeze, channels, 1)
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class InvertedResidualV3(nn.Layer):
    def __init__(self, in_c, exp_c, out_c, kernel, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        act_layer = nn.Hardswish if act == "hardswish" else nn.ReLU
        layers = []
        if exp_c != in_c:
            layers += [
                nn.Conv2D(in_c, exp_c, 1, bias_attr=False),
                nn.BatchNorm2D(exp_c),
                act_layer(),
            ]
        layers += [
            nn.Conv2D(exp_c, exp_c, kernel, stride=stride,
                      padding=(kernel - 1) // 2, groups=exp_c,
                      bias_attr=False),
            nn.BatchNorm2D(exp_c),
            act_layer(),  # reference order: conv -> BN -> act -> SE
        ]
        if use_se:
            layers.append(SqueezeExcite(exp_c))
        layers += [
            nn.Conv2D(exp_c, out_c, 1, bias_attr=False),
            nn.BatchNorm2D(out_c),
        ]
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


# (kernel, exp, out, use_se, act, stride) per reference config tables
_LARGE_CFG = [
    (3, 16, 16, False, "relu", 1),
    (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1),
    (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1),
    (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2),
    (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1),
    (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2),
    (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1),
]
_SMALL_CFG = [
    (3, 16, 16, True, "relu", 2),
    (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1),
    (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1),
    (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1),
    (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2),
    (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1),
]


class MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_exp, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        in_c = _make_divisible(16 * scale)
        self.conv = nn.Sequential(
            nn.Conv2D(3, in_c, 3, stride=2, padding=1, bias_attr=False),
            nn.BatchNorm2D(in_c),
            nn.Hardswish(),
        )
        blocks = []
        for k, exp, out, se, act, s in cfg:
            exp_c = _make_divisible(exp * scale)
            out_c = _make_divisible(out * scale)
            blocks.append(
                InvertedResidualV3(in_c, exp_c, out_c, k, s, se, act)
            )
            in_c = out_c
        self.blocks = nn.Sequential(*blocks)
        last_c = _make_divisible(last_exp * scale)
        self.lastconv = nn.Sequential(
            nn.Conv2D(in_c, last_c, 1, bias_attr=False),
            nn.BatchNorm2D(last_c),
            nn.Hardswish(),
        )
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            head_c = 1280 if last_exp == 960 else 1024
            self.classifier = nn.Sequential(
                nn.Linear(last_c, head_c),
                nn.Hardswish(),
                nn.Dropout(0.2),
                nn.Linear(head_c, num_classes),
            )

    def forward(self, x):
        x = self.lastconv(self.blocks(self.conv(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ...ops.manipulation import flatten

            x = self.classifier(flatten(x, 1))
        return x


class MobileNetV3Large(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_LARGE_CFG, 960, scale, num_classes, with_pool)


class MobileNetV3Small(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_SMALL_CFG, 576, scale, num_classes, with_pool)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Large(scale=scale, **kwargs)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV3Small(scale=scale, **kwargs)
