"""paddle.vision parity (python/paddle/vision/ — unverified)."""
from . import datasets, models, ops, transforms  # noqa: F401
from .models import LeNet  # noqa: F401
