"""Vision transforms (numpy-native, PIL-tolerant).

Reference parity: python/paddle/vision/transforms/ (unverified, mount
empty). Transforms are host-side preprocessing: they stay in numpy (PIL
accepted and converted) so the DataLoader worker pool can run them off the
accelerator's critical path; only the final batch crosses to device.
"""
from __future__ import annotations

import numbers
import random

import numpy as np


def _to_numpy(img):
    if isinstance(img, np.ndarray):
        return img
    try:  # PIL image
        return np.asarray(img)
    except Exception:
        raise TypeError(f"unsupported image type {type(img)}")


def _resize_np(img, size):
    """Nearest+bilinear resize via jax.image on host numpy (HWC or HW)."""
    import jax

    h, w = (size, size) if isinstance(size, int) else size
    arr = _to_numpy(img)
    squeeze = arr.ndim == 2
    if squeeze:
        arr = arr[:, :, None]
    out = np.asarray(
        jax.image.resize(
            arr.astype(np.float32), (h, w, arr.shape[2]), method="linear"
        )
    )
    if np.issubdtype(_to_numpy(img).dtype, np.integer):
        out = np.clip(np.round(out), 0, 255).astype(_to_numpy(img).dtype)
    return out[:, :, 0] if squeeze else out


class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, img):
        return self._apply_image(img)

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


def to_tensor(img, data_format="CHW"):
    arr = _to_numpy(img)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if np.issubdtype(arr.dtype, np.integer):
        arr = arr.astype(np.float32) / 255.0
    else:
        arr = arr.astype(np.float32)
    if data_format == "CHW":
        arr = np.transpose(arr, (2, 0, 1))
    return arr


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        super().__init__(keys)
        self.mean = np.asarray(
            mean if isinstance(mean, (list, tuple)) else [mean], np.float32
        )
        self.std = np.asarray(
            std if isinstance(std, (list, tuple)) else [std], np.float32
        )
        self.data_format = data_format

    def _apply_image(self, img):
        arr = _to_numpy(img).astype(np.float32)
        if self.data_format == "CHW":
            shape = (-1, 1, 1)
        else:
            shape = (1, 1, -1)
        return (arr - self.mean.reshape(shape)) / self.std.reshape(shape)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        arr = _to_numpy(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return np.transpose(arr, self.order)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        arr = _to_numpy(img)
        if isinstance(self.size, int):
            h, w = arr.shape[:2]
            if h < w:
                size = (self.size, int(w * self.size / h))
            else:
                size = (int(h * self.size / w), self.size)
        else:
            size = self.size
        return _resize_np(arr, size)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else size

    def _apply_image(self, img):
        arr = _to_numpy(img)
        th, tw = self.size
        h, w = arr.shape[:2]
        i = max(0, (h - th) // 2)
        j = max(0, (w - tw) // 2)
        return arr[i : i + th, j : j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else size
        self.padding = padding

    def _apply_image(self, img):
        arr = _to_numpy(img)
        if self.padding:
            p = self.padding
            if isinstance(p, numbers.Number):
                p = (p, p, p, p)
            pads = [(p[1], p[3]), (p[0], p[2])] + [(0, 0)] * (arr.ndim - 2)
            arr = np.pad(arr, pads)
        th, tw = self.size
        h, w = arr.shape[:2]
        i = random.randint(0, max(0, h - th))
        j = random.randint(0, max(0, w - tw))
        return arr[i : i + th, j : j + tw]


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4, 4.0 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else size
        self.scale = scale
        self.ratio = ratio

    def _apply_image(self, img):
        arr = _to_numpy(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = random.uniform(*self.ratio)
            cw = int(round((target * ar) ** 0.5))
            ch = int(round((target / ar) ** 0.5))
            if cw <= w and ch <= h:
                i = random.randint(0, h - ch)
                j = random.randint(0, w - cw)
                return _resize_np(arr[i : i + ch, j : j + cw], self.size)
        return _resize_np(arr, self.size)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return _to_numpy(img)[:, ::-1].copy()
        return _to_numpy(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return _to_numpy(img)[::-1].copy()
        return _to_numpy(img)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        p = padding
        if isinstance(p, numbers.Number):
            p = (p, p, p, p)
        elif len(p) == 2:
            p = (p[0], p[1], p[0], p[1])
        self.padding = p
        self.fill = fill

    def _apply_image(self, img):
        arr = _to_numpy(img)
        p = self.padding
        pads = [(p[1], p[3]), (p[0], p[2])] + [(0, 0)] * (arr.ndim - 2)
        return np.pad(arr, pads, constant_values=self.fill)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return _to_numpy(img)
        arr = _to_numpy(img).astype(np.float32)
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return np.clip(arr * factor, 0, 255).astype(_to_numpy(img).dtype)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return _to_numpy(img)
        arr = _to_numpy(img).astype(np.float32)
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        mean = arr.mean()
        return np.clip((arr - mean) * factor + mean, 0, 255).astype(
            _to_numpy(img).dtype
        )
