"""Deterministic fault-injection harness, shared by serving AND training.

Production failure modes — torn checkpoint directories, a process
killed mid-weight-swap, a KV-transfer socket dropping mid-frame, a
checkpoint writer thread that wedges, a NaN landing in the loss at step
80_000, a rank SIGKILLed mid-run — are all timing-dependent, which is
why they historically only had subprocess-SIGKILL smoke coverage. This
module makes them DETERMINISTIC so tier-1 tests can drive the
retry/timeout/rollback and graceful-degradation paths directly:

- :class:`ChaosMonkey` — a scripted fault plan. Production seams call
  :func:`poke` with a site name (``"kv.send_frame"``,
  ``"reload.apply"``, ``"train.step_begin"``, ...); when a monkey is
  installed and a plan is armed for that site, the poke raises the
  armed exception (or runs a callback) on exactly the scheduled
  invocations. Value seams call :func:`poke_value` — an armed callback
  may return a REPLACEMENT for the observed value (the training NaN
  injection: ``monkey.on("train.loss", lambda value, **_:
  float("nan"), after=k-1)``). With no monkey installed a poke is one
  module-attribute read — the production cost is nil and the seams
  stay in the real code path, not in test monkeypatches.
- :class:`ChaosClock` — a manual-advance monotonic clock. Every
  timeout/cooldown/deadline surface in the stack takes ``clock=``
  (engines, scheduler, router, RemotePrefillClient, CheckpointManager
  policy, the training watchdog), so tests step time forward instead
  of sleeping.
- checkpoint corruption helpers — :func:`tear_checkpoint` produces the
  torn-directory shapes the commit/verify protocol must catch, picking
  its victim file deterministically.
- writer-thread faults — :func:`slow_serializer` /
  :func:`wedged_serializer` wrap a ``CheckpointManager``'s serialize
  seam so backpressure and drain-timeout paths run on demand.

Instrumented sites (grep ``chaos.poke`` / ``_chaos.poke`` for the live
list):

- serving: ``kv.send_frame`` / ``kv.recv_frame`` (the KV-transfer
  wire), ``reload.prepare`` / ``reload.apply`` (the live weight swap —
  arming ``reload.apply`` is the deterministic "kill mid-swap").
- training (``jit.trainer.CompiledTrainStep``):
  ``train.step_begin`` (poked with ``step=`` before each dispatch — a
  blocking callback is the deterministic wedged step, an ``os._exit``
  callback the deterministic dead rank) and ``train.loss`` (a VALUE
  seam poked with the step's loss device ref — returning
  ``float("nan")`` from the callback is the deterministic anomaly the
  sentinel's rollback/skip ladder must recover from).

``paddle_tpu.serving.chaos`` re-exports this module unchanged (the
harness grew up there; serving callers keep their import path).
"""
from __future__ import annotations

import contextlib
import os
import threading


class ChaosError(RuntimeError):
    """Default exception an armed fault raises at its site."""


class ChaosClock:
    """Manual-advance monotonic clock (drop-in for ``time.monotonic``).

    ``clock()`` returns the current value; ``advance(dt)`` moves it;
    ``sleep(dt)`` advances without blocking (hand it to code that
    sleeps so waits become deterministic)."""

    def __init__(self, start=1000.0):
        self._t = float(start)
        self._lock = threading.Lock()

    def __call__(self):
        with self._lock:
            return self._t

    def advance(self, dt):
        with self._lock:
            self._t += float(dt)
            return self._t

    # alias so the clock can stand in for time.sleep in injected code
    def sleep(self, dt):
        self.advance(dt)


class _Plan:
    __slots__ = ("after", "times", "exc", "callback")

    def __init__(self, after, times, exc, callback):
        self.after = int(after)
        self.times = times if times is None else int(times)
        self.exc = exc
        self.callback = callback


class ChaosMonkey:
    """A scripted set of faults keyed by site name.

    ``fail(site)`` arms an exception; ``on(site, fn)`` arms a callback
    (``fn(**ctx)`` — raise from it to fault, return to observe; at a
    :func:`poke_value` site the callback receives ``value=`` and a
    non-None return REPLACES the value). ``after=N`` skips the first N
    pokes; ``times=K`` fires on the next K pokes then disarms
    (``times=None`` = every poke). ``fired(site)`` counts actual
    fires, ``poked(site)`` all pokes — tests assert on these instead
    of sleeping and hoping."""

    def __init__(self):
        self._plans = {}
        self._pokes = {}
        self._fires = {}
        self._lock = threading.Lock()

    def fail(self, site, *, times=1, after=0, exc=None):
        self._plans[site] = _Plan(
            after, times, exc or ChaosError(f"chaos: {site}"), None
        )
        return self

    def on(self, site, callback, *, times=None, after=0):
        self._plans[site] = _Plan(after, times, None, callback)
        return self

    def disarm(self, site):
        self._plans.pop(site, None)

    def poked(self, site):
        return self._pokes.get(site, 0)

    def fired(self, site):
        return self._fires.get(site, 0)

    def _schedule(self, site):
        """One poke's bookkeeping; returns the armed (exc, callback)
        when this poke fires, else (None, None)."""
        with self._lock:
            self._pokes[site] = self._pokes.get(site, 0) + 1
            plan = self._plans.get(site)
            if plan is None:
                return None, None
            if plan.after > 0:
                plan.after -= 1
                return None, None
            if plan.times is not None:
                if plan.times <= 0:
                    return None, None
                plan.times -= 1
            self._fires[site] = self._fires.get(site, 0) + 1
            return plan.exc, plan.callback

    def poke(self, site, **ctx):
        exc, callback = self._schedule(site)
        if callback is not None:
            callback(**ctx)
        elif exc is not None:
            raise exc

    def poke_value(self, site, value, **ctx):
        """Value seam: an armed callback gets ``value=`` and may return
        a replacement (None = observe only); an armed exception raises
        as usual. Unarmed pokes return ``value`` untouched."""
        exc, callback = self._schedule(site)
        if callback is not None:
            out = callback(value=value, **ctx)
            return value if out is None else out
        if exc is not None:
            raise exc
        return value


# one optional process-wide monkey; poke() is a no-op attribute read
# when none is installed, so the production seams cost nothing
_ACTIVE = None


def install(monkey):
    global _ACTIVE
    _ACTIVE = monkey
    return monkey


def uninstall():
    global _ACTIVE
    _ACTIVE = None


def active():
    return _ACTIVE


def poke(site, **ctx):
    """Production seam: fault here when a monkey armed this site."""
    m = _ACTIVE
    if m is not None:
        m.poke(site, **ctx)


def poke_value(site, value, **ctx):
    """Production VALUE seam: an armed monkey may observe or replace
    ``value`` (see :meth:`ChaosMonkey.poke_value`); with no monkey the
    value passes through untouched."""
    m = _ACTIVE
    if m is None:
        return value
    return m.poke_value(site, value, **ctx)


@contextlib.contextmanager
def chaos(monkey=None):
    """``with chaos() as monkey: monkey.fail("reload.apply"); ...`` —
    installs (a fresh) monkey for the block, always uninstalls."""
    m = monkey or ChaosMonkey()
    prev = _ACTIVE
    install(m)
    try:
        yield m
    finally:
        install(prev) if prev is not None else uninstall()


# ------------------------------------------------- checkpoint corruption
def tear_checkpoint(step_dir, mode="truncate_shard"):
    """Deterministically damage a committed checkpoint directory the way
    real crashes/bit-rot do. Returns the damaged file's path (or the
    removed one). Modes: ``truncate_shard`` (torn write),
    ``bitflip_shard`` (silent corruption), ``delete_shard`` (lost
    file), ``delete_manifest`` (commit marker gone). The victim shard
    is the first ``.npy`` in sorted order — deterministic, so a test's
    failure reproduces."""
    if mode == "delete_manifest":
        p = os.path.join(step_dir, "manifest.json")
        os.remove(p)
        return p
    shards = sorted(
        f for f in os.listdir(step_dir) if f.endswith(".npy")
    )
    if not shards:
        raise ValueError(f"no shard files under {step_dir}")
    p = os.path.join(step_dir, shards[0])
    if mode == "delete_shard":
        os.remove(p)
    elif mode == "truncate_shard":
        size = os.path.getsize(p)
        with open(p, "r+b") as f:
            f.truncate(max(size // 2, 1))
    elif mode == "bitflip_shard":
        with open(p, "r+b") as f:
            f.seek(-1, os.SEEK_END)
            last = f.read(1)
            f.seek(-1, os.SEEK_END)
            f.write(bytes([last[0] ^ 0xFF]))
    else:
        raise ValueError(f"unknown tear mode {mode!r}")
    return p


# --------------------------------------------------- writer-thread faults
def slow_serializer(manager, seconds, sleep=None):
    """Wrap ``manager``'s serialize seam with a fixed delay — drives the
    async-saver backpressure path. Returns an ``undo()`` callable."""
    import time as _time

    sleep = sleep or _time.sleep
    inner = manager._serialize

    def slowed(state, path):
        sleep(float(seconds))
        return inner(state, path)

    manager._serialize = slowed
    return lambda: setattr(manager, "_serialize", inner)


def wedged_serializer(manager, release):
    """Wrap the serialize seam so the writer BLOCKS until ``release``
    (a ``threading.Event``) is set — the wedged-writer scenario behind
    emergency-save grace timeouts. Returns an ``undo()`` callable."""
    inner = manager._serialize

    def wedged(state, path):
        release.wait()
        return inner(state, path)

    manager._serialize = wedged
    return lambda: setattr(manager, "_serialize", inner)
