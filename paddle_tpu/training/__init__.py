"""paddle_tpu.training — the resilient training runtime.

The training-side twin of the serving tier's zero-downtime ops: an
anomaly sentinel with a skip/rollback/abort policy ladder, a
hang/straggler watchdog, and the loop helper that makes
rollback-and-replay bit-identical to an uninterrupted run. See
:mod:`paddle_tpu.training.resilience`.
"""
from __future__ import annotations

from .resilience import (
    Action,
    AnomalySentinel,
    RollbackAndReplay,
    SentinelPolicy,
    TrainingAborted,
    TrainWatchdog,
    run_resilient,
)

__all__ = [
    "Action", "AnomalySentinel", "RollbackAndReplay", "SentinelPolicy",
    "TrainingAborted", "TrainWatchdog", "run_resilient",
]
