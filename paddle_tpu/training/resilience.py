"""Resilient training runtime: anomaly sentinel, watchdog, replay loop.

A multi-hour training job should SURVIVE bad steps, wedged collectives,
and dead workers — the reference's Fleet stack treats them as seconds
of rollback, not a lost run. This module is the training-side twin of
the serving tier's zero-downtime ops (PR 11), built on the same
discipline: every failure mode has a deterministic chaos seam, every
response is counted, and nothing here ever adds a device sync to the
hot loop.

Three layers:

- :class:`AnomalySentinel` — attached to a
  ``jit.trainer.CompiledTrainStep`` (``trainer.attach_sentinel``) or
  ``Model.fit(sentinel=)``. Each step's loss rides along as a DEVICE
  REF; the sentinel only inspects refs whose ``is_ready()`` reports
  done (the flight-recorder/StepMeter lazy-value discipline), so
  detection never blocks dispatch. On a NaN/inf loss or a configurable
  loss-spike it walks a policy ladder:

  * **skip-step** — restore the one pre-step on-device snapshot the
    sentinel keeps (params/optimizer state/buffers/fp8 histories/step
    count; ``jnp.copy`` per leaf, donation-immune, no host sync), drop
    the offending batch, and keep going. The RNG stream deliberately
    keeps advancing — step k+1 uses the key it would have used anyway,
    so a skipped batch never reshuffles every later key.
  * **rollback** — drain the checkpoint writer, restore the last
    COMMITTED checkpoint via ``CheckpointManager.restore_or_init``
    (params, optimizer moments, RNG, step count, and registered extra
    state — the fp8 amax histories persist via
    ``register_extra_state``, so an AMP O3 rollback is bit-identical),
    then raise :class:`RollbackAndReplay` so the driving loop rewinds
    its DATA CURSOR and replays the same batches: the recovered
    trajectory exactly equals an uninterrupted run.
  * **abort** — dump a flight-recorder bundle (nonblocking
    materialization: the bundle never deadlocks on the dying step's
    own in-flight refs) and raise :class:`TrainingAborted`.

  Every response is counted in
  ``paddle_training_anomaly_total{kind,action}`` and recorded as a
  flight-ring event.

- :class:`TrainWatchdog` — a monitor thread (injectable clock) that
  fires when the dispatch-to-dispatch gap exceeds ``stall_seconds``,
  EXCLUDING checkpoint-blocked time (it listens on the StepMeter's
  ``note_blocked`` seam, so an emergency save is never misread as a
  hang), plus per-rank heartbeat files (mtime = last dispatch) so a
  straggling or wedged PEER rank fires too. A fire bumps
  ``paddle_training_watchdog_fires_total{kind}``, attributes the
  coming run break (``StepMeter.note_wedged``), and dumps a flight
  bundle BEFORE the job dies silently.

- :func:`run_resilient` — the replay-capable driver loop: the step
  index is the data cursor, ``batch_fn(step)`` must be deterministic
  per index (the usual seeded-pipeline contract), and
  :class:`RollbackAndReplay` rewinds it.

Chaos seams (``paddle_tpu.chaos``): ``train.loss`` (value seam — a
callback returning ``float("nan")`` is the deterministic anomaly) and
``train.step_begin`` (a blocking callback is the deterministic wedged
step; an ``os._exit`` callback the deterministic dead rank the elastic
supervisor must recover). ``tools/train_chaos_smoke.py`` drives all
three recovery paths as subprocess gates.
"""
from __future__ import annotations

import os
import statistics
import threading
import time

import numpy as np

from ..observability import Counter, get_registry
from ..observability.registry import value_is_ready

KIND_NANINF = "naninf"
KIND_LOSS_SPIKE = "loss_spike"

ACTION_SKIP = "skip"
ACTION_ROLLBACK = "rollback"
ACTION_ABORT = "abort"


class RollbackAndReplay(RuntimeError):
    """Control-flow: the sentinel restored the last committed
    checkpoint; the driving loop must rewind its data cursor to
    ``action.resume_step`` and replay. :func:`run_resilient` and
    ``Model.fit`` handle it; custom loops must too (or run with a
    policy that never rolls back)."""

    def __init__(self, action):
        self.action = action
        super().__init__(
            f"anomaly ({action.kind}) at step {action.step}: rolled "
            f"back, replay from step {action.resume_step}"
        )


class TrainingAborted(RuntimeError):
    """The policy ladder's last rung: the anomaly was not recoverable
    (or recovery budget exhausted); a flight bundle was dumped."""

    def __init__(self, action, bundle_path=None):
        self.action = action
        self.bundle_path = bundle_path
        super().__init__(
            f"training aborted: {action.kind} at step {action.step}"
            + (f" (flight bundle: {bundle_path})" if bundle_path else "")
        )


class Action:
    """One sentinel response."""

    __slots__ = ("kind", "action", "step", "value", "resume_step",
                 "dropped_steps")

    def __init__(self, kind, action, step, value=None, resume_step=None,
                 dropped_steps=0):
        self.kind = kind
        self.action = action
        self.step = int(step)
        self.value = value
        self.resume_step = resume_step
        self.dropped_steps = int(dropped_steps)

    def __repr__(self):
        return (f"Action({self.kind}, {self.action}, step={self.step}, "
                f"resume_step={self.resume_step})")


class SentinelPolicy:
    """What counts as an anomaly, and what to do about it.

    ``nan_action`` / ``spike_action`` pick the ladder entry point per
    kind (``"skip" | "rollback" | "abort"``); the ladder always
    escalates downward when a rung is unavailable (no snapshot → no
    skip; no manager or no committed checkpoint → no rollback) or its
    budget (``max_skips`` / ``max_rollbacks``, per run) is spent.

    Spike detection: a loss is a spike when a window of
    ``spike_window`` healthy losses has at least ``min_history``
    entries and the new loss exceeds ``spike_factor`` x the window
    median (scale-free), or exceeds the absolute ``loss_ceiling`` when
    one is set. NaN/inf is always ``naninf`` regardless of history.

    Cost note: choosing ``"skip"`` for EITHER kind turns on the
    sentinel's pre-step on-device snapshot — a full copy of params +
    optimizer state + buffers refreshed every step. That is the price
    of undoing one step in place; at 7B scale it roughly doubles the
    optimizer-state footprint, which is why both actions default to
    rollback (no snapshot, no extra HBM) and skip is opt-in.
    """

    def __init__(self, nan_action=ACTION_ROLLBACK,
                 spike_action=ACTION_ROLLBACK, *, spike_window=32,
                 spike_factor=10.0, min_history=4, loss_ceiling=None,
                 max_skips=3, max_rollbacks=2):
        for a in (nan_action, spike_action):
            if a not in (ACTION_SKIP, ACTION_ROLLBACK, ACTION_ABORT):
                raise ValueError(f"unknown sentinel action {a!r}")
        self.nan_action = nan_action
        self.spike_action = spike_action
        self.spike_window = int(spike_window)
        self.spike_factor = float(spike_factor)
        self.min_history = int(min_history)
        self.loss_ceiling = (
            float(loss_ceiling) if loss_ceiling is not None else None
        )
        self.max_skips = int(max_skips)
        self.max_rollbacks = int(max_rollbacks)

    def action_for(self, kind):
        return self.nan_action if kind == KIND_NANINF \
            else self.spike_action

    def skip_enabled(self):
        return ACTION_SKIP in (self.nan_action, self.spike_action)


class AnomalySentinel:
    """Watch per-step losses; respond by the policy ladder.

    Wiring: ``trainer.attach_sentinel(sentinel)`` (the trainer calls
    :meth:`before_step` / :meth:`after_step` around each optimizer
    step), or ``Model.fit(sentinel=sentinel)``. ``manager`` is the
    ``checkpoint.CheckpointManager`` the rollback rung restores from —
    without one, rollback escalates to abort.

    ``sync=True`` blocks on each step's loss ref before the next step
    dispatches — detection latency becomes exactly zero at the cost of
    per-step device sync. The default (``sync=False``) checks only
    READY refs: on real accelerators detection lags dispatch by the
    in-flight window, so a skip may drop the couple of steps that
    dispatched behind the bad one (counted in
    ``Action.dropped_steps``); a rollback first QUARANTINES any
    generation committed at/after the anomalous step (the detection
    lag can let one land), so the restore always predates the anomaly.
    """

    def __init__(self, policy=None, manager=None, *, sync=False,
                 registry=None, recorder=None):
        self.policy = policy or SentinelPolicy()
        self.manager = manager
        self.sync = bool(sync)
        self._recorder = recorder
        self._trainer = None
        self._lock = threading.Lock()
        self._pending = []       # [(step, loss_ref)] oldest first
        self._history = []       # recent healthy losses (spike window)
        self._snapshot = None    # pre-step on-device state (skip rung)
        self._snapshot_step = None
        self.skips_taken = 0
        self.rollbacks_taken = 0
        self.last_action = None
        self.anomalies = Counter(
            "training_anomalies",
            prom_name="paddle_training_anomaly_total",
            help="train-loop anomalies detected by the sentinel, by "
                 "kind (naninf|loss_spike) and response "
                 "(skip|rollback|abort)",
        )
        (registry or get_registry()).register_all([self.anomalies])
        from ..analysis.lock_sentinel import maybe_instrument

        maybe_instrument(self)

    # ------------------------------------------------------------- wiring
    @property
    def recorder(self):
        if self._recorder is not None:
            return self._recorder
        from ..observability import get_flight_recorder

        return get_flight_recorder()

    def bind(self, trainer):
        self._trainer = trainer
        return self

    def attach(self, trainer):
        """Convenience: ``sentinel.attach(trainer)`` ==
        ``trainer.attach_sentinel(sentinel)``."""
        trainer.attach_sentinel(self)
        return trainer

    def _note(self, event, **info):
        try:
            self.recorder.note(event, **info)
        except Exception:
            pass

    # ----------------------------------------------------------- trainer hooks
    def before_step(self, step):
        """Called by the trainer BEFORE it gathers/donates state for
        ``step``. Refreshes the skip rung's pre-step snapshot — but
        only while no earlier loss is still unverified, so the
        snapshot always predates the OLDEST step that could turn out
        bad."""
        if not self.policy.skip_enabled() or self._trainer is None:
            return
        with self._lock:
            if self._pending:
                return
        self._snapshot = self._trainer._memory_snapshot()
        self._snapshot_step = int(step) - 1

    def after_step(self, step, loss_ref):
        """Called by the trainer after write-back (and before its
        checkpoint hook). Registers the loss ref and runs a check;
        returns the Action taken for a skip (the trainer must not
        checkpoint a step that was just undone), raises for
        rollback/abort."""
        with self._lock:
            self._pending.append((int(step), loss_ref))
        return self.check()

    # -------------------------------------------------------------- checking
    def check(self, block=None):
        """Inspect pending loss refs (oldest first). ``block=None``
        follows the sentinel's ``sync`` setting; ``block=False`` only
        looks at refs that are already ready. Returns the last Action
        taken this call (or None); raises RollbackAndReplay /
        TrainingAborted per the ladder."""
        block = self.sync if block is None else bool(block)
        taken = None
        while True:
            with self._lock:
                if not self._pending:
                    break
                step, ref = self._pending[0]
            if not block and not value_is_ready(ref):
                break
            try:
                value = float(np.asarray(ref))
            except Exception as e:
                # an unreadable ref (donated, deleted) can't be judged;
                # drop it rather than wedge the sentinel
                self._note("sentinel_unreadable", step=step, error=repr(e))
                with self._lock:
                    self._pending.pop(0)
                continue
            kind = self._classify(value)
            if kind is None:
                with self._lock:
                    self._pending.pop(0)
                    self._history.append(value)
                    if len(self._history) > self.policy.spike_window:
                        del self._history[: -self.policy.spike_window]
                continue
            taken = self._respond(kind, step, value)  # skip returns,
        return taken                                  # others raise

    def _classify(self, value):
        if not np.isfinite(value):
            return KIND_NANINF
        pol = self.policy
        if pol.loss_ceiling is not None and value > pol.loss_ceiling:
            return KIND_LOSS_SPIKE
        with self._lock:
            hist = list(self._history)
        if len(hist) >= pol.min_history:
            med = statistics.median(hist)
            if med > 0 and value > pol.spike_factor * med:
                return KIND_LOSS_SPIKE
        return None

    # ------------------------------------------------------------ responses
    def _respond(self, kind, step, value):
        pol = self.policy
        action = pol.action_for(kind)
        # ladder escalation: each rung only runs when its machinery and
        # budget are actually available
        if action == ACTION_SKIP and (
            self._snapshot is None or self._trainer is None
            or self.skips_taken >= pol.max_skips
        ):
            action = ACTION_ROLLBACK
        if action == ACTION_ROLLBACK and not self._can_rollback():
            action = ACTION_ABORT
        self.anomalies.inc(kind=kind, action=action)
        self._note(
            "train_anomaly", kind=kind, action=action, step=step,
            value=value if np.isfinite(value) else repr(value),
        )
        if action == ACTION_SKIP:
            return self._skip(kind, step, value)
        if action == ACTION_ROLLBACK:
            self._rollback(kind, step, value)  # raises RollbackAndReplay
        self._abort(kind, step, value)         # raises TrainingAborted

    def _can_rollback(self):
        if self.manager is None or \
                self.rollbacks_taken >= self.policy.max_rollbacks:
            return False
        try:
            from ..checkpoint import commit as commit_mod

            return bool(commit_mod.list_committed(self.manager.root))
        except Exception:
            return False

    def _skip(self, kind, step, value):
        with self._lock:
            dropped = len(self._pending)
            self._pending.clear()
        snap, self._snapshot = self._snapshot, None
        resume = self._snapshot_step + 1
        self._trainer._restore_memory_snapshot(snap)
        self.skips_taken += 1
        act = Action(kind, ACTION_SKIP, step, value=value,
                     resume_step=resume, dropped_steps=dropped)
        self.last_action = act
        return act

    def _rollback(self, kind, step, value):
        with self._lock:
            self._pending.clear()
            self._history.clear()
        self._snapshot = None
        self.rollbacks_taken += 1
        try:
            # a save dispatched before detection may still be in
            # flight; let it land so the generation set is final
            # before quarantine + restore below
            self.manager.wait()
        except Exception:
            pass
        # async detection lag means a POISONED step may already have
        # been checkpointed (the trainer only gates the step it judged
        # synchronously): any generation at step >= the anomalous step
        # holds post-anomaly params and must never be restored — or
        # resumed from later. Quarantine renames it onto a .tmp name
        # (discovery never trusts .tmp; startup GC reaps it).
        self._quarantine_poisoned(step)
        res = self.manager.restore_or_init()
        if not res.restored:
            self._abort(kind, step, value)
        act = Action(kind, ACTION_ROLLBACK, step, value=value,
                     resume_step=res.step + 1)
        self.last_action = act
        self._note("train_rollback", step=step, resume_step=act.resume_step)
        raise RollbackAndReplay(act)

    def _quarantine_poisoned(self, bad_step):
        """Retire every committed generation at step >= the anomalous
        step: its params already contain the bad update. The rename
        targets a ``.tmp``-suffixed name so discovery skips it
        immediately and the manager's startup GC reaps it later; in a
        shared-root multi-rank deployment only the first rename wins
        (peers' failures are ignored)."""
        from ..checkpoint import commit as commit_mod

        try:
            committed = commit_mod.list_committed(self.manager.root)
        except Exception:
            return
        for gen_step, path in committed:
            if gen_step < bad_step:
                continue
            try:
                os.rename(
                    path, path + ".anomaly" + commit_mod.TMP_SUFFIX
                )
                self._note("train_quarantine", step=gen_step,
                           path=path, bad_step=bad_step)
            except OSError:
                pass

    def _abort(self, kind, step, value):
        act = Action(kind, ACTION_ABORT, step, value=value)
        self.last_action = act
        path = None
        try:
            # nonblocking materialization: the dump must never deadlock
            # on the dying run's own in-flight refs
            path = self.recorder.dump(
                reason=f"train_anomaly:{kind}", sync=False
            )
        except Exception:
            pass
        raise TrainingAborted(act, bundle_path=path)


# --------------------------------------------------------------- watchdog
class TrainWatchdog:
    """Detect wedged steps and straggling peer ranks before the job
    dies silently.

    - **Wedged step**: :meth:`note_dispatch` timestamps each step
      dispatch (called by the attached trainer — one clock read, no
      sync). :meth:`check` fires when ``clock() - last_dispatch -
      blocked`` exceeds ``stall_seconds``; checkpoint stalls reported
      through the StepMeter's ``note_blocked`` seam are excluded, so
      an emergency save never reads as a hang. One fire per wedge: a
      new dispatch re-arms.
    - **Straggler / dead peer**: when ``heartbeat_dir`` is set (or the
      ``PADDLE_TPU_HEARTBEAT_DIR`` env var — the elastic supervisor
      exports it), each dispatch refreshes this rank's heartbeat file
      (mtime = dispatch recency, the elastic-manager discipline) and
      :meth:`check` fires ``missed_heartbeat`` for any peer whose file
      went stale past ``heartbeat_timeout_s`` — once per staleness
      episode. Peer staleness runs on REAL file mtimes (cross-process
      comparable); the wedge gap runs on the injectable ``clock`` so
      tests advance time instead of sleeping.

    A fire bumps ``paddle_training_watchdog_fires_total{kind}``, marks
    the StepMeter's next run break ``watchdog_fire``, records a flight
    event, dumps a flight bundle (``reason="watchdog:<kind>"``), and
    invokes ``on_fire(kind, **info)`` when given. :meth:`start` runs
    :meth:`check` on a monitor thread every ``poll_interval_s``."""

    KIND_WEDGED = "wedged_step"
    KIND_MISSED = "missed_heartbeat"

    def __init__(self, *, stall_seconds=300.0, clock=time.monotonic,
                 poll_interval_s=None, heartbeat_dir=None, rank=None,
                 heartbeat_timeout_s=None, registry=None, recorder=None,
                 on_fire=None, heartbeat_min_interval_s=0.2):
        self.stall_seconds = float(stall_seconds)
        self.clock = clock
        self.poll_interval_s = (
            float(poll_interval_s) if poll_interval_s is not None
            else max(0.05, min(self.stall_seconds / 4.0, 5.0))
        )
        self.heartbeat_dir = heartbeat_dir or os.environ.get(
            "PADDLE_TPU_HEARTBEAT_DIR"
        )
        self.rank = self._resolve_rank(rank)
        self.heartbeat_timeout_s = (
            float(heartbeat_timeout_s) if heartbeat_timeout_s is not None
            else self.stall_seconds
        )
        self.heartbeat_min_interval_s = float(heartbeat_min_interval_s)
        self._recorder = recorder
        self.on_fire = on_fire
        self._lock = threading.Lock()
        self._last = None
        self._last_step = None
        self._blocked = 0.0
        self._fired_this_gap = False
        self._peer_fired = {}      # rank -> mtime at fire time
        self._hb_last_write = 0.0
        self._stop = threading.Event()
        self._thread = None
        self._meter_undo = None
        self.last_dump_path = None
        self.fires = Counter(
            "training_watchdog_fires",
            prom_name="paddle_training_watchdog_fires_total",
            help="watchdog detections, by kind "
                 "(wedged_step|missed_heartbeat)",
        )
        (registry or get_registry()).register_all([self.fires])
        if self.heartbeat_dir:
            os.makedirs(self.heartbeat_dir, exist_ok=True)
        from ..analysis.lock_sentinel import maybe_instrument

        maybe_instrument(self)

    @staticmethod
    def _resolve_rank(rank):
        if rank is not None:
            return int(rank)
        env = os.environ.get("PADDLE_TRAINER_ID", "").strip()
        if env.isdigit():
            return int(env)
        try:
            import jax

            return int(jax.process_index())
        except Exception:
            return 0

    @property
    def recorder(self):
        if self._recorder is not None:
            return self._recorder
        from ..observability import get_flight_recorder

        return get_flight_recorder()

    # ------------------------------------------------------------- wiring
    def attach(self, trainer):
        """``trainer.attach_watchdog(self)`` + listen on the process
        StepMeter's blocked seam so checkpoint stalls are excluded
        from the wedge gap."""
        trainer.attach_watchdog(self)
        self._listen_blocked()
        return trainer

    def _listen_blocked(self):
        if self._meter_undo is not None:
            return
        try:
            from ..observability import get_step_meter

            self._meter_undo = get_step_meter().add_blocked_listener(
                self.note_blocked
            )
        except Exception:
            self._meter_undo = None

    # -------------------------------------------------------------- feeding
    def note_dispatch(self, step):
        """One step dispatched (host-side timestamp only)."""
        with self._lock:
            self._last = self.clock()
            self._last_step = int(step)
            self._blocked = 0.0
            self._fired_this_gap = False
        self._write_heartbeat(step)

    def note_blocked(self, seconds):
        """Train-loop stall that is NOT step work (checkpoint writer
        backpressure / emergency save): excluded from the wedge gap."""
        with self._lock:
            self._blocked += float(seconds)

    def _write_heartbeat(self, step):
        if not self.heartbeat_dir:
            return
        now = time.time()
        if now - self._hb_last_write < self.heartbeat_min_interval_s:
            return
        self._hb_last_write = now
        path = os.path.join(self.heartbeat_dir, str(self.rank))
        try:
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(f"{int(step)}\n")
            os.replace(tmp, path)
        except OSError:
            pass

    # ------------------------------------------------------------- checking
    def check(self):
        """One watchdog pass; returns the list of fires it produced
        (``[(kind, info), ...]``). The monitor thread calls this every
        ``poll_interval_s``; tests with a ChaosClock call it
        directly."""
        fires = []
        now = self.clock()
        with self._lock:
            last = self._last
            blocked = self._blocked
            fired = self._fired_this_gap
            step = self._last_step
        if last is not None and not fired:
            gap = now - last - blocked
            if gap > self.stall_seconds:
                with self._lock:
                    self._fired_this_gap = True
                info = {"step": step, "gap_s": round(gap, 3),
                        "blocked_s": round(blocked, 3)}
                self._fire(self.KIND_WEDGED, **info)
                fires.append((self.KIND_WEDGED, info))
        fires.extend(self._check_peers())
        return fires

    def _check_peers(self):
        fires = []
        if not self.heartbeat_dir:
            return fires
        try:
            names = os.listdir(self.heartbeat_dir)
        except OSError:
            return fires
        now = time.time()
        for name in names:
            if not name.isdigit() or int(name) == self.rank:
                continue
            p = os.path.join(self.heartbeat_dir, name)
            try:
                mtime = os.stat(p).st_mtime
            except OSError:
                continue
            if now - mtime <= self.heartbeat_timeout_s:
                continue
            # monitor-thread state goes under the lock: tests and the
            # attach()ing thread call check() too, and an unlocked dict
            # write races them (unlocked-shared-write)
            with self._lock:
                if self._peer_fired.get(name) == mtime:
                    continue  # already fired for this staleness episode
                self._peer_fired[name] = mtime
            info = {"rank": int(name),
                    "stale_s": round(now - mtime, 3)}
            self._fire(self.KIND_MISSED, **info)
            fires.append((self.KIND_MISSED, info))
        return fires

    def _fire(self, kind, **info):
        self.fires.inc(kind=kind)
        try:
            from ..observability import get_step_meter

            if kind == self.KIND_WEDGED:
                get_step_meter().note_wedged()
        except Exception:
            pass
        try:
            self.recorder.note("watchdog_fire", watchdog_kind=kind,
                               **info)
            # the whole point: the bundle lands BEFORE the job dies
            # silently (nonblocking — the wedged step's refs are by
            # definition not ready)
            path = self.recorder.dump(
                reason=f"watchdog:{kind}", sync=False
            )
            with self._lock:
                # published to other threads (the smoke asserts on it)
                self.last_dump_path = path
        except Exception:
            pass
        if self.on_fire is not None:
            try:
                self.on_fire(kind, **info)
            except Exception:
                pass

    # ------------------------------------------------------------ lifecycle
    def start(self):
        """Run :meth:`check` on a daemon monitor thread."""
        self._listen_blocked()
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="paddle-train-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.check()
            except Exception:
                pass

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self._meter_undo is not None:
            self._meter_undo()
            self._meter_undo = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


# ------------------------------------------------------------ driver loop
def run_resilient(trainer, batch_fn, *, steps, start_step=1,
                  on_step=None):
    """Drive ``trainer`` from ``start_step`` through ``steps`` with
    rollback-and-replay semantics.

    ``batch_fn(step) -> (inputs, labels)`` is the DATA CURSOR: it must
    be deterministic per step index (the usual seeded-pipeline
    contract), because a rollback rewinds the cursor to the restored
    step and re-feeds the same batches — which is what makes the
    recovered loss trajectory exactly equal an uninterrupted run.

    ``on_step(step, loss, action)`` is called after every completed
    step; ``action`` is the sentinel's Action when this step triggered
    a skip (the step's update was undone and its batch dropped), else
    None. Returns a summary dict. :class:`TrainingAborted` propagates.
    """
    sentinel = getattr(trainer, "_sentinel", None)
    step = int(start_step)
    replays = 0
    completed = 0
    skipped = 0
    while step <= int(steps):
        inputs, labels = batch_fn(step)
        prev_action = sentinel.last_action if sentinel else None
        try:
            loss, _outs = trainer(inputs, labels)
        except RollbackAndReplay as rb:
            replays += 1
            step = int(rb.action.resume_step)
            continue
        action = None
        if sentinel is not None:
            la = sentinel.last_action
            # identity check, not step equality: an async skip fires
            # while verifying an EARLIER step's ref than the cursor
            if la is not None and la is not prev_action \
                    and la.action == ACTION_SKIP:
                action = la
                skipped += la.dropped_steps
        if action is None:
            completed += 1
        if on_step is not None:
            on_step(step, loss, action)
        step += 1
    return {
        "completed_steps": completed,
        "replays": replays,
        "skipped_steps": skipped,
        "final_step": (
            trainer.optimizer._step_count
            if getattr(trainer, "optimizer", None) is not None else None
        ),
    }
