"""CheckpointManager — policy, async saves, verified restore, preemption.

Reference parity: upstream fleet's checkpoint/elastic pairing ("kill one
worker → training resumes", python/paddle/distributed/fleet/, unverified,
mount empty) with Orbax-style async commit discipline on the TPU side.

The manager owns everything the raw serializer does not decide:

- **when** to save (:class:`CheckpointPolicy` — every N steps and/or
  every S seconds), driven by :meth:`on_step` from the compiled trainer
  or the hapi fit loop;
- **how** to save without stalling the chip: an on-device snapshot
  (snapshot.py) handed to a single background writer (async_saver.py),
  committed atomically (commit.py); backpressure and emergency saves
  report into ``paddle_ckpt_blocked_seconds`` and are excluded from
  ``step_time`` via ``StepMeter.note_blocked``;
- **what** to keep: last-K plus every-M-steps retention, orphaned
  ``.tmp`` GC at startup;
- **whether** what came back is intact: :meth:`restore_or_init` verifies
  manifest checksums and falls back to the previous committed
  checkpoint (bumping ``paddle_ckpt_restore_fallbacks_total``) instead
  of crashing on a torn or bit-rotted save;
- **preemption**: SIGTERM triggers an emergency synchronous save within
  a grace window, so a preempted worker loses at most the current step.
"""
from __future__ import annotations

import logging
import os
import shutil
import signal
import threading
import time

import numpy as np

from ..core import random as random_mod
from ..distributed.checkpoint.save_load import (
    load_state_dict,
    save_state_dict,
)
from ..observability import (
    Counter,
    Gauge,
    Histogram,
    get_registry,
)
from . import commit as commit_mod
from .async_saver import AsyncSaver
from .snapshot import snapshot_nbytes, snapshot_state

logger = logging.getLogger("paddle_tpu.checkpoint")

# save durations run from milliseconds (tiny CI nets) to many minutes
# (multi-TB sharded states on real pods)
SAVE_SECONDS_BUCKETS = (
    0.01, 0.05, 0.25, 1.0, 5.0, 15.0, 60.0, 300.0, 1800.0,
)

# a .tmp dir younger than this may belong to ANOTHER process's live save
# (launcher-style deployments share the root without sharing a jax
# world); startup GC only reaps older ones
ORPHAN_TMP_MIN_AGE_S = 300.0

# multiprocess time-based triggers need cross-rank agreement (a
# collective); running it every step would make the host loop
# collective-synchronous, so agreement points fire on this step cadence
# — a seconds-scale save interval is delayed by at most 15 steps
TIME_TRIGGER_AGREE_STEPS = 16


class CheckpointPolicy:
    """When to save and what to keep.

    ``save_every_steps`` / ``save_every_seconds``: either (or both) may
    trigger a save; time-based triggers are what keep a slow-step run
    from going hours between checkpoints. ``keep_last_k`` bounds disk;
    ``keep_every_m`` additionally pins every M-th step forever (the
    "keep a trail for post-hoc analysis" knob)."""

    def __init__(self, save_every_steps=None, save_every_seconds=None,
                 keep_last_k=3, keep_every_m=None):
        self.save_every_steps = (
            int(save_every_steps) if save_every_steps else None
        )
        self.save_every_seconds = (
            float(save_every_seconds) if save_every_seconds else None
        )
        self.keep_last_k = max(1, int(keep_last_k))
        self.keep_every_m = int(keep_every_m) if keep_every_m else None

    def should_save(self, step, now, last_saved_step, last_saved_time,
                    include_time=True):
        """``include_time=False`` asks for the clock-free verdict only —
        the one every rank of a multiprocess run computes identically
        (the manager uses it between cross-rank agreement points, where
        a rank-local clock read could split the ranks)."""
        if step == last_saved_step:
            return False
        if self.save_every_steps is not None and \
                step - last_saved_step >= self.save_every_steps:
            return True
        if include_time and self.save_every_seconds is not None and \
                now - last_saved_time >= self.save_every_seconds:
            return True
        return False

    def retained_steps(self, steps_newest_first):
        keep = set(steps_newest_first[: self.keep_last_k])
        if self.keep_every_m:
            keep.update(
                s for s in steps_newest_first if s % self.keep_every_m == 0
            )
        return keep


class RestoreResult:
    """What :meth:`CheckpointManager.restore_or_init` found."""

    def __init__(self, restored, step, path):
        self.restored = bool(restored)
        self.step = int(step)
        self.path = path

    def __repr__(self):
        return (
            f"RestoreResult(restored={self.restored}, step={self.step}, "
            f"path={self.path!r})"
        )


def _fallback_reason(problems):
    first = problems[0] if problems else ""
    if first.startswith("manifest"):
        return "manifest_missing"
    if first.startswith("missing file"):
        return "missing_shard"
    if first.startswith(("size mismatch", "checksum mismatch")):
        return "checksum_mismatch"
    if first.startswith(("metadata", "shard not covered")):
        return "metadata_error"
    return "load_error"


def _encode_extra_state(data):
    """{key: array-like} -> JSON-safe manifest block. Floats travel as
    JSON doubles (exact for <=fp32, e.g. the delayed-scaling
    histories); integer/bool dtypes travel as Python ints — arbitrary
    precision, so an int64 value past 2^53 is NOT squeezed through a
    double and restores bit-identical."""
    out = {}
    for k, v in data.items():
        a = np.asarray(v)
        if a.dtype.kind in "iub":
            vals = a.ravel().tolist()
        else:
            vals = a.astype(np.float64).ravel().tolist()
        out[str(k)] = {
            "dtype": str(a.dtype),
            "shape": list(a.shape),
            "data": vals,
        }
    return out


def _decode_extra_state(block):
    out = {}
    for k, rec in block.items():
        dtype = np.dtype(rec.get("dtype", "float32"))
        out[k] = np.asarray(rec["data"], dtype).reshape(
            rec.get("shape", [-1])
        )
    return out


class CheckpointManager:
    """Fault-tolerant checkpoint runtime over a checkpoint root dir.

    Typical wiring::

        mgr = CheckpointManager("ckpts", network=net, optimizer=opt,
                                policy=CheckpointPolicy(save_every_steps=100))
        res = mgr.restore_or_init()          # crash-safe auto-resume
        trainer.attach_checkpoint(mgr)       # or Model.fit(checkpoint=mgr)
        mgr.install_preemption_handler()     # SIGTERM -> emergency save

    ``state_fn(step)`` may replace the default state assembly (model +
    optimizer state dicts + step + RNG key data) for custom loops.
    """

    def __init__(self, root, *, network=None, optimizer=None,
                 state_fn=None, policy=None, async_saves=True,
                 registry=None, manifest_extra_fn=None,
                 coordinator_rank=0):
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)
        self.network = network
        self.optimizer = optimizer
        self._state_fn = state_fn
        self.policy = policy or CheckpointPolicy()
        self.async_saves = bool(async_saves)
        self.coordinator_rank = int(coordinator_rank)
        self._manifest_extra_fn = manifest_extra_fn
        self._serialize = save_state_dict  # test seam: wrap to slow/fault
        self._extra_states = {}            # name -> (get_fn, set_fn)
        self._last_restore_manifest = None
        self._lock = threading.Lock()
        self._last_step = 0
        self._last_saved_step = 0  # steps are 1-based: first save at N
        self._last_saved_time = time.monotonic()
        self.preempted = False
        self._prev_handlers = {}
        self._preempt_rethrow = {}
        self._preempt_thread = None
        self._init_metrics(registry or get_registry())
        from ..analysis.lock_sentinel import maybe_instrument

        maybe_instrument(self)
        self._saver = (
            AsyncSaver(on_error=self._on_writer_error)
            if self.async_saves else None
        )
        if self._should_gc_orphans():
            removed = commit_mod.gc_orphans(
                self.root, min_age_s=ORPHAN_TMP_MIN_AGE_S
            )
            for p in removed:
                logger.warning("checkpoint: removed orphaned save %s", p)
                self.fallbacks_total.inc(reason="orphan_tmp")
                self._note_event("checkpoint_orphan_gc", path=p)

    # ------------------------------------------------------------- plumbing
    def _init_metrics(self, reg):
        self.registry = reg
        self.save_seconds = Histogram(
            "ckpt_save_seconds", unit="s", buckets=SAVE_SECONDS_BUCKETS,
            prom_name="paddle_ckpt_save_seconds",
            help="wall time of one checkpoint write+commit (writer-side)",
        )
        self.blocked_seconds = Histogram(
            "ckpt_blocked_seconds", unit="s",
            prom_name="paddle_ckpt_blocked_seconds",
            help="train-loop stalls caused by checkpointing (writer "
                 "backpressure, synchronous/emergency saves) — excluded "
                 "from paddle_training_step_time_seconds",
        )
        self.bytes_total = Counter(
            "ckpt_bytes", unit="bytes",
            prom_name="paddle_ckpt_bytes_total",
            help="checkpoint bytes committed to storage",
        )
        self.saves_total = Counter(
            "ckpt_saves", prom_name="paddle_ckpt_saves_total",
            help="committed checkpoints by mode (async|sync|emergency)",
        )
        self.save_failures_total = Counter(
            "ckpt_save_failures",
            prom_name="paddle_ckpt_save_failures_total",
            help="checkpoint saves that errored (training continued)",
        )
        self.last_step = Gauge(
            "ckpt_last_step", prom_name="paddle_ckpt_last_step",
            help="step of the newest committed checkpoint",
        )
        self.fallbacks_total = Counter(
            "ckpt_restore_fallbacks",
            prom_name="paddle_ckpt_restore_fallbacks_total",
            help="restore candidates rejected (torn/corrupt/orphaned) "
                 "by reason",
        )
        self.restores_total = Counter(
            "ckpt_restores", prom_name="paddle_ckpt_restores_total",
            help="restore_or_init outcomes (restored|init)",
        )
        reg.register_all([
            self.save_seconds, self.blocked_seconds, self.bytes_total,
            self.saves_total, self.save_failures_total, self.last_step,
            self.fallbacks_total, self.restores_total,
        ])

    @staticmethod
    def _process_count():
        try:
            import jax

            return jax.process_count()
        except Exception:
            return 1

    @staticmethod
    def _process_index():
        try:
            import jax

            return jax.process_index()
        except Exception:
            return 0

    def _is_coordinator(self):
        return self._process_index() == self.coordinator_rank or \
            self._process_count() == 1

    def _should_gc_orphans(self):
        """Startup GC touches a SHARED directory: in launcher-style
        deployments every rank is its own single-process jax world
        (process_count == 1 everywhere), so gate on the launcher's rank
        env too — peers' in-flight saves are not this rank's to reap
        (the age window in gc_orphans is the second guard)."""
        if not self._is_coordinator():
            return False
        env_rank = os.environ.get("PADDLE_TRAINER_ID", "").strip()
        if env_rank.isdigit():
            return int(env_rank) == self.coordinator_rank
        return True

    def _note_event(self, kind, **info):
        try:
            from ..observability import get_flight_recorder

            get_flight_recorder().note(kind, **info)
        except Exception:
            pass

    def _note_blocked(self, seconds, reason):
        """A train-loop stall attributable to checkpointing: publish the
        dedicated histogram and tell the StepMeter to EXCLUDE it from
        the next dispatch-to-dispatch step_time interval, so tokens/sec
        and MFU are not silently deflated by save stalls."""
        self.blocked_seconds.observe(seconds)
        try:
            from ..observability import get_step_meter

            get_step_meter().note_blocked(seconds)
        except Exception:
            pass
        self._note_event(
            "checkpoint_blocked", seconds=seconds, reason=reason
        )

    def _on_writer_error(self, exc):
        self.save_failures_total.inc()
        self._note_event("checkpoint_save_failed", error=repr(exc))
        logger.error("checkpoint: background save failed: %r", exc)

    # ---------------------------------------------------------------- state
    def bind(self, network=None, optimizer=None):
        """Late binding for managers constructed before the model (the
        hapi callback binds at on_train_begin)."""
        if network is not None and self.network is None:
            self.network = network
        if optimizer is not None and self.optimizer is None:
            self.optimizer = optimizer
        return self

    def register_extra_state(self, name, get_fn, set_fn):
        """Attach a small named side-state that must survive a resume
        but lives outside the model/optimizer state dicts — e.g. the
        AMP O3 fp8 delayed-scaling amax histories. ``get_fn()`` returns
        ``{key: array-like}`` (empty dict = nothing to persist this
        save); it is captured at each save and stored in the commit
        manifest's ``extra`` block (written LAST, so it is exactly as
        crash-safe as the checkpoint itself). On restore, ``set_fn``
        receives the decoded ``{key: np.float32 array}``. Registration
        AFTER a restore applies the restored state immediately, so
        ``restore_or_init()`` / ``attach_checkpoint()`` work in either
        order."""
        self._extra_states[name] = (get_fn, set_fn)
        man = self._last_restore_manifest
        if man:
            data = ((man.get("extra") or {}).get("state") or {}).get(
                name
            )
            if data is not None:
                try:
                    set_fn(_decode_extra_state(data))
                except Exception as e:
                    logger.warning(
                        "checkpoint: restored extra state %r not "
                        "applicable: %r", name, e,
                    )
        return self

    def _collect_extra_state(self):
        """Snapshot every registered extra state on the CALLER thread
        (save-time semantics, like the device snapshot). Collection
        errors are logged, never allowed to fail a save."""
        out = {}
        for name, (get_fn, _set) in self._extra_states.items():
            try:
                data = get_fn()
                if data:
                    out[name] = _encode_extra_state(data)
            except Exception as e:
                logger.warning(
                    "checkpoint: extra state %r not captured: %r",
                    name, e,
                )
        return out

    def _build_state(self, step):
        if self._state_fn is not None:
            return self._state_fn(step)
        if self.network is None:
            raise RuntimeError(
                "CheckpointManager has no network bound and no state_fn; "
                "pass network=/optimizer= or state_fn= at construction, "
                "or bind() before saving"
            )
        state = {"model": self.network.state_dict()}
        if self.optimizer is not None:
            state["opt"] = self.optimizer.state_dict()
        state["step"] = int(step if step is not None else self._last_step)
        state["rng"] = np.asarray(random_mod.get_rng_state())
        return state

    # ---------------------------------------------------------------- saves
    def on_step(self, step):
        """Per-step hook (compiled trainer / fit loop): updates the step
        clock and saves when policy says so. Returns True if a save was
        triggered."""
        step = int(step)
        now = time.monotonic()
        with self._lock:
            self._last_step = step
            last_step = self._last_saved_step
            last_time = self._last_saved_time
        pol = self.policy
        trigger = pol.should_save(step, now, last_step, last_time)
        if pol.save_every_seconds is not None and \
                self._process_count() > 1:
            # time-based triggers read each rank's LOCAL clock; ranks
            # straddling the threshold would disagree, and a save whose
            # collectives only some ranks enter is a distributed hang.
            # The coordinator's verdict is broadcast at agreement points
            # on a deterministic step cadence; between them only the
            # policy's clock-free verdict — identical on every rank —
            # may trigger.
            if step % TIME_TRIGGER_AGREE_STEPS == 0:
                from ..distributed import communication as comm

                verdict = [bool(trigger)]
                comm.broadcast_object_list(
                    verdict, src=self.coordinator_rank
                )
                trigger = bool(verdict[0])
            else:
                trigger = pol.should_save(
                    step, now, last_step, last_time, include_time=False
                )
        if trigger:
            self.save(step)
        return trigger

    def save(self, step=None, blocking=None, mode=None):
        """Checkpoint the current state at ``step``. ``blocking=False``
        (the async default) snapshots on the caller thread and hands the
        write to the background writer; ``blocking=True`` writes+commits
        before returning."""
        step = int(self._last_step if step is None else step)
        if blocking is None:
            blocking = not self.async_saves
        if self._process_count() > 1:
            # multiprocess writes contain collectives; issued from the
            # background writer they would interleave nondeterministically
            # with the train loop's own collectives across ranks (rank 0
            # pairs writer-barrier against rank 1's main-thread gather —
            # a distributed hang), so the write runs on the calling
            # thread, where collective order is program order
            blocking = True
        mode = mode or ("sync" if blocking else "async")
        state = self._build_state(step)
        snap = snapshot_state(state)
        extra_state = self._collect_extra_state()
        with self._lock:
            prev = (self._last_saved_step, self._last_saved_time)
            self._last_saved_step = step
            self._last_saved_time = time.monotonic()

        def write():
            # the saved-marker was advanced optimistically (policy must
            # not re-trigger while the write runs); a FAILED write rolls
            # it back so the next policy check — and an emergency save —
            # knows this step never landed
            try:
                self._write_and_commit(step, snap, mode, extra_state)
            except BaseException:
                with self._lock:
                    if self._last_saved_step == step:
                        (self._last_saved_step,
                         self._last_saved_time) = prev
                raise

        if blocking or self._saver is None:
            t0 = time.perf_counter()
            write()
            self._note_blocked(time.perf_counter() - t0, reason=mode)
        else:
            blocked = self._saver.submit(write)
            if blocked > 1e-4:
                # backpressure: the previous save was still in flight
                self._note_blocked(blocked, reason="backpressure")
        return step

    def _write_and_commit(self, step, snap, mode, extra_state=None):
        """Writer-side: serialize shards into step_N.tmp, write the
        manifest, barrier, rename. Runs on the background writer thread
        for async saves."""
        t0 = time.perf_counter()
        tmp = commit_mod.tmp_dir(self.root, step)
        nprocs = self._process_count()
        if nprocs > 1:
            # every process writes shards into the SHARED tmp dir: only
            # the coordinator may clear a stale one, and the barrier
            # keeps any peer from streaming shards into a dir that is
            # about to be rmtree'd under it
            from ..distributed import communication as comm

            if self._is_coordinator() and os.path.isdir(tmp):
                shutil.rmtree(tmp)
            comm.barrier()
        elif os.path.isdir(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp, exist_ok=True)
        err = None
        try:
            files = self._serialize(snap, tmp) or {}
        except Exception as e:
            err, files = e, {}
        if nprocs > 1:
            # manifest needs every process's file digests; the gather
            # doubles as the all-shards-on-storage barrier. A rank-local
            # serialize failure is gathered too, never raised before the
            # collective — a rank bailing early would strand its peers
            # in the allgather forever — and then raised on ALL ranks,
            # so every rank rolls its saved-marker back and the step
            # triggers stay in sync.
            from ..distributed import communication as comm

            gathered = []  # all_gather_object APPENDS one entry per rank
            comm.all_gather_object(
                gathered,
                {"files": files, "error": repr(err) if err else None},
            )
            files, failed = {}, {}
            for rank, part in enumerate(gathered):
                part = part or {}
                if part.get("error"):
                    failed[rank] = part["error"]
                files.update(part.get("files") or {})
            if failed:
                raise RuntimeError(
                    f"checkpoint save for step {step} failed on "
                    f"rank(s) {failed}"
                ) from err
        elif err is not None:
            raise err
        path = None
        commit_err = None
        if self._is_coordinator():
            try:
                extra = (
                    self._manifest_extra_fn(step, snap)
                    if self._manifest_extra_fn is not None else None
                )
                if extra_state:
                    extra = dict(extra or {})
                    extra["state"] = extra_state
                commit_mod.write_manifest(tmp, step, files, extra=extra)
                path = commit_mod.commit(self.root, step)
                self._apply_retention()
            except Exception as e:
                commit_err = e
        if nprocs > 1:
            from ..distributed import communication as comm

            # the outcome broadcast doubles as the pre-resume barrier
            # (nobody resumes past a half-published commit), and a
            # coordinator-side manifest/commit/retention failure raises
            # on EVERY rank instead of stranding peers in a barrier the
            # coordinator never reached
            outcome = [repr(commit_err) if commit_err else None]
            comm.broadcast_object_list(outcome, src=self.coordinator_rank)
            if outcome[0]:
                raise RuntimeError(
                    f"checkpoint commit for step {step} failed on the "
                    f"coordinator: {outcome[0]}"
                ) from commit_err
        elif commit_err is not None:
            raise commit_err
        dt = time.perf_counter() - t0
        nbytes = sum(int(rec["bytes"]) for rec in files.values())
        self.save_seconds.observe(dt)
        self.bytes_total.inc(nbytes)
        self.saves_total.inc(mode=mode)
        self.last_step.set(step)
        self._note_event(
            "checkpoint_commit", step=step, seconds=dt, bytes=nbytes,
            mode=mode, path=path or commit_mod.step_dir(self.root, step),
        )
        return path

    def _apply_retention(self):
        committed = commit_mod.list_committed(self.root)
        keep = self.policy.retained_steps([s for s, _ in committed])
        for s, path in committed:
            if s not in keep:
                shutil.rmtree(path, ignore_errors=True)
                self._note_event("checkpoint_retired", step=s, path=path)

    def wait(self, timeout=None):
        """Drain any in-flight background save."""
        if self._saver is not None:
            return self._saver.wait(timeout)
        return True

    def finalize(self):
        """End-of-training: drain the writer (and any emergency save)
        so the last commit lands."""
        self.join_preemption()
        self.wait()
        if self._saver is not None and self._saver.last_error is not None:
            logger.error(
                "checkpoint: last background save error: %r",
                self._saver.last_error,
            )

    def close(self):
        self.finalize()
        if self._saver is not None:
            self._saver.close()

    # -------------------------------------------------------------- restore
    def restore_or_init(self):
        """Crash-safe auto-resume: load the newest INTACT committed
        checkpoint into the bound network/optimizer (and RNG state), or
        leave the fresh init in place when none exists.

        Every candidate is verified against its manifest (checksums,
        sizes, shard coverage) before any load; a torn or corrupted
        checkpoint is logged, counted in
        ``paddle_ckpt_restore_fallbacks_total{reason=...}``, and skipped
        in favor of the previous one — a bad newest save degrades to
        losing one checkpoint interval, never to a crash loop."""
        state = self._build_state(None)
        for step, path, manifest in commit_mod.list_candidates(self.root):
            if manifest is None:
                self._reject(path, ["manifest missing or unparsable"])
                continue
            problems = commit_mod.verify_checkpoint(path)
            if problems:
                self._reject(path, problems)
                continue
            try:
                load_state_dict(state, path)
            except Exception as e:
                self._reject(path, [f"load failed: {e!r}"], reason="load_error")
                continue
            if self.optimizer is not None and isinstance(
                state.get("opt"), dict
            ):
                self.optimizer.set_state_dict(state["opt"])
            if state.get("rng") is not None and self._state_fn is None:
                try:
                    random_mod.set_rng_state(np.asarray(state["rng"]))
                except Exception:
                    logger.warning(
                        "checkpoint: RNG state from %s not restorable",
                        path,
                    )
            restored_step = int(state.get("step", step))
            with self._lock:
                self._last_step = restored_step
                self._last_saved_step = restored_step
                self._last_saved_time = time.monotonic()
            # extra side-states (fp8 amax histories, ...) ride in the
            # manifest; keep it so attach-after-restore still applies
            self._last_restore_manifest = manifest
            extra = (manifest.get("extra") or {}).get("state") or {}
            for name, (_get, set_fn) in self._extra_states.items():
                data = extra.get(name)
                if data is None:
                    continue
                try:
                    set_fn(_decode_extra_state(data))
                except Exception as e:
                    logger.warning(
                        "checkpoint: extra state %r from %s not "
                        "applicable: %r", name, path, e,
                    )
            self.restores_total.inc(outcome="restored")
            self._note_event(
                "checkpoint_restore", step=restored_step, path=path
            )
            logger.info(
                "checkpoint: resumed from %s (step %d)", path, restored_step
            )
            return RestoreResult(True, restored_step, path)
        self.restores_total.inc(outcome="init")
        self._note_event("checkpoint_restore", step=0, path=None)
        return RestoreResult(False, 0, None)

    def _reject(self, path, problems, reason=None):
        reason = reason or _fallback_reason(problems)
        self.fallbacks_total.inc(reason=reason)
        self._note_event(
            "checkpoint_fallback", path=path, reason=reason,
            problems=problems[:4],
        )
        logger.warning(
            "checkpoint: skipping %s (%s): %s", path, reason, problems[:4]
        )

    # ----------------------------------------------------------- preemption
    def install_preemption_handler(self, signals=(signal.SIGTERM,),
                                   grace_seconds=30.0):
        """SIGTERM (preemption notice) → drain any in-flight save within
        the grace window, then take an emergency synchronous save of the
        current step. Sets :attr:`preempted` for the train loop to exit;
        a previous (callable) handler is honored after the save lands by
        RE-RAISING the signal at the process with it restored — never by
        calling it from the worker thread, where e.g.
        ``signal.default_int_handler``'s KeyboardInterrupt would kill
        only that thread and the stale interrupted frame would be
        invoked long after its signal context.

        The handler itself only sets the flag and hands the save to a
        dedicated thread: signal handlers run on the main thread between
        bytecodes, and taking the manager/saver locks from one would
        deadlock whenever the signal lands inside a frame that already
        holds them (the interrupted frame can't release a lock while the
        handler sits on top of it). The thread is non-daemon so the
        process outlives the main loop long enough for the save to
        commit; :meth:`join_preemption` waits for it explicitly."""
        grace_seconds = float(grace_seconds)

        def handler(signum, frame, _grace=grace_seconds):
            if self._preempt_rethrow.pop(signum, None):
                # the emergency save committed and the worker re-raised:
                # restore the previous handler and deliver the signal to
                # it ON THE MAIN THREAD with real signal semantics
                # (signal.signal is main-thread-only, so the restore has
                # to happen here, not on the worker)
                prev = self._prev_handlers.get(signum)
                signal.signal(
                    signum, prev if prev is not None else signal.SIG_DFL
                )
                signal.raise_signal(signum)
                return
            self.preempted = True
            if self._preempt_thread is not None and \
                    self._preempt_thread.is_alive():
                return  # a second notice while the save is running
            prev = self._prev_handlers.get(signum)

            def run():
                self.emergency_save(grace_seconds=_grace)
                if callable(prev) and prev is not handler:
                    self._preempt_rethrow[signum] = True
                    os.kill(os.getpid(), signum)

            self._preempt_thread = threading.Thread(
                target=run, name="ckpt-preempt", daemon=False
            )
            self._preempt_thread.start()

        for sig in signals:
            self._prev_handlers[sig] = signal.signal(sig, handler)
        return self

    def join_preemption(self, timeout=None):
        """Wait for an in-progress emergency save (train loops that see
        :attr:`preempted` call this before exiting). Returns True when
        no emergency save is running."""
        t = self._preempt_thread
        if t is None:
            return True
        t.join(timeout)
        return not t.is_alive()

    def emergency_save(self, grace_seconds=30.0):
        """Synchronous best-effort save of the current step (preemption
        path). Never raises — a failed emergency save still lets the
        chained handler / exit proceed."""
        t0 = time.perf_counter()
        try:
            drained = self.wait(timeout=grace_seconds)
            if not drained:
                logger.error(
                    "checkpoint: in-flight save did not drain within "
                    "%.0fs grace; emergency save skipped", grace_seconds
                )
                return None
            with self._lock:
                already = self._last_saved_step == self._last_step
            if already:
                return self._last_step
            return self.save(blocking=True, mode="emergency")
        except Exception as e:
            self.save_failures_total.inc()
            self._note_event("checkpoint_save_failed", error=repr(e),
                             mode="emergency")
            logger.error("checkpoint: emergency save failed: %r", e)
            return None
        finally:
            self._note_event(
                "checkpoint_preempted",
                seconds=time.perf_counter() - t0,
                step=self._last_step,
            )
            # alongside the emergency save: the last-K step records,
            # written NEXT TO the checkpoints (the post-mortem reader
            # already looks there). Only the NaN hook and the
            # excepthook used to dump — a PREEMPTED run lost its
            # flight ring entirely. Nonblocking materialization: a
            # step may still be in flight inside the grace window, and
            # this must never stall past it.
            try:
                from ..observability import get_flight_recorder

                # under root/flight/ — a plain file in the root would
                # read as a legacy step-numbered checkpoint to
                # latest_checkpoint's file discovery
                path = get_flight_recorder().dump(
                    path=os.path.join(
                        self.root, "flight",
                        f"preemption_{os.getpid()}.json",
                    ),
                    reason="preemption", sync=False,
                )
                self._note_event("flight_dump", path=path,
                                 reason="preemption")
            except Exception:
                pass

    # -------------------------------------------------------------- context
    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False
