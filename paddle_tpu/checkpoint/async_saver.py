"""Background checkpoint writer: one save in flight, backpressure after.

The writer is a single daemon thread consuming a depth-1 mailbox. The
train loop's side of a save is only (1) taking the on-device snapshot
(microseconds) and (2) handing it to :meth:`AsyncSaver.submit`. Submit
normally returns immediately; when the PREVIOUS save is still writing,
it blocks until that save finishes — the "at most one in flight"
backpressure the checkpoint manager reports as blocked time. Saves are
strictly ordered: a later step's checkpoint never commits before an
earlier one.

Writer errors never kill training: they are recorded (``last_error``,
an error counter via the manager's callback) and the next save
proceeds. Callers that must know a save landed (emergency saves, end of
training) use :meth:`wait`.
"""
from __future__ import annotations

import threading
import time


class AsyncSaver:
    def __init__(self, on_error=None):
        self._on_error = on_error
        self._lock = threading.Lock()
        self._job_ready = threading.Condition(self._lock)
        self._job_done = threading.Condition(self._lock)
        self._job = None  # pending (not yet picked up) job
        self._running = False  # a picked-up job is executing
        self._closed = False
        self.last_error = None
        self._thread = threading.Thread(
            target=self._worker, name="ckpt-writer", daemon=True
        )
        self._thread.start()

    # --------------------------------------------------------------- worker
    def _worker(self):
        while True:
            with self._lock:
                while self._job is None and not self._closed:
                    self._job_ready.wait()
                if self._job is None and self._closed:
                    return
                job, self._job = self._job, None
                self._running = True
                self._job_done.notify_all()  # mailbox slot free
            try:
                job()
            except Exception as e:  # surfaced, never fatal to training
                with self._lock:
                    # writer-thread publication: readers poll last_error
                    # from the train thread (unlocked-shared-write)
                    self.last_error = e
                if self._on_error is not None:
                    try:
                        self._on_error(e)
                    except Exception:
                        pass
            finally:
                with self._lock:
                    self._running = False
                    self._job_done.notify_all()

    # ----------------------------------------------------------------- api
    def submit(self, job):
        """Enqueue ``job`` (a zero-arg callable doing write+commit).
        Blocks while a previous save is in flight; returns the seconds
        spent blocked (0.0 on the fast path)."""
        t0 = time.perf_counter()
        with self._lock:
            if self._closed:
                raise RuntimeError("AsyncSaver is closed")
            while self._job is not None or self._running:
                self._job_done.wait()
            self._job = job
            self._job_ready.notify()
        return time.perf_counter() - t0

    def busy(self):
        with self._lock:
            return self._job is not None or self._running

    def wait(self, timeout=None):
        """Block until no save is pending or in flight. Returns True if
        drained, False on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while self._job is not None or self._running:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return False
                self._job_done.wait(remaining)
        return True

    def close(self, timeout=30.0):
        """Drain and stop the worker thread."""
        self.wait(timeout)
        with self._lock:
            self._closed = True
            self._job_ready.notify_all()
        self._thread.join(timeout=5.0)
