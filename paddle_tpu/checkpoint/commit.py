"""Atomic commit protocol: a checkpoint exists completely or not at all.

Reference parity: the reference's fleet checkpoint machinery
(python/paddle/distributed/checkpoint/, unverified, mount empty) plus
the Orbax-style commit discipline used for async TPU checkpointing.

Layout under a checkpoint root::

    root/
      step_00000042.tmp/    # in-flight save: shards stream in here
        <name>.p0.s0.npy    # sharded tensor data (atomic per-file)
        metadata.json       # serializer metadata (written after shards)
        manifest.json       # commit manifest: written LAST
      step_00000042/        # committed: the .tmp dir renamed
      LATEST                # text marker naming the newest committed dir

The manifest records the step and a ``{filename: {crc32, bytes}}`` map
of every file in the checkpoint. Because each file write is itself
atomic (fsio.py) and the manifest is written after all of them, the
single ``os.rename`` of ``step_N.tmp`` -> ``step_N`` is the commit
point: discovery only trusts directories whose manifest parses, so a
crash at ANY earlier instant leaves at worst an orphaned ``.tmp`` that
startup GC removes. ``LATEST`` is an O(1) hint, not the source of
truth — if it is stale or torn, discovery falls back to scanning.

In multi-process SPMD every process writes its own shards into the same
``.tmp`` (shared filesystem); a barrier precedes the coordinator-only
rename so the commit never races a straggler's shard write.
"""
from __future__ import annotations

import json
import os
import re
import shutil
import time

from ..distributed.checkpoint.fsio import (
    atomic_write_text,
    crc32_file,
    fsync_dir,
)
from ..distributed.checkpoint.metadata import Metadata, metadata_path

MANIFEST_FILE = "manifest.json"
LATEST_FILE = "LATEST"
TMP_SUFFIX = ".tmp"
# when re-saving an already-committed step, the old generation is
# renamed aside to step_N.replaced.tmp for the duration of the commit
# rename (never rmtree'd while the replacement is unpublished); startup
# GC renames it back if a crash left the step with no committed dir
REPLACED_SUFFIX = ".replaced" + TMP_SUFFIX

_STEP_DIR_RE = re.compile(r"step_(\d+)")


def step_dir_name(step):
    return f"step_{int(step):08d}"


def step_dir(root, step):
    return os.path.join(root, step_dir_name(step))


def tmp_dir(root, step):
    return step_dir(root, step) + TMP_SUFFIX


def manifest_path(dirname):
    return os.path.join(dirname, MANIFEST_FILE)


def write_manifest(dirname, step, files, extra=None):
    """Write the commit manifest (atomically, then fsync the dir so the
    subsequent rename publishes durable contents)."""
    doc = {
        "version": 1,
        "step": int(step),
        "time": time.time(),
        "files": {
            str(k): {"crc32": int(v["crc32"]), "bytes": int(v["bytes"])}
            for k, v in files.items()
        },
    }
    if extra:
        doc["extra"] = extra
    atomic_write_text(manifest_path(dirname), json.dumps(doc, indent=1))
    fsync_dir(dirname)
    return doc


def read_manifest(dirname):
    """Parsed manifest dict, or None when absent/unparsable/malformed (a
    torn, hand-edited, or pre-runtime directory). Validates the fields
    every consumer relies on — an integer ``step`` and integer
    crc32/bytes per file — so discovery and verification can trust a
    non-None manifest without re-checking shapes."""
    try:
        with open(manifest_path(dirname)) as f:
            doc = json.load(f)
        files = doc.get("files")
        if not isinstance(files, dict):
            return None
        doc["step"] = int(doc["step"])
        for rec in files.values():
            rec["crc32"] = int(rec["crc32"])
            rec["bytes"] = int(rec["bytes"])
        return doc
    except (OSError, ValueError, TypeError, KeyError):
        return None


def commit(root, step):
    """The commit point: rename ``step_N.tmp`` -> ``step_N`` and refresh
    the LATEST marker. Returns the committed path."""
    src, dst = tmp_dir(root, step), step_dir(root, step)
    aside = None
    if os.path.isdir(dst):
        # a previous save of the same step (re-run after restore):
        # replace it wholesale — two generations of one step must not
        # mix. Rename the old generation ASIDE rather than rmtree'ing
        # it: a crash during an rmtree-then-rename would destroy the
        # committed generation while the replacement is still
        # unpublished, losing the step entirely. The aside copy is
        # deleted only after the new one is in place (and startup GC
        # renames it back if a crash lands between the two renames).
        aside = dst + REPLACED_SUFFIX
        if os.path.isdir(aside):
            shutil.rmtree(aside)
        os.rename(dst, aside)
        os.utime(aside, None)  # rename keeps mtime; stamp for GC's age window
    try:
        os.rename(src, dst)
    except OSError:
        if aside is not None and not os.path.isdir(dst):
            os.rename(aside, dst)  # put the old generation back
        raise
    atomic_write_text(os.path.join(root, LATEST_FILE), step_dir_name(step))
    fsync_dir(root)
    if aside is not None:
        shutil.rmtree(aside, ignore_errors=True)
    return dst


def list_candidates(root):
    """Every step-shaped directory under ``root`` (committed or not),
    newest first: [(step, path, manifest_or_None)]. ``.tmp`` dirs are
    never candidates — they were never committed."""
    out = []
    try:
        names = os.listdir(root)
    except OSError:
        return out
    for name in names:
        m = _STEP_DIR_RE.fullmatch(name)
        if not m:
            continue
        p = os.path.join(root, name)
        if not os.path.isdir(p):
            continue
        out.append((int(m.group(1)), p, read_manifest(p)))
    out.sort(reverse=True)
    return out


def list_committed(root):
    """Committed checkpoints, newest first: [(step, path)]."""
    return [
        (step, path)
        for step, path, manifest in list_candidates(root)
        if manifest is not None
    ]


def latest_committed(root):
    """Path of the newest committed checkpoint, or None. The LATEST
    marker is a fast path but only a LOWER bound: a crash between the
    commit rename and the marker write leaves it one step behind, so any
    step-shaped dir newer than the marker (a cheap name scan, no
    manifest reads) forces the full scan; a stale/torn marker falls back
    the same way."""
    try:
        with open(os.path.join(root, LATEST_FILE)) as f:
            name = f.read().strip()
        m = _STEP_DIR_RE.fullmatch(name)
        p = os.path.join(root, name)
        if m and read_manifest(p) is not None:
            marker_step = int(m.group(1))
            newer = any(
                mm and int(mm.group(1)) > marker_step
                for mm in map(_STEP_DIR_RE.fullmatch, os.listdir(root))
            )
            if not newer:
                return p
    except OSError:
        pass
    committed = list_committed(root)
    return committed[0][1] if committed else None


def verify_checkpoint(path, level="full"):
    """Integrity problems of a checkpoint directory, [] when intact.

    Checks, in order: manifest present + parsable; every manifest file
    present with the recorded size (and, at ``level="full"`` /
    ``"files"``, the recorded CRC32); serializer metadata parsable and
    referencing only manifest-covered shard files. ``level="files"``
    stops after the per-file checks — the discovery mode
    (``fleet.elastic.latest_checkpoint``) for directories that carry a
    commit manifest but no serializer metadata."""
    problems = []
    manifest = read_manifest(path)
    if manifest is None:
        return [f"manifest missing or unparsable: {manifest_path(path)}"]
    for fname, rec in manifest["files"].items():
        fpath = os.path.join(path, fname)
        if not os.path.isfile(fpath):
            problems.append(f"missing file: {fname}")
            continue
        size = os.path.getsize(fpath)
        if size != int(rec["bytes"]):
            problems.append(
                f"size mismatch: {fname} has {size} bytes, "
                f"manifest says {rec['bytes']}"
            )
            continue
        if level in ("full", "files"):
            crc, _ = crc32_file(fpath)
            if crc != int(rec["crc32"]):
                problems.append(
                    f"checksum mismatch: {fname} crc32 {crc} != "
                    f"manifest {rec['crc32']}"
                )
    if level == "files":
        return problems
    try:
        with open(metadata_path(path)) as f:
            meta = Metadata.from_json(f.read())
        for name, tmeta in meta.tensors.items():
            for sh in tmeta.shards:
                if sh.file not in manifest["files"]:
                    problems.append(
                        f"shard not covered by manifest: {sh.file} "
                        f"(tensor {name})"
                    )
    except (OSError, ValueError, KeyError) as e:
        problems.append(f"metadata unreadable: {e}")
    return problems


def gc_orphans(root, min_age_s=0.0):
    """Remove orphaned ``.tmp`` dirs (saves that died before their
    commit rename). Returns the removed paths. Call at startup, before
    this process has a save in flight. ``min_age_s`` protects OTHER
    processes sharing the root: a tmp dir modified within the window is
    presumed to be a live writer's (every shard write touches the dir —
    create + rename per file) and is left alone.

    ``step_N.replaced.tmp`` dirs (the old generation a same-step re-save
    moved aside mid-commit) get recovery instead of plain reaping: if a
    crash between commit()'s two renames left the step with NO committed
    dir, the aside copy — still intact, manifest and all — is renamed
    back into place; otherwise it is reaped like any orphan."""
    removed = []
    now = time.time()
    try:
        names = os.listdir(root)
    except OSError:
        return removed
    for name in names:
        if not name.endswith(TMP_SUFFIX):
            continue
        stem = name[: -len(TMP_SUFFIX)]
        replaced = name.endswith(REPLACED_SUFFIX)
        if replaced:
            stem = name[: -len(REPLACED_SUFFIX)]
        if not _STEP_DIR_RE.fullmatch(stem):
            continue
        p = os.path.join(root, name)
        if not os.path.isdir(p):
            continue
        if replaced and not os.path.isdir(os.path.join(root, stem)) \
                and read_manifest(p) is not None:
            # recovery is NOT age-gated: an elastic relaunch seconds
            # after a mid-commit crash must get its step back, not
            # restart from an older checkpoint until the window expires
            # (worst case against a still-LIVE committer: its commit
            # rename fails and that save errors — no data loss)
            try:
                os.rename(p, os.path.join(root, stem))
                continue  # recovered, not removed
            except OSError:
                pass
        if min_age_s > 0:
            try:
                if now - os.path.getmtime(p) < min_age_s:
                    continue  # plausibly a live writer/committer
            except OSError:
                continue
        shutil.rmtree(p, ignore_errors=True)
        removed.append(p)
    return removed
