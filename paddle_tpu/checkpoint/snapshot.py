"""Device→host snapshots that do not block the train loop.

Reference parity: Orbax-style async checkpointing on TPU — the save's
device reads are decoupled from the train loop's dispatch.

The hazard this module exists for: ``jit.CompiledTrainStep`` donates the
parameter and optimizer-state buffers every step, so a background writer
that held the ORIGINAL array refs would race the next step's donation —
by the time it serializes, the buffers have been invalidated. A
:func:`snapshot_state` therefore tree-maps every device leaf through an
on-device copy (``jnp.copy`` — an async dispatch into the device stream,
microseconds on the host) so the snapshot owns buffers no later step can
donate away. The actual device→host transfer then happens on the writer
thread when it serializes the copies, following the same ``is_ready()``
discipline the flight recorder uses for in-flight values: the train
loop never waits on it.

Host leaves (numpy arrays, python scalars) are copied eagerly — they are
mutable in place by later steps, and cheap.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor


def _snap_leaf(v):
    if isinstance(v, Tensor):
        v = v.value
    if isinstance(v, jax.Array):
        # own buffer: an async on-device copy, immune to later donation;
        # sharding follows the source so the sharded serializer writes
        # the same per-process shard boxes the live array had
        return jnp.copy(v)
    if isinstance(v, np.ndarray):
        return np.array(v, copy=True)
    return v


def snapshot_state(state):
    """Deep-copy a (possibly nested) state dict into snapshot form:
    device leaves become freshly dispatched on-device copies, host
    leaves are copied now. Returns the parallel structure."""
    if isinstance(state, dict):
        return {k: snapshot_state(v) for k, v in state.items()}
    if isinstance(state, (list, tuple)):
        return [snapshot_state(v) for v in state]
    return _snap_leaf(state)


def _device_leaves(snap):
    if isinstance(snap, dict):
        for v in snap.values():
            yield from _device_leaves(v)
    elif isinstance(snap, (list, tuple)):
        for v in snap:
            yield from _device_leaves(v)
    elif isinstance(snap, jax.Array):
        yield snap


def snapshot_is_ready(snap):
    """True when every device copy in the snapshot has materialized
    (the writer may serialize without blocking on the device)."""
    for leaf in _device_leaves(snap):
        try:
            if not leaf.is_ready():
                return False
        except AttributeError:
            pass  # backends without is_ready: treat as ready (blocking ok)
    return True


def snapshot_nbytes(snap):
    """Approximate payload size (device + host array bytes)."""
    total = 0
    if isinstance(snap, dict):
        return sum(snapshot_nbytes(v) for v in snap.values())
    if isinstance(snap, (list, tuple)):
        return sum(snapshot_nbytes(v) for v in snap)
    nbytes = getattr(snap, "nbytes", None)
    if nbytes:
        total += int(nbytes)
    return total
