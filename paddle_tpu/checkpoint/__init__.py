"""paddle_tpu.checkpoint — fault-tolerant checkpoint runtime.

Reference parity: ``paddle.distributed.checkpoint`` + fleet elastic's
restart-from-checkpoint recovery model (unverified, mount empty),
re-architected around Orbax-style async TPU checkpointing. The layered
design:

- :mod:`snapshot` — on-device copies decouple the save from the train
  loop's buffer donation; the device→host fetch happens off-thread;
- :mod:`async_saver` — one background writer, at most one save in
  flight, backpressure (reported as blocked time) when a second save
  triggers early;
- :mod:`commit` — shards + per-file CRC32s stream into ``step_N.tmp``,
  a manifest is written last, and one rename publishes the checkpoint:
  it exists completely or not at all;
- :mod:`manager` — :class:`CheckpointManager` owns save policy,
  last-K/every-M retention, orphan GC, verified restore with fallback,
  SIGTERM emergency saves, and ``paddle_ckpt_*`` registry metrics.

The raw sharded serializer (reshard-on-load) stays in
``paddle_tpu.distributed.checkpoint``; this package is the runtime that
decides when to call it and whether to trust what it reads back.
"""
from .async_saver import AsyncSaver  # noqa: F401
from .commit import (  # noqa: F401
    LATEST_FILE,
    MANIFEST_FILE,
    gc_orphans,
    latest_committed,
    list_committed,
    read_manifest,
    verify_checkpoint,
)
from .manager import (  # noqa: F401
    CheckpointManager,
    CheckpointPolicy,
    RestoreResult,
)
from .snapshot import (  # noqa: F401
    snapshot_is_ready,
    snapshot_nbytes,
    snapshot_state,
)

__all__ = [
    "CheckpointManager", "CheckpointPolicy", "RestoreResult",
    "AsyncSaver",
    "snapshot_state", "snapshot_is_ready", "snapshot_nbytes",
    "latest_committed", "list_committed", "verify_checkpoint",
    "read_manifest", "gc_orphans", "MANIFEST_FILE", "LATEST_FILE",
]
