"""paddle.sparse.nn (python/paddle/sparse/nn/ parity — unverified):
activation layers + softmax over sparse tensors. Activations are thin
wrappers over the shared ``_value_op`` zero-preserving kernel helper.
The reference's 3-D submanifold convolutions (SubmConv3D et al.) are
point-cloud kernels with data-dependent gather tables — out of the TPU
static-shape scope; documented gap in COVERAGE.md."""
from __future__ import annotations

import jax.numpy as jnp
from jax.experimental import sparse as jsparse


class _ValueActivation:
    def __init__(self, fn):
        from . import _value_op

        self._op = _value_op(type(self).__name__, fn)

    def __call__(self, x):
        return self._op(x)


class ReLU(_ValueActivation):
    def __init__(self):
        super().__init__(lambda v: jnp.maximum(v, 0))


class ReLU6(_ValueActivation):
    def __init__(self):
        super().__init__(lambda v: jnp.clip(v, 0, 6))


class LeakyReLU(_ValueActivation):
    def __init__(self, negative_slope=0.01):
        s = float(negative_slope)
        super().__init__(lambda v: jnp.where(v >= 0, v, s * v))


class Softmax:
    """Softmax over the last axis, restricted to stored elements —
    the reference's sparse softmax semantics (zeros stay zero, each
    row normalizes over its nonzeros)."""

    def __init__(self, axis=-1):
        if axis != -1:
            raise ValueError("sparse Softmax supports axis=-1 only")

    def __call__(self, x):
        from . import SparseCooTensor, SparseCsrTensor, _coo

        csr = isinstance(x, SparseCsrTensor)
        coo = _coo(x)
        idx = coo._bcoo.indices
        data = coo._bcoo.data
        # group key = all but the last sparse dim
        if idx.shape[1] == 1:
            key = jnp.zeros((idx.shape[0],), jnp.int32)
            n_rows = 1
        else:
            lead_shape = coo._bcoo.shape[:-1]
            key = jnp.ravel_multi_index(
                tuple(idx[:, :-1].T), lead_shape, mode="clip"
            ).astype(jnp.int32)
            n_rows = 1
            for s in lead_shape:
                n_rows *= int(s)
        row_max = jnp.full((n_rows,), -jnp.inf, data.dtype).at[key].max(data)
        ex = jnp.exp(data - row_max[key])
        row_sum = jnp.zeros((n_rows,), data.dtype).at[key].add(ex)
        out = ex / row_sum[key]
        if csr:
            # rebuild CSR layout from the (unchanged) structure
            return SparseCsrTensor(x.crows, x.cols, out, x.shape)
        return SparseCooTensor(
            jsparse.BCOO((out, idx), shape=coo._bcoo.shape)
        )
