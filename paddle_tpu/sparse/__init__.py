"""paddle.sparse parity (minimal): COO/CSR tensors over jax BCOO.

Reference parity: python/paddle/sparse + phi sparse kernels
(SparseCooTensor/SparseCsrTensor — unverified, mount empty). TPU scope:
sparse formats exist in the reference mainly for recommender embeddings
and sparse research ops; none of the BASELINE configs exercise them, so
this module provides the core surface — construction, conversion,
elementwise + matmul compute — over `jax.experimental.sparse.BCOO`
(XLA-compilable scatter/gather under the hood), not the full ~100-op
sparse library.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor


def _val(x):
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        # dense materialization for paths without a sparse kernel
        return _coo(x)._bcoo.todense()
    return x.value if isinstance(x, Tensor) else jnp.asarray(x)


class SparseCooTensor:
    """COO sparse tensor (wraps a jax BCOO)."""

    def __init__(self, bcoo):
        self._bcoo = bcoo

    # -------------------------------------------------------- properties
    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    def nnz(self):
        return int(self._bcoo.nse)

    def indices(self):
        return Tensor(self._bcoo.indices.T)  # paddle layout [ndim, nnz]

    def values(self):
        return Tensor(self._bcoo.data)

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    # ------------------------------------------------------- conversion
    def to_dense(self):
        return Tensor(self._bcoo.todense())

    def coalesce(self):
        return SparseCooTensor(self._bcoo.sum_duplicates())

    # ---------------------------------------------------------- compute
    def matmul(self, other):
        return matmul(self, other)

    def __matmul__(self, other):
        return matmul(self, other)

    def __repr__(self):
        return (
            f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
            f"dtype={self.dtype})"
        )


class SparseCsrTensor:
    """CSR sparse tensor (2-D): stored as crows/cols/values; compute
    routes through COO."""

    def __init__(self, crows, cols, values, shape):
        self.crows = jnp.asarray(_val(crows), jnp.int32)
        self.cols = jnp.asarray(_val(cols), jnp.int32)
        self.data = _val(values)
        self._shape = [int(s) for s in shape]

    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        return self.data.dtype

    def nnz(self):
        return int(self.data.shape[0])

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    def crows_cols_values(self):
        return Tensor(self.crows), Tensor(self.cols), Tensor(self.data)

    def to_sparse_coo(self, sparse_dim=2):
        counts = jnp.diff(self.crows)
        rows = jnp.repeat(
            jnp.arange(self._shape[0], dtype=jnp.int32), counts,
            total_repeat_length=self.nnz(),
        )
        idx = jnp.stack([rows, self.cols], axis=1)
        return SparseCooTensor(
            jsparse.BCOO((self.data, idx), shape=tuple(self._shape))
        )

    def to_dense(self):
        return self.to_sparse_coo().to_dense()

    def __repr__(self):
        return (
            f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz()}, "
            f"dtype={self.dtype})"
        )


# -------------------------------------------------------------- creation
def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    """indices: [ndim, nnz] (paddle layout); values: [nnz, ...]."""
    idx = jnp.asarray(_val(indices), jnp.int32).T  # -> [nnz, ndim]
    vals = _val(values)
    if dtype is not None:
        from ..core.dtypes import convert_dtype

        vals = vals.astype(convert_dtype(dtype))
    if shape is None:
        if idx.shape[0] == 0:
            raise ValueError(
                "shape is required for an empty (nnz=0) sparse tensor"
            )
        shape = tuple(int(m) + 1 for m in np.asarray(idx).max(axis=0))
    return SparseCooTensor(
        jsparse.BCOO((vals, idx), shape=tuple(int(s) for s in shape))
    )


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True):
    vals = _val(values)
    if dtype is not None:
        from ..core.dtypes import convert_dtype

        vals = vals.astype(convert_dtype(dtype))
    return SparseCsrTensor(crows, cols, vals, shape)


def to_sparse_coo(x, sparse_dim=None):
    """Dense Tensor -> SparseCooTensor (reference Tensor.to_sparse_coo).
    ``sparse_dim`` keeps trailing dims dense (hybrid COO: values become
    [nnz, *dense_dims])."""
    v = _val(x)
    n_dense = 0 if sparse_dim is None else v.ndim - int(sparse_dim)
    if n_dense < 0 or n_dense > v.ndim:
        raise ValueError(
            f"sparse_dim {sparse_dim} out of range for {v.ndim}-d tensor"
        )
    return SparseCooTensor(jsparse.BCOO.fromdense(v, n_dense=n_dense))


def is_sparse(x):
    return isinstance(x, (SparseCooTensor, SparseCsrTensor))


# --------------------------------------------------------------- compute
def _coo(x):
    if isinstance(x, SparseCsrTensor):
        return x.to_sparse_coo()
    return x


def matmul(x, y, name=None):
    """sparse @ dense (the reference's spmm); dense @ dense passes
    through."""
    x = _coo(x)
    if isinstance(x, SparseCooTensor):
        out = x._bcoo @ _val(y)
        return Tensor(out)
    return Tensor(_val(x) @ _val(y))


def add(x, y, name=None):
    x, y = _coo(x), _coo(y)
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        return SparseCooTensor((x._bcoo + y._bcoo).sum_duplicates())
    if isinstance(x, SparseCooTensor):
        return Tensor(x._bcoo.todense() + _val(y))
    return Tensor(_val(x) + _val(y))


def multiply(x, y, name=None):
    """Elementwise; sparse * scalar keeps sparsity."""
    x = _coo(x)
    if isinstance(x, SparseCooTensor) and np.isscalar(y):
        return SparseCooTensor(
            jsparse.BCOO((x._bcoo.data * y, x._bcoo.indices),
                         shape=x._bcoo.shape)
        )
    if isinstance(x, SparseCooTensor):
        return Tensor(x._bcoo.todense() * _val(y))
    return Tensor(_val(x) * _val(y))


def relu(x, name=None):
    x = _coo(x)
    if isinstance(x, SparseCooTensor):
        return SparseCooTensor(
            jsparse.BCOO(
                (jnp.maximum(x._bcoo.data, 0), x._bcoo.indices),
                shape=x._bcoo.shape,
            )
        )
    return Tensor(jnp.maximum(_val(x), 0))


def subtract(x, y, name=None):
    x, y = _coo(x), _coo(y)
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        neg_y = jsparse.BCOO(
            (-y._bcoo.data, y._bcoo.indices), shape=y._bcoo.shape
        )
        return SparseCooTensor((x._bcoo + neg_y).sum_duplicates())
    return Tensor(_val(x) - _val(y))


def divide(x, y, name=None):
    """Elementwise; sparse / scalar keeps sparsity."""
    x = _coo(x)
    if isinstance(x, SparseCooTensor) and np.isscalar(y):
        return SparseCooTensor(
            jsparse.BCOO((x._bcoo.data / y, x._bcoo.indices),
                         shape=x._bcoo.shape)
        )
    return Tensor(_val(x) / _val(y))


def _value_op(name, fn):
    """Zero-preserving value-wise op: applies to nonzeros only, exactly
    the reference's sparse unary kernel contract."""

    def op(x, name=None):
        if isinstance(x, SparseCsrTensor):
            return SparseCsrTensor(
                x.crows, x.cols, fn(x.data), x.shape
            )
        if isinstance(x, SparseCooTensor):
            return SparseCooTensor(
                jsparse.BCOO((fn(x._bcoo.data), x._bcoo.indices),
                             shape=x._bcoo.shape)
            )
        return Tensor(fn(_val(x)))

    op.__name__ = name
    return op


sin = _value_op("sin", jnp.sin)
tan = _value_op("tan", jnp.tan)
asin = _value_op("asin", jnp.arcsin)
atan = _value_op("atan", jnp.arctan)
sinh = _value_op("sinh", jnp.sinh)
tanh = _value_op("tanh", jnp.tanh)
asinh = _value_op("asinh", jnp.arcsinh)
atanh = _value_op("atanh", jnp.arctanh)
sqrt = _value_op("sqrt", jnp.sqrt)
square = _value_op("square", jnp.square)
abs = _value_op("abs", jnp.abs)  # noqa: A001
neg = _value_op("neg", jnp.negative)
expm1 = _value_op("expm1", jnp.expm1)
log1p = _value_op("log1p", jnp.log1p)
deg2rad = _value_op("deg2rad", jnp.deg2rad)
rad2deg = _value_op("rad2deg", jnp.rad2deg)


def pow(x, factor, name=None):  # noqa: A001
    return _value_op("pow", lambda v: jnp.power(v, factor))(x)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    """Casts values/indices, preserving the storage format (CSR in,
    CSR out — reference contract)."""
    from ..core.dtypes import convert_dtype

    if isinstance(x, SparseCsrTensor):
        data = x.data
        crows, cols = x.crows, x.cols
        if value_dtype is not None:
            data = data.astype(convert_dtype(value_dtype))
        if index_dtype is not None:
            crows = crows.astype(convert_dtype(index_dtype))
            cols = cols.astype(convert_dtype(index_dtype))
        return SparseCsrTensor(crows, cols, data, x.shape)
    x = _coo(x)
    data, idx = x._bcoo.data, x._bcoo.indices
    if value_dtype is not None:
        data = data.astype(convert_dtype(value_dtype))
    if index_dtype is not None:
        idx = idx.astype(convert_dtype(index_dtype))
    return SparseCooTensor(jsparse.BCOO((data, idx), shape=x._bcoo.shape))


def transpose(x, perm, name=None):
    x = _coo(x)
    perm = [int(p) for p in perm]
    idx = x._bcoo.indices[:, jnp.asarray(perm)]
    shape = tuple(x._bcoo.shape[p] for p in perm)
    return SparseCooTensor(
        jsparse.BCOO((x._bcoo.data, idx), shape=shape).sum_duplicates()
    )


def reshape(x, shape, name=None):
    x = _coo(x)
    old = x._bcoo.shape
    size = int(np.prod(old))
    shape = [int(s) for s in shape]
    if shape.count(-1) > 1:
        raise ValueError(f"reshape: more than one -1 in {shape}")
    if -1 in shape:
        known = int(np.prod([s for s in shape if s != -1]))
        if known == 0 or size % known != 0:
            raise ValueError(
                f"reshape: cannot infer -1 for {size} elements into {shape}"
            )
        shape[shape.index(-1)] = size // known
    if int(np.prod(shape)) != size:
        raise ValueError(
            f"reshape: {size} elements cannot reshape to {shape}"
        )
    flat = jnp.ravel_multi_index(
        tuple(x._bcoo.indices.T), old, mode="clip"
    )
    new_idx = jnp.stack(
        jnp.unravel_index(flat, tuple(shape)), axis=1
    ).astype(jnp.int32)
    return SparseCooTensor(
        jsparse.BCOO((x._bcoo.data, new_idx), shape=tuple(shape))
    )


def sum(x, axis=None, dtype=None, keepdim=False, name=None):  # noqa: A001
    """O(nnz): reduces stored values directly (axis=None) or
    segment-sums over the kept axes — never densifies."""
    x = _coo(x)
    data, idx = x._bcoo.data, x._bcoo.indices
    shape = x._bcoo.shape
    if axis is None:
        out = jnp.sum(data)
        if keepdim:
            out = out.reshape((1,) * len(shape))
    else:
        ax = int(axis) % len(shape)
        keep = [d for d in range(len(shape)) if d != ax]
        out_shape = tuple(shape[d] for d in keep)
        if keep:
            key = jnp.ravel_multi_index(
                tuple(idx[:, d] for d in keep), out_shape, mode="clip"
            )
            flat = jnp.zeros(
                (int(np.prod(out_shape)),), data.dtype
            ).at[key].add(data)
            out = flat.reshape(out_shape)
        else:
            out = jnp.sum(data)
        if keepdim:
            out = jnp.expand_dims(out, ax)
    if dtype is not None:
        from ..core.dtypes import convert_dtype

        out = out.astype(convert_dtype(dtype))
    return Tensor(out)


def mv(x, vec, name=None):
    """sparse [M, N] @ dense vector [N] -> dense [M]."""
    x = _coo(x)
    return Tensor(x._bcoo @ _val(vec))


def masked_matmul(x, y, mask, name=None):
    """(dense @ dense) evaluated only at mask's nonzero positions —
    the reference SDDMM. Gathers the needed rows/cols per nnz: O(nnz*K)
    work, never materializing the dense product."""
    xv, yv = _val(x), _val(y)
    m = _coo(mask)
    rows = m._bcoo.indices[:, 0]
    cols = m._bcoo.indices[:, 1]
    vals = jnp.einsum("nk,nk->n", xv[rows, :], yv[:, cols].T)
    return SparseCooTensor(
        jsparse.BCOO((vals, m._bcoo.indices), shape=m._bcoo.shape)
    )


def is_same_shape(x, y):
    xs = x.shape if is_sparse(x) else list(_val(x).shape)
    ys = y.shape if is_sparse(y) else list(_val(y).shape)
    return list(xs) == list(ys)


from . import nn  # noqa: E402,F401
