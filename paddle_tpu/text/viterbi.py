"""Viterbi decoding (python/paddle/text/viterbi_decode.py parity —
unverified): max-score path through a linear-chain CRF's emission +
transition potentials. lax.scan forward pass keeps the whole decode in
one XLA program (no per-step host sync); backtrace is a reverse scan
over the stored argmax tables.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dispatch


def _viterbi(potentials, trans, lengths, *, include_bos_eos_tag):
    b, t, n = potentials.shape
    mask = (
        jnp.arange(t)[None, :] < lengths[:, None]
    )  # [B, T] valid steps

    if include_bos_eos_tag:
        # reference convention: tag n-2 = BOS, tag n-1 = EOS
        bos, eos = n - 2, n - 1
        alpha0 = potentials[:, 0] + trans[bos][None, :]
    else:
        alpha0 = potentials[:, 0]

    def step(alpha, inputs):
        emit, valid = inputs  # emit [B, N], valid [B]
        # score of arriving at tag j from best tag i
        scores = alpha[:, :, None] + trans[None, :, :]  # [B, N(from), N(to)]
        best_prev = jnp.argmax(scores, axis=1)  # [B, N]
        best_score = jnp.max(scores, axis=1) + emit
        new_alpha = jnp.where(valid[:, None], best_score, alpha)
        return new_alpha, best_prev

    emits = jnp.moveaxis(potentials[:, 1:], 1, 0)  # [T-1, B, N]
    valids = jnp.moveaxis(mask[:, 1:], 1, 0)  # [T-1, B]
    alpha, back = jax.lax.scan(step, alpha0, (emits, valids))
    if include_bos_eos_tag:
        alpha = alpha + trans[:, eos][None, :]

    scores = jnp.max(alpha, axis=-1)
    last_tag = jnp.argmax(alpha, axis=-1)  # [B]

    def backstep(tag, inputs):
        bp, valid = inputs  # bp [B, N], valid [B]
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        new_tag = jnp.where(valid, prev, tag)
        return new_tag, new_tag

    _, path_rev = jax.lax.scan(
        backstep, last_tag, (back[::-1], valids[::-1])
    )
    # path_rev[k] = tag at position T-2-k; full path = [...reversed, last]
    path = jnp.concatenate(
        [path_rev[::-1], last_tag[None]], axis=0
    )  # [T, B]
    path = jnp.moveaxis(path, 0, 1).astype(jnp.int64)  # [B, T]
    # positions beyond each length repeat the final tag; zero them for a
    # clean contract
    path = jnp.where(mask, path, 0)
    return scores, path


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """Returns (scores [B], paths [B, T]) for the best tag sequences."""
    return dispatch.apply(
        "viterbi_decode", _viterbi,
        (potentials, transition_params, lengths),
        {"include_bos_eos_tag": bool(include_bos_eos_tag)},
        nondiff=True,
    )


class ViterbiDecoder:
    """Layer-style wrapper (paddle.text.ViterbiDecoder parity)."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths):
        return viterbi_decode(
            potentials, self.transitions, lengths,
            self.include_bos_eos_tag,
        )
