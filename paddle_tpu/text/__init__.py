"""paddle.text namespace (python/paddle/text/__init__.py parity —
unverified): corpora + Viterbi decoding."""
from . import datasets  # noqa: F401
from .datasets import (  # noqa: F401
    Imdb,
    Imikolov,
    Movielens,
    UCIHousing,
    WMT14,
    WMT16,
)
from .viterbi import ViterbiDecoder, viterbi_decode  # noqa: F401
