"""paddle.text.datasets (python/paddle/text/datasets/ parity —
unverified): UCIHousing, Imdb, Imikolov, Movielens, WMT14, WMT16.

Zero-egress environment: when the real cached archives are absent each
dataset generates a DETERMINISTIC synthetic corpus with the same sample
structure (shapes, dtypes, vocab contract) as the real one, with a
warning — mirroring vision/datasets/mnist.py. Real files are used when
present:

- UCIHousing: the standard whitespace ``housing.data`` (13 features +
  target), reference normalization (feature-wise max-min scaling).
- Imdb: an ``aclImdb``-layout directory (pos/neg text files).
Other corpora (Imikolov/Movielens/WMT) have bespoke archive layouts
that cannot be verified against the empty reference mount, so they are
synthetic-only here.
"""
from __future__ import annotations

import os
import re
import warnings

import numpy as np

from ..io.dataset import Dataset

_CACHE = os.path.expanduser("~/.cache/paddle/dataset")


def _warn_synth(name):
    warnings.warn(
        f"paddle.text.datasets.{name}: real corpus not found and no "
        "network egress; serving a deterministic synthetic stand-in "
        "with the same sample structure"
    )


class UCIHousing(Dataset):
    """13 float features -> house price. mode: train/test (80/20)."""

    def __init__(self, data_file=None, mode="train", download=True):
        data_file = data_file or os.path.join(
            _CACHE, "uci_housing", "housing.data"
        )
        if os.path.exists(data_file):
            raw = np.loadtxt(data_file).astype(np.float32)
        else:
            _warn_synth("UCIHousing")
            rng = np.random.RandomState(42)
            x = rng.rand(506, 13).astype(np.float32)
            w = rng.randn(13).astype(np.float32)
            y = x @ w + 0.1 * rng.randn(506).astype(np.float32)
            raw = np.concatenate([x, y[:, None]], axis=1)
        feats = raw[:, :-1]
        mx, mn = feats.max(0), feats.min(0)
        feats = (feats - mn) / np.maximum(mx - mn, 1e-8)
        raw = np.concatenate([feats, raw[:, -1:]], axis=1)
        split = int(len(raw) * 0.8)
        self.data = raw[:split] if mode == "train" else raw[split:]

    def __len__(self):
        return len(self.data)

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1], row[-1:]


_TOKEN_RE = re.compile(r"[A-Za-z]+")


class Imdb(Dataset):
    """Movie-review sentiment: (int64 token ids, 0/1 label)."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=True):
        root = data_file or os.path.join(_CACHE, "imdb", "aclImdb")
        sub = "train" if mode == "train" else "test"
        texts, labels = [], []
        real_corpus = os.path.isdir(os.path.join(root, sub))
        if real_corpus:
            for lbl, name in ((0, "neg"), (1, "pos")):
                d = os.path.join(root, sub, name)
                for fn in sorted(os.listdir(d)):
                    with open(os.path.join(d, fn), errors="ignore") as f:
                        texts.append(_TOKEN_RE.findall(f.read().lower()))
                    labels.append(lbl)
        else:
            _warn_synth("Imdb")
            rng = np.random.RandomState(0 if mode == "train" else 1)
            pos_vocab = [f"good{i}" for i in range(50)]
            neg_vocab = [f"bad{i}" for i in range(50)]
            common = [f"word{i}" for i in range(100)]
            for i in range(512):
                lbl = int(rng.rand() > 0.5)
                pool = (pos_vocab if lbl else neg_vocab) + common
                n = rng.randint(20, 60)
                texts.append([pool[j] for j in rng.randint(0, len(pool), n)])
                labels.append(lbl)
        freq = {}
        for t in texts:
            for w in t:
                freq[w] = freq.get(w, 0) + 1
        # real corpus honors the requested frequency cutoff; the small
        # synthetic corpus would lose its whole vocab at cutoff=150, so
        # it clamps to 2
        threshold = cutoff if real_corpus else min(cutoff, 2)
        vocab = [
            w for w, c in sorted(freq.items(), key=lambda kv: -kv[1])
            if c >= threshold
        ]
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.docs = [
            np.array([self.word_idx.get(w, unk) for w in t], np.int64)
            for t in texts
        ]
        self.labels = np.array(labels, np.int64)

    def __len__(self):
        return len(self.docs)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]


class Imikolov(Dataset):
    """PTB-style n-gram LM samples: int64 vectors of length N."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, download=True):
        self.window_size = int(window_size)
        _warn_synth("Imikolov")
        rng = np.random.RandomState(2 if mode == "train" else 3)
        vocab_size = 200
        corpus = rng.randint(0, vocab_size, 20000)
        # inject bigram structure so a trained LM beats chance
        for i in range(1, len(corpus)):
            if rng.rand() < 0.5:
                corpus[i] = (corpus[i - 1] + 1) % vocab_size
        self.word_idx = {f"w{i}": i for i in range(vocab_size)}
        w = self.window_size
        self.samples = np.stack(
            [corpus[i:i + w] for i in range(len(corpus) - w)]
        ).astype(np.int64)

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        s = self.samples[idx]
        return tuple(s[i] for i in range(self.window_size))


class Movielens(Dataset):
    """(user_id, gender, age, job, movie_id, categories, title, rating)."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True):
        _warn_synth("Movielens")
        rng = np.random.RandomState(rand_seed)
        n = 4096
        users = rng.randint(1, 500, n)
        movies = rng.randint(1, 1000, n)
        # structured ratings: each user/movie has a latent quality
        uq = np.random.RandomState(7).rand(500)
        mq = np.random.RandomState(8).rand(1000)
        ratings = np.clip(
            np.round(1 + 4 * (0.5 * uq[users] + 0.5 * mq[movies])
                     + rng.randn(n) * 0.3),
            1, 5,
        )
        is_test = rng.rand(n) < test_ratio
        sel = is_test if mode == "test" else ~is_test
        self.rows = [
            (
                np.int64(users[i]), np.int64(rng.randint(0, 2)),
                np.int64(rng.randint(1, 7)), np.int64(rng.randint(0, 21)),
                np.int64(movies[i]),
                np.array(rng.randint(0, 19, 3), np.int64),
                np.array(rng.randint(0, 5000, 4), np.int64),
                np.float32(ratings[i]),
            )
            for i in range(n) if sel[i]
        ]

    def __len__(self):
        return len(self.rows)

    def __getitem__(self, idx):
        return self.rows[idx]


class _WMTBase(Dataset):
    """Synthetic translation pairs: (src ids, trg ids, trg_next ids)."""

    BOS, EOS, UNK = 0, 1, 2

    def __init__(self, mode, dict_size, seed):
        self.dict_size = max(int(dict_size), 10)
        rng = np.random.RandomState(seed + (0 if mode == "train" else 1))
        self.pairs = []
        for _ in range(1024):
            n = rng.randint(4, 12)
            src = rng.randint(3, self.dict_size, n)
            # target = reversed source with an offset (learnable mapping)
            trg = ((src[::-1] + 1) % (self.dict_size - 3)) + 3
            src_ids = np.array(src, np.int64)
            trg_in = np.array([self.BOS, *trg], np.int64)
            trg_next = np.array([*trg, self.EOS], np.int64)
            self.pairs.append((src_ids, trg_in, trg_next))

    def __len__(self):
        return len(self.pairs)

    def __getitem__(self, idx):
        return self.pairs[idx]


class WMT14(_WMTBase):
    def __init__(self, data_file=None, mode="train", dict_size=30000,
                 download=True):
        _warn_synth("WMT14")
        super().__init__(mode, dict_size, seed=14)


class WMT16(_WMTBase):
    def __init__(self, data_file=None, mode="train", src_dict_size=30000,
                 trg_dict_size=30000, lang="en", download=True):
        _warn_synth("WMT16")
        super().__init__(mode, src_dict_size, seed=16)
