"""paddle.version parity (reference: generated python/paddle/version/
__init__.py — unverified). The TPU rebuild reports its own version and
the reference major.minor it tracks for API-surface parity."""
full_version = "3.0.0+tpu"
major = "3"
minor = "0"
patch = "0"
rc = "0"
commit = "tpu-native-rebuild"
istaged = False
with_gpu = "OFF"
with_xpu = "OFF"
xpu_xccl = "OFF"
cuda_version = "False"
cudnn_version = "False"
tensorrt_version = "False"


def show():
    print(f"full_version: {full_version}")
    print(f"commit: {commit}")


def cuda():
    return False


def cudnn():
    return False
