"""Int8 KV-cache storage — real narrow-dtype residency for serving.

The bf16 KV caches already halved decode HBM vs fp32; this module
halves it again: K/V live as **int8 values + per-(slot, kv-head) fp32
scales** (symmetric absmax over the head dim), so a resident token
costs ``kvH * (D + 4)`` bytes instead of ``kvH * D * 2``. At flagship
head dims (D=128) that is ~1.94x fewer bytes per resident token —
compounding multiplicatively with the paged pool's per-length claims
(PR 7) at the millions-of-users concurrency ceiling.

Design contract (every call site shares these invariants):

- :class:`QuantizedKV` is a registered jax pytree, so the engines'
  flat cache lists, jit carries, scans and donation all work unchanged
  — a cache entry is simply two leaves (``q`` int8, ``scale`` fp32)
  instead of one.
- **Quantize-on-write**: every cache write path (prefill's
  ``dynamic_update_slice``, the per-row decode scatter, the paged
  (page, offset) scatter, slab/page adoption) quantizes the incoming
  tokens with :func:`quantize_kv` — per token, per kv head, absmax/127
  — so the SAME token quantizes identically in ``net.generate``, the
  slab engine and the paged engine (quantized token streams stay
  exact-equal across all three; tier-1-pinned).
- **Dequant-on-read**: the composed attention paths dequantize the
  gathered cache to the compute dtype right before the masked SDPA;
  the tuned paged-attention kernel dequantizes page blocks in VMEM
  instead (the int8 arrays are what crosses HBM either way).
- Zero-initialized storage dequantizes to exact zeros (garbage pages /
  masked columns keep contributing exact 0 through the fp32 softmax —
  the discipline that makes recycled slots safe without scrubbing).

Accuracy is a *ratcheted budget*, not a vibe: ``tests/test_serving.py``
pins the greedy-decode agreement length and the prefill-logit
max-abs-err of int8-KV decode against the bf16 baseline.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

# symmetric int8: values in [-127, 127] (the -128 code is unused so the
# scale maps absmax exactly onto the grid edge)
QMAX = 127.0
# absmax floor: an all-zero token must quantize to (0, tiny-scale) and
# dequantize to exact 0 rather than divide by zero
_EPS = 1e-8

# dtype names alloc_kv_caches accepts (the models/generation API seam
# validates against this set — see normalize_cache_dtype there)
QUANT_CACHE_DTYPES = ("int8",)


@jax.tree_util.register_pytree_node_class
class QuantizedKV:
    """One quantized cache array: ``q`` int8 ``[..., S, kvH, D]`` plus
    ``scale`` fp32 ``[..., S, kvH]`` (one scale per stored token per kv
    head). Behaves as a pytree of its two leaves, so jit carries, scan,
    flatten and donation treat it like any cache array pair."""

    __slots__ = ("q", "scale")

    def __init__(self, q, scale):
        self.q = q
        self.scale = scale

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    # the pool/engine dtype checks read `.dtype` off cache arrays
    @property
    def dtype(self):
        return self.q.dtype

    @property
    def shape(self):
        return self.q.shape

    def __repr__(self):
        return (f"QuantizedKV(q={getattr(self.q, 'shape', None)}, "
                f"scale={getattr(self.scale, 'shape', None)})")


def is_quantized(cache):
    return isinstance(cache, QuantizedKV)


def alloc_quantized(shape):
    """Zeroed int8 storage + zeroed scales for a cache of logical shape
    ``[..., S, kvH, D]`` (zero scales dequantize to exact zeros)."""
    return QuantizedKV(
        jnp.zeros(shape, jnp.int8),
        jnp.zeros(shape[:-1], jnp.float32),
    )


def quantize_kv(x):
    """``[..., D]`` float -> (int8 values ``[..., D]``, fp32 scales
    ``[...]``). Symmetric per-vector absmax: scale = max|x| / 127,
    rounded through bf16 before use. The rounding is what makes int8
    KV provenance-independent at the byte level: different compiled
    programs computing the same position (full prefill, chunked tail,
    S=1 decode step) may reduce ``max|x|`` in different tree shapes
    and disagree by one float32 ulp — a bf16-grid scale absorbs that,
    so a decode-written page is bitwise what re-prefilling those
    tokens writes (the serving prefix cache's decode-publish pin).
    Cost: <=2^-9 relative scale error, well under int8's own 1/127
    step."""
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1)
    scale = (jnp.maximum(absmax, _EPS) / QMAX) \
        .astype(jnp.bfloat16).astype(jnp.float32)
    q = jnp.clip(
        jnp.round(xf / scale[..., None]), -QMAX, QMAX
    ).astype(jnp.int8)  # tpu-lint: quant
    return q, scale


def dequantize_kv(q, scale, dtype):
    """int8 values + scales -> dense array in the compute ``dtype``."""
    return (
        q.astype(jnp.float32) * scale[..., None]
    ).astype(dtype)  # tpu-lint: quant


def kv_token_bytes(kv_heads, head_dim, dtype):
    """HBM bytes ONE cached token costs per K-or-V array in ``dtype``
    (int8 counts its fp32 scale overhead — the equal-HBM concurrency
    comparisons must not flatter quantized pools)."""
    dt = jnp.dtype(dtype)
    if dt == jnp.int8:
        return kv_heads * (head_dim * dt.itemsize
                           + jnp.dtype(jnp.float32).itemsize)
    return kv_heads * head_dim * dt.itemsize


# ------------------------------------------------------------- cache writes
#
# Each helper mirrors one existing bf16 write site in models/llama.py /
# the serving adopt programs, handling both plain cache arrays (exactly
# today's op sequence — byte-identical behavior) and QuantizedKV.


def write_at_pos(cache, val, pos):
    """Prefill / whole-batch decode write: ``val`` ``[B, S, kvH, D]``
    lands at positions ``[pos, pos + S)`` (scalar traced ``pos``)."""
    z = jnp.zeros((), pos.dtype)
    if is_quantized(cache):
        q, s = quantize_kv(val)
        return QuantizedKV(
            jax.lax.dynamic_update_slice(cache.q, q, (z, pos, z, z)),
            jax.lax.dynamic_update_slice(cache.scale, s, (z, pos, z)),
        )
    return jax.lax.dynamic_update_slice(
        cache, val.astype(cache.dtype), (z, pos, z, z)
    )


def write_at_rows(cache, val, rows, cols):
    """Per-row decode write (continuous batching): ``val`` ``[B, S,
    kvH, D]`` scattered at each row's own depth (``rows``/``cols`` as
    in the slab decode path)."""
    if is_quantized(cache):
        q, s = quantize_kv(val)
        return QuantizedKV(
            cache.q.at[rows, cols].set(q),
            cache.scale.at[rows, cols].set(s),
        )
    return cache.at[rows, cols].set(val.astype(cache.dtype))


def write_paged(cache, val, page, offset):
    """Paged decode write: ``val`` ``[B, kvH, D]`` (this step's token
    per row) scattered at each row's ``(page, offset)``."""
    if is_quantized(cache):
        q, s = quantize_kv(val)
        return QuantizedKV(
            cache.q.at[page, offset].set(q),
            cache.scale.at[page, offset].set(s),
        )
    return cache.at[page, offset].set(val.astype(cache.dtype))


def read_dense(cache, dtype):
    """The composed attention read: the full cache as a dense array in
    the compute ``dtype`` (dequant-on-read for int8; pass-through for
    plain arrays — attention upcasts at the matmul as before)."""
    if is_quantized(cache):
        return dequantize_kv(cache.q, cache.scale, dtype)
    return cache


def slab_row_block(cache, slot):
    """Inverse of :func:`adopt_into_slab`: the ``[1, S, ...]`` block of
    decode-slab row ``slot`` (traced) — how the speculative verify
    program materializes one request's KV as a prefill-layout block."""
    if is_quantized(cache):
        return QuantizedKV(
            jax.lax.dynamic_slice_in_dim(cache.q, slot, 1, axis=0),
            jax.lax.dynamic_slice_in_dim(cache.scale, slot, 1, axis=0),
        )
    return jax.lax.dynamic_slice_in_dim(cache, slot, 1, axis=0)


def broadcast_rows(cache, n):
    """``[1, S, ...]`` block -> ``[n, S, ...]`` broadcast: the
    speculative verify re-read gives every proposed position its own
    batch row over the SAME written content, so one decode-shaped
    program scores all K+1 positions at per-row positions."""
    if is_quantized(cache):
        return QuantizedKV(
            jnp.broadcast_to(cache.q, (n,) + cache.q.shape[1:]),
            jnp.broadcast_to(cache.scale, (n,) + cache.scale.shape[1:]),
        )
    return jnp.broadcast_to(cache, (n,) + cache.shape[1:])


# ----------------------------------------------------------- adopt programs


def adopt_into_slab(dst, blk, slot):
    """One leaf of the slab engine's adopt program: copy a prefilled
    ``[1, bucket, ...]`` block into decode row ``slot`` (traced)."""
    z = jnp.zeros((), slot.dtype)
    if is_quantized(dst):
        return QuantizedKV(
            jax.lax.dynamic_update_slice(dst.q, blk.q, (slot, z, z, z)),
            jax.lax.dynamic_update_slice(dst.scale, blk.scale,
                                         (slot, z, z)),
        )
    return jax.lax.dynamic_update_slice(
        dst, blk.astype(dst.dtype), (slot, z, z, z)
    )


def gather_block_from_pages(arena, page_ids, n_pages, page_size):
    """The inverse of :func:`adopt_into_pages`: materialize ``n_pages``
    arena pages at traced ``page_ids`` as one prefill-layout block
    ``[1, n_pages * page_size, ...]`` — the serving prefix cache uses it
    to rebuild a request's cached-prefix KV so the chunked prefill can
    attend over it (ids past the cached span point at the garbage page
    0; its content sits behind the position mask like any stale slot)."""
    if is_quantized(arena):
        kvh = arena.q.shape[2]
        d = arena.q.shape[3]
        return QuantizedKV(
            arena.q[page_ids].reshape(1, n_pages * page_size, kvh, d),
            arena.scale[page_ids].reshape(1, n_pages * page_size, kvh),
        )
    return arena[page_ids].reshape(
        1, n_pages * page_size, arena.shape[2], arena.shape[3]
    )


def adopt_into_pages(arena, blk, page_ids, n_pages, page_size):
    """One leaf of the paged engine's adopt program: scatter a
    prefilled ``[1, bucket, ...]`` block into the arena as ``n_pages``
    whole pages at traced ``page_ids`` (tail ids -> garbage page 0)."""
    if is_quantized(arena):
        kvh = blk.q.shape[2]
        d = blk.q.shape[3]
        return QuantizedKV(
            arena.q.at[page_ids].set(
                blk.q[0].reshape(n_pages, page_size, kvh, d)
            ),
            arena.scale.at[page_ids].set(
                blk.scale[0].reshape(n_pages, page_size, kvh)
            ),
        )
    b = blk
    return arena.at[page_ids].set(
        b[0].reshape(n_pages, page_size, b.shape[2],
                     b.shape[3]).astype(arena.dtype)
    )
