"""QAT (reference: python/paddle/quantization/qat.py — unverified):
wrap target layers so forward applies fake-quant to weights and
activations; training gradients flow via the STE."""
from __future__ import annotations

from ..nn.layer.layers import Layer
from .quanters import fake_quant


class QuantedWrapper(Layer):
    """Wraps one layer: fake-quant input activation + weight, then run
    the wrapped layer with the quantized weight."""

    def __init__(self, inner, act_quanter=None, weight_quanter=None):
        super().__init__()
        self._inner = inner
        self._act_quanter = (
            act_quanter._instance() if act_quanter is not None else None
        )
        self._weight_quanter = (
            weight_quanter._instance() if weight_quanter is not None
            else None
        )

    def forward(self, x, *args, **kw):
        if self._act_quanter is not None:
            x = self._act_quanter(x)
        wq = self._weight_quanter
        if wq is not None and hasattr(self._inner, "weight"):
            w = self._inner.weight
            if hasattr(wq, "observe"):  # observer-style (per-channel etc.)
                wq.observe(w)
                wfq = fake_quant(w, wq.scales(), wq.quant_bits)
            else:  # quanter-style (moving-average fake quanter)
                wfq = wq(w)
            orig = w
            try:
                object.__setattr__(self._inner, "weight", wfq)
                return self._inner(x, *args, **kw)
            finally:
                object.__setattr__(self._inner, "weight", orig)
        return self._inner(x, *args, **kw)


class ObservedLayer(Layer):
    """Post-convert layer: quant arithmetic with FROZEN scales baked in
    (what jit.save exports)."""

    def __init__(self, inner, act_scale, weight_scale, quant_bits=8):
        super().__init__()
        self._inner = inner
        self.act_scale = act_scale
        self.weight_scale = weight_scale
        self.quant_bits = quant_bits

    def forward(self, x, *args, **kw):
        if self.act_scale is not None:
            x = fake_quant(x, self.act_scale, self.quant_bits)
        if self.weight_scale is not None and hasattr(self._inner, "weight"):
            w = self._inner.weight
            orig = w
            try:
                object.__setattr__(
                    self._inner, "weight",
                    fake_quant(w, self.weight_scale, self.quant_bits),
                )
                return self._inner(x, *args, **kw)
            finally:
                object.__setattr__(self._inner, "weight", orig)
        return self._inner(x, *args, **kw)


def _swap_layers(model, make):
    """Replace matching sublayers in place (reference quantize walks
    and replaces named children)."""
    for name, child in list(model._sub_layers.items()):
        replacement = make(child)
        if replacement is not None:
            model._sub_layers[name] = replacement
        else:
            _swap_layers(child, make)
    return model


class QAT:
    def __init__(self, config):
        self._config = config

    def quantize(self, model, inplace=False):
        if not inplace:
            import copy

            model = copy.deepcopy(model)

        def make(layer):
            cfg = self._config._config_for(layer)
            if cfg is None or isinstance(layer, QuantedWrapper):
                return None
            return QuantedWrapper(
                layer, cfg.get("activation"), cfg.get("weight")
            )

        return _swap_layers(model, make)

    def convert(self, model, inplace=False):
        """Freeze the learned scales into ObservedLayers."""
        if not inplace:
            import copy

            model = copy.deepcopy(model)

        def make(layer):
            if not isinstance(layer, QuantedWrapper):
                return None
            aq = layer._act_quanter
            act_scale = (
                (aq.scales() if hasattr(aq, "observe") else aq.scale())
                if aq is not None else None
            )
            w_scale = None
            bits = 8
            wq = layer._weight_quanter
            if wq is not None and hasattr(layer._inner, "weight"):
                if hasattr(wq, "observe"):
                    wq.observe(layer._inner.weight)
                    w_scale = wq.scales()
                else:
                    wq(layer._inner.weight)
                    w_scale = wq.scale()
                bits = wq.quant_bits
            if aq is not None:
                bits = aq.quant_bits
            return ObservedLayer(layer._inner, act_scale, w_scale, bits)

        return _swap_layers(model, make)
