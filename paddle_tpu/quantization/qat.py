"""QAT (reference: python/paddle/quantization/qat.py — unverified):
wrap target layers so forward applies fake-quant to weights and
activations; training gradients flow via the STE."""
from __future__ import annotations

from ..nn.layer.layers import Layer
from .quanters import fake_quant


class QuantedWrapper(Layer):
    """Wraps one layer: fake-quant input activation + weight, then run
    the wrapped layer with the quantized weight."""

    def __init__(self, inner, act_quanter=None, weight_quanter=None):
        super().__init__()
        self._inner = inner
        self._act_quanter = (
            act_quanter._instance() if act_quanter is not None else None
        )
        self._weight_quanter = (
            weight_quanter._instance() if weight_quanter is not None
            else None
        )

    def forward(self, x, *args, **kw):
        if self._act_quanter is not None:
            x = self._act_quanter(x)
        wq = self._weight_quanter
        if wq is not None and hasattr(self._inner, "weight"):
            w = self._inner.weight
            if hasattr(wq, "observe"):  # observer-style (per-channel etc.)
                wq.observe(w)
                wfq = fake_quant(w, wq.scales(), wq.quant_bits)
            else:  # quanter-style (moving-average fake quanter)
                wfq = wq(w)
            orig = w
            try:
                object.__setattr__(self._inner, "weight", wfq)
                return self._inner(x, *args, **kw)
            finally:
                object.__setattr__(self._inner, "weight", orig)
        return self._inner(x, *args, **kw)


class ObservedLayer(Layer):
    """Post-convert layer: quant arithmetic with FROZEN scales baked in
    (what jit.save exports). Activation and weight bit widths are
    tracked separately (they may differ per config)."""

    def __init__(self, inner, act_scale, weight_scale, act_bits=8,
                 weight_bits=8):
        super().__init__()
        self._inner = inner
        self.act_scale = act_scale
        self.weight_scale = weight_scale
        self.act_bits = act_bits
        self.weight_bits = weight_bits

    def forward(self, x, *args, **kw):
        if self.act_scale is not None:
            x = fake_quant(x, self.act_scale, self.act_bits)
        if self.weight_scale is not None and hasattr(self._inner, "weight"):
            w = self._inner.weight
            orig = w
            try:
                object.__setattr__(
                    self._inner, "weight",
                    fake_quant(w, self.weight_scale, self.weight_bits),
                )
                return self._inner(x, *args, **kw)
            finally:
                object.__setattr__(self._inner, "weight", orig)
        return self._inner(x, *args, **kw)


# layers the walker must never descend into (their _inner would be
# matched and double-wrapped). QuantizedLinear (serving.py) matches by
# name to avoid a circular import: wrapping an already-int8 layer in a
# fake-quant wrapper (or re-quantizing it) would double-round weights.
def _is_quant_layer(layer):
    return isinstance(layer, (QuantedWrapper, ObservedLayer)) or (
        type(layer).__name__ in ("_ObservingWrapper", "QuantizedLinear")
    )


def _swap_layers(model, make):
    """Replace matching sublayers in place (reference quantize walks
    and replaces named children). Does not recurse into already-
    quantized wrappers."""
    for name, child in list(model._sub_layers.items()):
        replacement = make(child)
        if replacement is not None:
            model._sub_layers[name] = replacement
        elif not _is_quant_layer(child):
            _swap_layers(child, make)
    return model


def _named_paths(model, prefix=""):
    for name, child in model._sub_layers.items():
        path = f"{prefix}.{name}" if prefix else name
        yield path, child
        if not _is_quant_layer(child):
            yield from _named_paths(child, path)


def _layer_by_path(model, path):
    cur = model
    for part in path.split("."):
        cur = cur._sub_layers[part]
    return cur


def _resolve_then_copy(model, config, inplace):
    """Resolve per-layer configs on the ORIGINAL model (so id()-based
    add_layer_config overrides survive deepcopy), then copy."""
    resolved = {
        path: config._config_for(layer)
        for path, layer in _named_paths(model)
    }
    if not inplace:
        import copy

        model = copy.deepcopy(model)
    by_id = {
        id(_layer_by_path(model, path)): cfg
        for path, cfg in resolved.items()
    }
    return model, by_id


class QAT:
    def __init__(self, config):
        self._config = config

    def quantize(self, model, inplace=False):
        model, by_id = _resolve_then_copy(model, self._config, inplace)

        def make(layer):
            cfg = by_id.get(id(layer))
            if cfg is None or _is_quant_layer(layer):
                return None
            return QuantedWrapper(
                layer, cfg.get("activation"), cfg.get("weight")
            )

        return _swap_layers(model, make)

    def convert(self, model, inplace=False):
        """Freeze the learned scales into ObservedLayers."""
        if not inplace:
            import copy

            model = copy.deepcopy(model)

        def make(layer):
            if not isinstance(layer, QuantedWrapper):
                return None
            aq = layer._act_quanter
            act_scale = None
            act_bits = 8
            if aq is not None:
                act_scale = (
                    aq.scales() if hasattr(aq, "observe") else aq.scale()
                )
                act_bits = aq.quant_bits
            w_scale = None
            w_bits = 8
            wq = layer._weight_quanter
            if wq is not None and hasattr(layer._inner, "weight"):
                if hasattr(wq, "observe"):
                    wq.observe(layer._inner.weight)
                    w_scale = wq.scales()
                elif wq._initialized:
                    # freeze the TRAINED moving-average scale; do not
                    # run another EMA update here
                    w_scale = wq.scale()
                else:
                    import numpy as _np

                    wq._state = float(_np.abs(
                        _np.asarray(layer._inner.weight.numpy())
                    ).max(initial=0.0))
                    wq._initialized = True
                    w_scale = wq.scale()
                w_bits = wq.quant_bits
            return ObservedLayer(
                layer._inner, act_scale, w_scale, act_bits, w_bits
            )

        return _swap_layers(model, make)
