"""Observers: track activation/weight ranges to derive quant scales
(reference: python/paddle/quantization/observers/abs_max.py et al. —
unverified)."""
from __future__ import annotations

import numpy as np


class _ObserverFactory:
    """Reference API shape: config holds a factory; one instance is
    materialized per observed tensor via ``_instance()``."""

    def __init__(self, cls, **kw):
        self._cls = cls
        self._kw = kw

    def _instance(self):
        return self._cls(**self._kw)


class BaseObserver:
    def __init__(self, quant_bits=8):
        self.quant_bits = int(quant_bits)
        self._qmax = float(2 ** (self.quant_bits - 1) - 1)

    def observe(self, value):
        raise NotImplementedError

    def scales(self):
        raise NotImplementedError


class _AbsmaxObserver(BaseObserver):
    """Running max of |x| over observed batches -> per-tensor scale."""

    def __init__(self, quant_bits=8):
        super().__init__(quant_bits)
        self._absmax = 0.0

    def observe(self, value):
        v = np.asarray(value.numpy() if hasattr(value, "numpy") else value)
        self._absmax = max(self._absmax, float(np.abs(v).max(initial=0.0)))

    def scales(self):
        return max(self._absmax, 1e-8) / self._qmax


class _PerChannelAbsmaxObserver(BaseObserver):
    """Per-output-channel |w| max (weights; channel axis configurable)."""

    def __init__(self, quant_bits=8, channel_axis=-1):
        super().__init__(quant_bits)
        self.channel_axis = channel_axis
        self._absmax = None

    def observe(self, value):
        v = np.asarray(value.numpy() if hasattr(value, "numpy") else value)
        axes = tuple(
            i for i in range(v.ndim)
            if i != (self.channel_axis % v.ndim)
        )
        cur = np.abs(v).max(axis=axes) if axes else np.abs(v)
        self._absmax = (
            cur if self._absmax is None else np.maximum(self._absmax, cur)
        )

    def scales(self):
        return np.maximum(self._absmax, 1e-8) / self._qmax


def AbsmaxObserver(quant_bits=8):
    return _ObserverFactory(_AbsmaxObserver, quant_bits=quant_bits)


def PerChannelAbsmaxObserver(quant_bits=8, channel_axis=-1):
    return _ObserverFactory(
        _PerChannelAbsmaxObserver, quant_bits=quant_bits,
        channel_axis=channel_axis,
    )
