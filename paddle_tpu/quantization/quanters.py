"""Fake quanters: quant->round->dequant in the graph with a
straight-through estimator (reference: python/paddle/quantization/
quanters/abs_max.py FakeQuanterWithAbsMaxObserver — unverified)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dispatch
from .observers import _ObserverFactory


def _fake_quant(x, scale, *, qmax):
    q = jnp.clip(jnp.round(x / scale), -qmax - 1, qmax)
    dq = q * scale
    # straight-through estimator: forward = dq, gradient = identity
    return x + jax.lax.stop_gradient(dq - x)


def fake_quant(x, scale, quant_bits=8):
    """Public helper: simulate b-bit symmetric quantization of x."""
    from ..core.tensor import Tensor

    if not isinstance(scale, Tensor):
        scale = Tensor(jnp.asarray(scale, jnp.float32))
    return dispatch.apply(
        "fake_quant", _fake_quant, (x, scale),
        {"qmax": float(2 ** (int(quant_bits) - 1) - 1)},
    )


class _FakeQuanter:
    """Moving-average absmax scale + STE fake quant (QAT activation
    quanter). Stateful like the reference (the scale is part of the
    layer's quant state)."""

    def __init__(self, quant_bits=8, moving_rate=0.9):
        self.quant_bits = int(quant_bits)
        self._qmax = float(2 ** (self.quant_bits - 1) - 1)
        self.moving_rate = float(moving_rate)
        self._state = 0.0
        self._initialized = False

    def scale(self):
        return max(self._state, 1e-8) / self._qmax

    def __call__(self, x):
        import numpy as np

        cur = float(np.abs(np.asarray(x.numpy())).max(initial=0.0))
        if not self._initialized:
            self._state = cur
            self._initialized = True
        else:
            self._state = (
                self.moving_rate * self._state
                + (1.0 - self.moving_rate) * cur
            )
        return fake_quant(x, self.scale(), self.quant_bits)


def FakeQuanterWithAbsMaxObserver(quant_bits=8, moving_rate=0.9):
    return _ObserverFactory(
        _FakeQuanter, quant_bits=quant_bits, moving_rate=moving_rate
    )
