"""quantize_for_serving — real int8 weight-only execution for deploy.

The PTQ/QAT stack simulates quantization (fake-quant: scales learned,
arithmetic still wide). This pass makes it REAL for the serving/decode
path: every eligible ``nn.Linear`` (and every PTQ/QAT-converted
``ObservedLayer`` wrapping one) is replaced by a
:class:`QuantizedLinear` that stores its weight as **int8 values + a
per-output-channel fp32 scale** — registered as persistable buffers,
so the narrow weights flow unchanged through ``state_dict``,
``jit.save`` artifacts (``Predictor.into_engine()`` serves them), and
the serving engines' weight snapshots. Forward runs through
``kernels/int8_matmul``: composed dequant->matmul by default, the
fused dequant-epilogue Pallas kernel when the tune cache opts it in.

The pass is IDEMPOTENT: quantizing an already-quantized model returns
it unchanged (already-int8 weights must never be re-quantized — a
second rounding pass would silently degrade them; tier-1-pinned).

Scale derivation: an ``ObservedLayer`` carrying a per-channel observed
weight scale keeps its CALIBRATED scales (the PTQ/QAT -> serve chain);
a bare Linear (or a per-tensor observed scale) gets fresh symmetric
absmax-per-output-channel scales from the weight itself — for
weight-only quantization the weight is fully known, so calibration
data is not required.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from .qat import ObservedLayer, _swap_layers


class QuantizedLinear(Layer):
    """Weight-only int8 Linear: ``y = x @ dequant(weight_q, scale) + b``.

    ``weight_q`` (int8 ``[in, out]``) and ``weight_scale`` (fp32
    ``[out]``) are persistable BUFFERS — not parameters — so optimizer
    walks skip them while snapshots/exports carry them. Kernel choice
    is per-call-shape tune-cache opt-in (``int8_matmul_select``): with
    no measured entry the composed dequant->matmul runs."""

    def __init__(self, weight_q, weight_scale, bias=None):
        super().__init__()
        wq = jnp.asarray(weight_q)
        ws = jnp.asarray(weight_scale, jnp.float32)
        if wq.dtype != jnp.int8:
            raise ValueError(f"weight_q must be int8, got {wq.dtype}")
        if wq.ndim != 2 or ws.shape != (wq.shape[1],):
            raise ValueError(
                f"expected weight_q [in, out] with per-out-channel "
                f"scale [out]; got {wq.shape} / {ws.shape}"
            )
        self.in_features = int(wq.shape[0])
        self.out_features = int(wq.shape[1])
        self.register_buffer("weight_q", Tensor(wq, stop_gradient=True))
        self.register_buffer("weight_scale",
                             Tensor(ws, stop_gradient=True))
        if bias is not None:
            self.register_buffer(
                "bias", Tensor(jnp.asarray(
                    bias.value if isinstance(bias, Tensor) else bias
                ), stop_gradient=True)
            )
        else:
            self.bias = None

    def forward(self, x):
        from ..kernels.int8_matmul import (
            int8_matmul_apply,
            int8_matmul_select,
        )

        rows = 1
        for s in x.shape[:-1]:
            rows *= int(s)
        cfg = int8_matmul_select(rows, self.in_features,
                                 self.out_features)
        y = int8_matmul_apply(x, self.weight_q, self.weight_scale,
                              config=cfg)
        if self.bias is not None:
            y = y + self.bias
        return y

    def extra_repr(self):
        return (f"in_features={self.in_features}, "
                f"out_features={self.out_features}, dtype=int8")


def quantize_linear_weight(weight):
    """Float ``[in, out]`` weight -> (int8 values, fp32 ``[out]``
    per-output-channel scales) — the kernel module's symmetric absmax
    quantizer (ONE home for the rounding rule)."""
    from ..kernels.int8_matmul import quantize_weight

    w = weight.value if isinstance(weight, Tensor) else jnp.asarray(
        weight
    )
    return quantize_weight(w)


def _requantize_with_scales(weight, scales):
    """Quantize ``[in, out]`` with CALIBRATED per-channel scales (the
    PTQ/QAT observed absmax path — divide by the frozen scale instead
    of deriving a fresh one; the rounding rule itself lives in
    ``kernels/int8_matmul.quantize_weight_with_scales``)."""
    from ..kernels.int8_matmul import quantize_weight_with_scales

    w = weight.value if isinstance(weight, Tensor) else jnp.asarray(
        weight
    )
    return quantize_weight_with_scales(w, scales)


def _is_linear(layer):
    from ..nn.layer.common import Linear

    return isinstance(layer, Linear)


def _from_linear(lin):
    wq, ws = quantize_linear_weight(lin.weight)
    return QuantizedLinear(wq, ws, bias=lin.bias)


def _from_observed(obs):
    inner = obs._inner
    if not _is_linear(inner):
        return None
    ws = obs.weight_scale
    per_channel = (
        ws is not None
        and int(obs.weight_bits) == 8
        and np.ndim(ws) == 1
        and np.shape(ws)[0] == int(inner.weight.shape[-1])
    )
    if per_channel:
        wq, s = _requantize_with_scales(inner.weight, ws)
        return QuantizedLinear(wq, s, bias=inner.bias)
    # per-tensor / non-8-bit observed scales: fall back to fresh
    # per-channel absmax (strictly tighter than a per-tensor scale)
    return _from_linear(inner)


def quantize_for_serving(model, inplace=False):
    """Convert a trained / PTQ'd / QAT-converted model's Linear weights
    to ``(int8, scale)`` pairs executed by the int8 matmul kernels.

    Returns the converted model (a deep copy unless ``inplace=True``).
    Calling it again on the result is a no-op (idempotent)."""
    if not inplace:
        import copy

        model = copy.deepcopy(model)

    def make(layer):
        if isinstance(layer, QuantizedLinear):
            return None  # idempotence: never re-round int8 weights
        if isinstance(layer, ObservedLayer):
            return _from_observed(layer)
        if _is_linear(layer):
            return _from_linear(layer)
        return None

    return _swap_layers(model, make)
