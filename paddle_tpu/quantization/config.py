"""QuantConfig (reference: python/paddle/quantization/config.py —
unverified): which layers get quantized and with what observers."""
from __future__ import annotations


class QuantConfig:
    def __init__(self, activation=None, weight=None):
        self._global_activation = activation
        self._global_weight = weight
        self._type_configs = {}
        self._layer_configs = {}

    def add_type_config(self, layer_types, activation=None, weight=None):
        if not isinstance(layer_types, (list, tuple)):
            layer_types = [layer_types]
        for t in layer_types:
            self._type_configs[t] = {
                "activation": activation, "weight": weight,
            }

    def add_layer_config(self, layers, activation=None, weight=None):
        if not isinstance(layers, (list, tuple)):
            layers = [layers]
        for layer in layers:
            self._layer_configs[id(layer)] = {
                "activation": activation, "weight": weight,
            }

    def _config_for(self, layer):
        if id(layer) in self._layer_configs:
            return self._layer_configs[id(layer)]
        for t, cfg in self._type_configs.items():
            if isinstance(layer, t):
                return cfg
        if self._global_activation or self._global_weight:
            return {
                "activation": self._global_activation,
                "weight": self._global_weight,
            }
        return None
