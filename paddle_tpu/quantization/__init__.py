"""paddle.quantization parity (python/paddle/quantization/ — unverified):
QuantConfig + QAT/PTQ over fake-quant simulation.

TPU design: TRAINING-time quantization is *simulated* (fake-quant) —
scales are learned/observed and quant/dequant round-trips run in the
graph with a straight-through estimator, exactly the reference's
QAT/PTQ training semantics; ``convert`` bakes the final scales into
ObservedLayers. SERVING-time quantization is REAL narrow-dtype
execution: ``quantize_for_serving`` converts the weights to
(int8, per-channel scale) pairs executed by the Pallas weight-only
matmul (``kernels/int8_matmul``), and ``kv.QuantizedKV`` stores the
serving KV caches as int8 values + per-token scales (the paged pools'
``cache_dtype="int8"``), halving weight and KV HBM again under bf16.
"""
from .config import QuantConfig  # noqa: F401
from .observers import (  # noqa: F401
    AbsmaxObserver,
    PerChannelAbsmaxObserver,
)
from .qat import QAT  # noqa: F401
from .ptq import PTQ  # noqa: F401
from .quanters import FakeQuanterWithAbsMaxObserver  # noqa: F401
from .serving import (  # noqa: F401
    QuantizedLinear,
    quantize_for_serving,
)
from . import kv  # noqa: F401
