"""paddle.quantization parity (python/paddle/quantization/ — unverified):
QuantConfig + QAT/PTQ over fake-quant simulation.

TPU design: quantization here is *simulated* (fake-quant) — scales are
learned/observed and quant/dequant round-trips run in the graph with a
straight-through estimator, exactly the reference's QAT/PTQ training
semantics. True int8 matmul execution is a deployment-backend concern
(the reference hands that to TensorRT/Paddle-Lite; this build's analog
would be XLA int8 dots) and is out of scope — ``convert`` bakes the
final scales into ObservedLayers so the exported StableHLO carries the
quant arithmetic explicitly.
"""
from .config import QuantConfig  # noqa: F401
from .observers import (  # noqa: F401
    AbsmaxObserver,
    PerChannelAbsmaxObserver,
)
from .qat import QAT  # noqa: F401
from .ptq import PTQ  # noqa: F401
from .quanters import FakeQuanterWithAbsMaxObserver  # noqa: F401
