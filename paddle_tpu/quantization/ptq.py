"""PTQ (reference: python/paddle/quantization/ptq.py — unverified):
insert observers, run calibration batches, freeze scales on convert."""
from __future__ import annotations

from ..nn.layer.layers import Layer
from .qat import (
    ObservedLayer,
    _is_quant_layer,
    _resolve_then_copy,
    _swap_layers,
)


class _ObservingWrapper(Layer):
    def __init__(self, inner, act_observer=None, weight_observer=None):
        super().__init__()
        self._inner = inner
        self._act_observer = (
            act_observer._instance() if act_observer is not None else None
        )
        self._weight_observer = (
            weight_observer._instance() if weight_observer is not None
            else None
        )

    def forward(self, x, *args, **kw):
        if self._act_observer is not None:
            self._act_observer.observe(x)
        if self._weight_observer is not None and hasattr(
            self._inner, "weight"
        ):
            self._weight_observer.observe(self._inner.weight)
        return self._inner(x, *args, **kw)


class PTQ:
    def __init__(self, config):
        self._config = config

    def quantize(self, model, inplace=False):
        """Insert observers; run calibration data through the returned
        model, then ``convert``."""
        model, by_id = _resolve_then_copy(model, self._config, inplace)

        def make(layer):
            cfg = by_id.get(id(layer))
            if cfg is None or _is_quant_layer(layer):
                return None
            return _ObservingWrapper(
                layer, cfg.get("activation"), cfg.get("weight")
            )

        return _swap_layers(model, make)

    def convert(self, model, inplace=False):
        if not inplace:
            import copy

            model = copy.deepcopy(model)

        def make(layer):
            if not isinstance(layer, _ObservingWrapper):
                return None
            act_scale = None
            act_bits = 8
            if layer._act_observer is not None:
                act_scale = layer._act_observer.scales()
                act_bits = layer._act_observer.quant_bits
            w_scale = None
            w_bits = 8
            if layer._weight_observer is not None and hasattr(
                layer._inner, "weight"
            ):
                layer._weight_observer.observe(layer._inner.weight)
                w_scale = layer._weight_observer.scales()
                w_bits = layer._weight_observer.quant_bits
            return ObservedLayer(
                layer._inner, act_scale, w_scale, act_bits, w_bits
            )

        return _swap_layers(model, make)
