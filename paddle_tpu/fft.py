"""paddle.fft parity over jnp.fft.

Reference parity: python/paddle/fft.py backed by cuFFT/pocketfft phi
kernels (unverified, mount empty). TPU redesign: XLA ships FFT lowering,
so every transform is one jnp.fft call through core.dispatch (autograd
via jax.vjp; fused inside compiled steps). Norm semantics follow the
reference ("backward" default, "ortho", "forward").
"""
from __future__ import annotations

import jax.numpy as jnp

from .core import dispatch
from .ops._helpers import normalize_axis, static_int_list


def _norm(norm):
    if norm is None:
        return "backward"
    if norm not in ("backward", "ortho", "forward"):
        raise ValueError(
            f"norm must be backward/ortho/forward, got {norm!r}"
        )
    return norm


def _one(op_name, jfn):
    # fn created ONCE per op: dispatch's jit cache keys on fn identity
    def fn(xv, *, n, axis, norm):
        return jfn(xv, n=n, axis=axis, norm=norm)

    def op(x, n=None, axis=-1, norm="backward", name=None):
        return dispatch.apply(
            op_name, fn, (x,),
            {"n": None if n is None else int(n), "axis": int(axis),
             "norm": _norm(norm)},
        )

    op.__name__ = op.__qualname__ = op_name
    return op


def _nd(op_name, jfn):
    def fn(xv, *, s, axes, norm):
        return jfn(xv, s=s, axes=axes, norm=norm)

    def op(x, s=None, axes=None, norm="backward", name=None):
        return dispatch.apply(
            op_name, fn, (x,),
            {"s": None if s is None else static_int_list(s),
             "axes": normalize_axis(axes), "norm": _norm(norm)},
        )

    op.__name__ = op.__qualname__ = op_name
    return op


fft = _one("fft", jnp.fft.fft)
ifft = _one("ifft", jnp.fft.ifft)
rfft = _one("rfft", jnp.fft.rfft)
irfft = _one("irfft", jnp.fft.irfft)
hfft = _one("hfft", jnp.fft.hfft)
ihfft = _one("ihfft", jnp.fft.ihfft)

fft2 = _nd("fft2", lambda x, *, s, axes, norm: jnp.fft.fft2(
    x, s=s, axes=axes if axes is not None else (-2, -1), norm=norm))
ifft2 = _nd("ifft2", lambda x, *, s, axes, norm: jnp.fft.ifft2(
    x, s=s, axes=axes if axes is not None else (-2, -1), norm=norm))
rfft2 = _nd("rfft2", lambda x, *, s, axes, norm: jnp.fft.rfft2(
    x, s=s, axes=axes if axes is not None else (-2, -1), norm=norm))
irfft2 = _nd("irfft2", lambda x, *, s, axes, norm: jnp.fft.irfft2(
    x, s=s, axes=axes if axes is not None else (-2, -1), norm=norm))
fftn = _nd("fftn", jnp.fft.fftn)
ifftn = _nd("ifftn", jnp.fft.ifftn)
rfftn = _nd("rfftn", jnp.fft.rfftn)
irfftn = _nd("irfftn", jnp.fft.irfftn)


def fftfreq(n, d=1.0, dtype=None, name=None):
    from .core.tensor import Tensor

    out = jnp.fft.fftfreq(int(n), d=float(d))
    if dtype is not None:
        from .core.dtypes import convert_dtype

        out = out.astype(convert_dtype(dtype))
    return Tensor(out)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from .core.tensor import Tensor

    out = jnp.fft.rfftfreq(int(n), d=float(d))
    if dtype is not None:
        from .core.dtypes import convert_dtype

        out = out.astype(convert_dtype(dtype))
    return Tensor(out)


def _fftshift_fn(xv, *, axes):
    return jnp.fft.fftshift(xv, axes=axes)


def _ifftshift_fn(xv, *, axes):
    return jnp.fft.ifftshift(xv, axes=axes)


def fftshift(x, axes=None, name=None):
    return dispatch.apply(
        "fftshift", _fftshift_fn, (x,), {"axes": normalize_axis(axes)},
    )


def ifftshift(x, axes=None, name=None):
    return dispatch.apply(
        "ifftshift", _ifftshift_fn, (x,), {"axes": normalize_axis(axes)},
    )
