"""Discrete distributions (python/paddle/distribution/{bernoulli,binomial,
categorical,geometric,multinomial,poisson}.py parity — unverified).

Same contracts as continuous.py: dispatch-routed densities, jax.random
samplers keyed from core.random. All discrete samples are nondiff.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dispatch
from ..core import random as random_mod
from .distribution import Distribution, _as_tensor, _shape_tuple


def _xlogy(x, y):
    return jnp.where(x == 0, 0.0, x * jnp.log(jnp.where(x == 0, 1.0, y)))


# --------------------------------------------------------------- Bernoulli
def _bernoulli_sample(p, *, key, shape):
    return jax.random.bernoulli(key, p, shape).astype(p.dtype)


def _bernoulli_logp(p, v, *, _):
    return _xlogy(v, p) + _xlogy(1.0 - v, 1.0 - p)


def _bernoulli_entropy(p, *, _):
    return -(_xlogy(p, p) + _xlogy(1.0 - p, 1.0 - p))


class _ProbsAttr:
    """Expose the success probability as a ``probs`` attribute (reference
    surface); Categorical is excluded — there ``probs`` is a method."""

    @property
    def probs(self):
        return self.probs_param


class Bernoulli(_ProbsAttr, Distribution):
    def __init__(self, probs, name=None):
        self.probs_param = _as_tensor(probs)
        super().__init__(tuple(self.probs_param.shape))

    @property
    def mean(self):
        return self.probs_param

    @property
    def variance(self):
        return self.probs_param * (1.0 - self.probs_param)

    def sample(self, shape=()):
        return dispatch.apply(
            "bernoulli_sample", _bernoulli_sample, (self.probs_param,),
            {"key": random_mod.next_key(),
             "shape": self._extend_shape(shape)},
            cache=False, nondiff=True,
        )

    def rsample(self, shape=(), temperature=1.0):
        """Gumbel-softmax style relaxed sample (paddle exposes this)."""
        from ..ops.math import log, sigmoid

        u = dispatch.apply(
            "uniform_raw",
            lambda p, *, key, shape: jax.random.uniform(key, shape),
            (self.probs_param,),
            {"key": random_mod.next_key(),
             "shape": self._extend_shape(shape)},
            cache=False, nondiff=True,
        )
        logits = log(self.probs_param) - log(1.0 - self.probs_param)
        noise = log(u) - log(1.0 - u)
        return sigmoid((logits + noise) / float(temperature))

    def log_prob(self, value):
        return dispatch.apply(
            "bernoulli_logp", _bernoulli_logp,
            (self.probs_param, _as_tensor(value)), {"_": 0},
        )

    def entropy(self):
        return dispatch.apply(
            "bernoulli_entropy", _bernoulli_entropy,
            (self.probs_param,), {"_": 0},
        )


# ------------------------------------------------------------- Categorical
def _categorical_sample(logits, *, key, shape):
    return jax.random.categorical(key, logits, shape=shape).astype(jnp.int64)


def _categorical_logp(logits, v, *, _):
    logp = jax.nn.log_softmax(logits, axis=-1)
    return jnp.take_along_axis(
        logp, v[..., None].astype(jnp.int32), axis=-1
    )[..., 0]


def _categorical_entropy(logits, *, _):
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.sum(jnp.exp(logp) * logp, axis=-1)


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = _as_tensor(logits)
        shape = tuple(self.logits.shape)
        super().__init__(shape[:-1])
        self._num_categories = shape[-1]

    @property
    def probs_tensor(self):
        from ..nn.functional.activation import softmax

        return softmax(self.logits, axis=-1)

    def sample(self, shape=()):
        return dispatch.apply(
            "categorical_sample", _categorical_sample, (self.logits,),
            {"key": random_mod.next_key(),
             "shape": _shape_tuple(shape) + self._batch_shape},
            cache=False, nondiff=True,
        )

    def log_prob(self, value):
        return dispatch.apply(
            "categorical_logp", _categorical_logp,
            (self.logits, _as_tensor(value)), {"_": 0},
        )

    def probs(self, value):
        from ..ops.math import exp

        return exp(self.log_prob(value))

    def entropy(self):
        return dispatch.apply(
            "categorical_entropy", _categorical_entropy,
            (self.logits,), {"_": 0},
        )


# --------------------------------------------------------------- Geometric
def _geometric_sample(p, *, key, shape):
    u = jax.random.uniform(key, shape, dtype=p.dtype)
    # number-of-trials-until-first-success, support {1, 2, ...} — the
    # paddle convention (mean 1/p); torch's {0,1,...} variant is this - 1.
    # Matches Tensor.geometric_ (ops/inplace.py).
    return jnp.floor(jnp.log1p(-u) / jnp.log1p(-p)) + 1.0


def _geometric_logp(p, v, *, _):
    return (v - 1.0) * jnp.log1p(-p) + jnp.log(p)


class Geometric(_ProbsAttr, Distribution):
    def __init__(self, probs, name=None):
        self.probs_param = _as_tensor(probs)
        super().__init__(tuple(self.probs_param.shape))

    @property
    def mean(self):
        return 1.0 / self.probs_param

    @property
    def variance(self):
        return (
            (1.0 - self.probs_param)
            / (self.probs_param * self.probs_param)
        )

    def sample(self, shape=()):
        return dispatch.apply(
            "geometric_sample", _geometric_sample, (self.probs_param,),
            {"key": random_mod.next_key(),
             "shape": self._extend_shape(shape)},
            cache=False, nondiff=True,
        )

    def log_prob(self, value):
        return dispatch.apply(
            "geometric_logp", _geometric_logp,
            (self.probs_param, _as_tensor(value)), {"_": 0},
        )

    def entropy(self):
        from ..ops.math import log

        p = self.probs_param
        return -((1.0 - p) * log(1.0 - p) + p * log(p)) / p


# ----------------------------------------------------------------- Poisson
def _poisson_sample(rate, *, key, shape):
    return jax.random.poisson(key, rate, shape).astype(rate.dtype)


def _poisson_logp(rate, v, *, _):
    return (
        v * jnp.log(rate) - rate - jax.scipy.special.gammaln(v + 1.0)
    )


class Poisson(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _as_tensor(rate)
        super().__init__(tuple(self.rate.shape))

    @property
    def mean(self):
        return self.rate

    @property
    def variance(self):
        return self.rate

    def sample(self, shape=()):
        return dispatch.apply(
            "poisson_dist_sample", _poisson_sample, (self.rate,),
            {"key": random_mod.next_key(),
             "shape": self._extend_shape(shape)},
            cache=False, nondiff=True,
        )

    def log_prob(self, value):
        return dispatch.apply(
            "poisson_logp", _poisson_logp,
            (self.rate, _as_tensor(value)), {"_": 0},
        )


# ---------------------------------------------------------------- Binomial
def _binomial_sample(p, *, key, shape, n):
    return jax.random.binomial(key, n, p, shape).astype(p.dtype)


def _binomial_logp(p, v, *, n):
    lg = jax.scipy.special.gammaln
    logc = lg(n + 1.0) - lg(v + 1.0) - lg(n - v + 1.0)
    return logc + _xlogy(v, p) + _xlogy(n - v, 1.0 - p)


class Binomial(_ProbsAttr, Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs_param = _as_tensor(probs)
        super().__init__(tuple(self.probs_param.shape))

    @property
    def mean(self):
        return self.total_count * self.probs_param

    @property
    def variance(self):
        return (
            self.total_count * self.probs_param * (1.0 - self.probs_param)
        )

    def sample(self, shape=()):
        return dispatch.apply(
            "binomial_sample", _binomial_sample, (self.probs_param,),
            {"key": random_mod.next_key(),
             "shape": self._extend_shape(shape),
             "n": float(self.total_count)},
            cache=False, nondiff=True,
        )

    def log_prob(self, value):
        return dispatch.apply(
            "binomial_logp", _binomial_logp,
            (self.probs_param, _as_tensor(value)),
            {"n": float(self.total_count)},
        )


# ------------------------------------------------------------- Multinomial
def _multinomial_sample(p, *, key, shape, n):
    return jax.random.multinomial(key, n, p, shape=shape).astype(p.dtype)


def _multinomial_logp(p, v, *, n):
    lg = jax.scipy.special.gammaln
    logc = lg(n + 1.0) - jnp.sum(lg(v + 1.0), -1)
    return logc + jnp.sum(_xlogy(v, p), -1)


class Multinomial(_ProbsAttr, Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        p = _as_tensor(probs)
        # reference normalizes along the event axis at construction
        self.probs_param = p / p.sum(axis=-1, keepdim=True)
        shape = tuple(self.probs_param.shape)
        super().__init__(shape[:-1], shape[-1:])

    @property
    def mean(self):
        return self.total_count * self.probs_param

    @property
    def variance(self):
        return (
            self.total_count * self.probs_param * (1.0 - self.probs_param)
        )

    def sample(self, shape=()):
        return dispatch.apply(
            "multinomial_sample", _multinomial_sample, (self.probs_param,),
            {"key": random_mod.next_key(),
             "shape": _shape_tuple(shape) + self._batch_shape
             + self._event_shape,
             "n": float(self.total_count)},
            cache=False, nondiff=True,
        )

    def log_prob(self, value):
        return dispatch.apply(
            "multinomial_logp", _multinomial_logp,
            (self.probs_param, _as_tensor(value)),
            {"n": float(self.total_count)},
        )
