"""MultivariateNormal (python/paddle/distribution/multivariate_normal.py
parity — unverified): parameterized by covariance, precision, or
scale_tril; internally everything runs on the Cholesky factor."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core import dispatch
from ..core import random as random_mod
from .distribution import Distribution, _as_tensor


def _mvn_sample(loc, tril, *, key, shape):
    eps = jax.random.normal(
        key, shape + loc.shape[-1:], dtype=jnp.result_type(loc)
    )
    return loc + jnp.einsum("...ij,...j->...i", tril, eps)


def _mvn_logp(loc, tril, v, *, _):
    d = loc.shape[-1]
    diff = v - loc
    y = jax.scipy.linalg.solve_triangular(tril, diff[..., None], lower=True)
    maha = jnp.sum(jnp.square(y[..., 0]), -1)
    logdet = jnp.sum(jnp.log(jnp.diagonal(tril, axis1=-2, axis2=-1)), -1)
    return -0.5 * (d * math.log(2.0 * math.pi) + maha) - logdet


def _mvn_entropy(tril, *, _):
    d = tril.shape[-1]
    logdet = jnp.sum(jnp.log(jnp.diagonal(tril, axis1=-2, axis2=-1)), -1)
    return 0.5 * d * (1.0 + math.log(2.0 * math.pi)) + logdet


class MultivariateNormal(Distribution):
    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None, name=None):
        self.loc = _as_tensor(loc)
        given = [
            m is not None
            for m in (covariance_matrix, precision_matrix, scale_tril)
        ]
        if sum(given) != 1:
            raise ValueError(
                "MultivariateNormal: exactly one of covariance_matrix, "
                "precision_matrix, scale_tril must be given"
            )
        if scale_tril is not None:
            self.scale_tril = _as_tensor(scale_tril)
        elif covariance_matrix is not None:
            cov = _as_tensor(covariance_matrix)
            from ..ops.linalg import cholesky

            self.scale_tril = cholesky(cov)
        else:
            prec = _as_tensor(precision_matrix)
            from ..ops.linalg import cholesky, inv

            self.scale_tril = cholesky(inv(prec))
        shape = tuple(self.loc.shape)
        super().__init__(shape[:-1], shape[-1:])

    @property
    def mean(self):
        return self.loc

    @property
    def covariance_matrix(self):
        from ..ops.math import matmul
        from ..ops.manipulation import transpose

        t = self.scale_tril
        nd = len(t.shape)
        perm = list(range(nd - 2)) + [nd - 1, nd - 2]
        return matmul(t, transpose(t, perm))

    @property
    def variance(self):
        from ..ops.linalg import matmul  # noqa: F401
        from ..ops.manipulation import diagonal

        return diagonal(self.covariance_matrix, axis1=-2, axis2=-1)

    def sample(self, shape=()):
        out = self.rsample(shape)
        out.stop_gradient = True
        return out

    def rsample(self, shape=()):
        from .distribution import _shape_tuple

        return dispatch.apply(
            "mvn_sample", _mvn_sample, (self.loc, self.scale_tril),
            {"key": random_mod.next_key(),
             "shape": _shape_tuple(shape) + self._batch_shape},
            cache=False, nondiff=False,
        )

    def log_prob(self, value):
        return dispatch.apply(
            "mvn_logp", _mvn_logp,
            (self.loc, self.scale_tril, _as_tensor(value)), {"_": 0},
        )

    def entropy(self):
        return dispatch.apply(
            "mvn_entropy", _mvn_entropy, (self.scale_tril,), {"_": 0}
        )
