"""Continuous distributions (python/paddle/distribution/{normal,uniform,
beta,cauchy,chi2,dirichlet,exponential,gamma,gumbel,laplace,lognormal,
student_t}.py parity — unverified).

Densities are module-level pure-jnp fns routed through core.dispatch
(autograd to parameters + value); samplers use jax.random with keys from
core.random (``cache=False`` — each call draws a fresh key). Samplers for
gamma/beta/dirichlet are jax's implicitly-reparameterized versions, so
``rsample`` gradients flow to parameters where jax supports it.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core import dispatch
from ..core import random as random_mod
from .distribution import Distribution, _as_tensor, _shape_tuple

_HALF_LOG_2PI = 0.5 * math.log(2.0 * math.pi)


def _sampler(name, fn, args, shape, extra=None, nondiff=True):
    kw = {"key": random_mod.next_key(), "shape": shape}
    if extra:
        kw.update(extra)
    return dispatch.apply(name, fn, args, kw, cache=False, nondiff=nondiff)


# ------------------------------------------------------------------ Normal
def _normal_sample(loc, scale, *, key, shape):
    eps = jax.random.normal(key, shape, dtype=jnp.result_type(loc))
    return loc + scale * eps


def _normal_logp(loc, scale, v, *, _):
    return (
        -jnp.square(v - loc) / (2.0 * jnp.square(scale))
        - jnp.log(scale) - _HALF_LOG_2PI
    )


def _normal_entropy(scale, *, _):
    return 0.5 + _HALF_LOG_2PI + jnp.log(scale)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _as_tensor(loc)
        self.scale = _as_tensor(scale)
        super().__init__(
            jnp.broadcast_shapes(
                tuple(self.loc.shape), tuple(self.scale.shape)
            )
        )

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return self.scale * self.scale

    @property
    def stddev(self):
        return self.scale

    def sample(self, shape=()):
        out = self.rsample(shape)
        out.stop_gradient = True
        return out

    def rsample(self, shape=()):
        return _sampler(
            "normal_sample", _normal_sample, (self.loc, self.scale),
            self._extend_shape(shape), nondiff=False,
        )

    def log_prob(self, value):
        return dispatch.apply(
            "normal_logp", _normal_logp,
            (self.loc, self.scale, _as_tensor(value)), {"_": 0},
        )

    def entropy(self):
        return dispatch.apply(
            "normal_entropy", _normal_entropy, (self.scale,), {"_": 0}
        )


# ----------------------------------------------------------------- Uniform
def _uniform_sample(low, high, *, key, shape):
    u = jax.random.uniform(key, shape, dtype=jnp.result_type(low))
    return low + (high - low) * u


def _uniform_logp(low, high, v, *, _):
    inside = (v >= low) & (v < high)
    return jnp.where(inside, -jnp.log(high - low), -jnp.inf)


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _as_tensor(low)
        self.high = _as_tensor(high)
        super().__init__(
            jnp.broadcast_shapes(
                tuple(self.low.shape), tuple(self.high.shape)
            )
        )

    @property
    def mean(self):
        return (self.low + self.high) / 2.0

    @property
    def variance(self):
        d = self.high - self.low
        return d * d / 12.0

    def sample(self, shape=()):
        out = self.rsample(shape)
        out.stop_gradient = True
        return out

    def rsample(self, shape=()):
        return _sampler(
            "uniform_sample", _uniform_sample, (self.low, self.high),
            self._extend_shape(shape), nondiff=False,
        )

    def log_prob(self, value):
        return dispatch.apply(
            "uniform_logp", _uniform_logp,
            (self.low, self.high, _as_tensor(value)), {"_": 0},
        )

    def entropy(self):
        from ..ops.math import log

        return log(self.high - self.low)


# -------------------------------------------------------------------- Beta
def _beta_sample(a, b, *, key, shape):
    return jax.random.beta(key, a, b, shape)


def _beta_logp(a, b, v, *, _):
    lbeta = (
        jax.scipy.special.gammaln(a) + jax.scipy.special.gammaln(b)
        - jax.scipy.special.gammaln(a + b)
    )
    return (a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v) - lbeta


def _beta_entropy(a, b, *, _):
    dg = jax.scipy.special.digamma
    lbeta = (
        jax.scipy.special.gammaln(a) + jax.scipy.special.gammaln(b)
        - jax.scipy.special.gammaln(a + b)
    )
    return (
        lbeta - (a - 1) * dg(a) - (b - 1) * dg(b)
        + (a + b - 2) * dg(a + b)
    )


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _as_tensor(alpha)
        self.beta = _as_tensor(beta)
        super().__init__(
            jnp.broadcast_shapes(
                tuple(self.alpha.shape), tuple(self.beta.shape)
            )
        )

    @property
    def mean(self):
        return self.alpha / (self.alpha + self.beta)

    @property
    def variance(self):
        s = self.alpha + self.beta
        return self.alpha * self.beta / (s * s * (s + 1.0))

    def sample(self, shape=()):
        out = self.rsample(shape)
        out.stop_gradient = True
        return out

    def rsample(self, shape=()):
        return _sampler(
            "beta_sample", _beta_sample, (self.alpha, self.beta),
            self._extend_shape(shape), nondiff=False,
        )

    def log_prob(self, value):
        return dispatch.apply(
            "beta_logp", _beta_logp,
            (self.alpha, self.beta, _as_tensor(value)), {"_": 0},
        )

    def entropy(self):
        return dispatch.apply(
            "beta_entropy", _beta_entropy, (self.alpha, self.beta), {"_": 0}
        )


# ------------------------------------------------------------------- Gamma
def _gamma_sample(conc, rate, *, key, shape):
    return jax.random.gamma(key, conc, shape) / rate


def _gamma_logp(conc, rate, v, *, _):
    return (
        conc * jnp.log(rate) + (conc - 1) * jnp.log(v) - rate * v
        - jax.scipy.special.gammaln(conc)
    )


def _gamma_entropy(conc, rate, *, _):
    dg = jax.scipy.special.digamma
    return (
        conc - jnp.log(rate) + jax.scipy.special.gammaln(conc)
        + (1.0 - conc) * dg(conc)
    )


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration = _as_tensor(concentration)
        self.rate = _as_tensor(rate)
        super().__init__(
            jnp.broadcast_shapes(
                tuple(self.concentration.shape), tuple(self.rate.shape)
            )
        )

    @property
    def mean(self):
        return self.concentration / self.rate

    @property
    def variance(self):
        return self.concentration / (self.rate * self.rate)

    def sample(self, shape=()):
        out = self.rsample(shape)
        out.stop_gradient = True
        return out

    def rsample(self, shape=()):
        return _sampler(
            "gamma_sample", _gamma_sample, (self.concentration, self.rate),
            self._extend_shape(shape), nondiff=False,
        )

    def log_prob(self, value):
        return dispatch.apply(
            "gamma_logp", _gamma_logp,
            (self.concentration, self.rate, _as_tensor(value)), {"_": 0},
        )

    def entropy(self):
        return dispatch.apply(
            "gamma_entropy", _gamma_entropy,
            (self.concentration, self.rate), {"_": 0},
        )


# ------------------------------------------------------------- Exponential
class Exponential(Gamma):
    def __init__(self, rate, name=None):
        rate = _as_tensor(rate)
        super().__init__(jnp.ones_like(rate.value), rate)
        self.rate = rate

    def entropy(self):
        from ..ops.math import log

        return 1.0 - log(self.rate)


# -------------------------------------------------------------------- Chi2
class Chi2(Gamma):
    def __init__(self, df, name=None):
        df = _as_tensor(df)
        super().__init__(
            df / 2.0, _as_tensor(jnp.full_like(df.value, 0.5))
        )
        self.df = df


# --------------------------------------------------------------- Dirichlet
def _dirichlet_sample(conc, *, key, shape):
    return jax.random.dirichlet(key, conc, shape)


def _dirichlet_logp(conc, v, *, _):
    norm = jax.scipy.special.gammaln(jnp.sum(conc, -1)) - jnp.sum(
        jax.scipy.special.gammaln(conc), -1
    )
    return jnp.sum((conc - 1) * jnp.log(v), -1) + norm


def _dirichlet_entropy(conc, *, _):
    dg = jax.scipy.special.digamma
    a0 = jnp.sum(conc, -1)
    k = conc.shape[-1]
    lnB = jnp.sum(
        jax.scipy.special.gammaln(conc), -1
    ) - jax.scipy.special.gammaln(a0)
    return (
        lnB + (a0 - k) * dg(a0) - jnp.sum((conc - 1) * dg(conc), -1)
    )


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = _as_tensor(concentration)
        shape = tuple(self.concentration.shape)
        super().__init__(shape[:-1], shape[-1:])

    @property
    def mean(self):
        from ..ops.reduction import sum as _sum

        return self.concentration / _sum(
            self.concentration, axis=-1, keepdim=True
        )

    @property
    def variance(self):
        from ..ops.reduction import sum as _sum

        a0 = _sum(self.concentration, axis=-1, keepdim=True)
        m = self.concentration / a0
        return m * (1.0 - m) / (a0 + 1.0)

    def sample(self, shape=()):
        out = self.rsample(shape)
        out.stop_gradient = True
        return out

    def rsample(self, shape=()):
        return _sampler(
            "dirichlet_sample", _dirichlet_sample, (self.concentration,),
            _shape_tuple(shape) + self._batch_shape, nondiff=False,
        )

    def log_prob(self, value):
        return dispatch.apply(
            "dirichlet_logp", _dirichlet_logp,
            (self.concentration, _as_tensor(value)), {"_": 0},
        )

    def entropy(self):
        return dispatch.apply(
            "dirichlet_entropy", _dirichlet_entropy,
            (self.concentration,), {"_": 0},
        )


# ----------------------------------------------------------------- Laplace
def _laplace_sample(loc, scale, *, key, shape):
    return loc + scale * jax.random.laplace(
        key, shape, dtype=jnp.result_type(loc)
    )


def _laplace_logp(loc, scale, v, *, _):
    return -jnp.abs(v - loc) / scale - jnp.log(2.0 * scale)


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _as_tensor(loc)
        self.scale = _as_tensor(scale)
        super().__init__(
            jnp.broadcast_shapes(
                tuple(self.loc.shape), tuple(self.scale.shape)
            )
        )

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return 2.0 * self.scale * self.scale

    @property
    def stddev(self):
        return (2.0 ** 0.5) * self.scale

    def sample(self, shape=()):
        out = self.rsample(shape)
        out.stop_gradient = True
        return out

    def rsample(self, shape=()):
        return _sampler(
            "laplace_sample", _laplace_sample, (self.loc, self.scale),
            self._extend_shape(shape), nondiff=False,
        )

    def log_prob(self, value):
        return dispatch.apply(
            "laplace_logp", _laplace_logp,
            (self.loc, self.scale, _as_tensor(value)), {"_": 0},
        )

    def entropy(self):
        from ..ops.math import log

        return 1.0 + log(2.0 * self.scale)


# ------------------------------------------------------------------ Gumbel
def _gumbel_sample(loc, scale, *, key, shape):
    return loc + scale * jax.random.gumbel(
        key, shape, dtype=jnp.result_type(loc)
    )


def _gumbel_logp(loc, scale, v, *, _):
    z = (v - loc) / scale
    return -(z + jnp.exp(-z)) - jnp.log(scale)


_EULER = 0.5772156649015329


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _as_tensor(loc)
        self.scale = _as_tensor(scale)
        super().__init__(
            jnp.broadcast_shapes(
                tuple(self.loc.shape), tuple(self.scale.shape)
            )
        )

    @property
    def mean(self):
        return self.loc + _EULER * self.scale

    @property
    def variance(self):
        return (math.pi ** 2 / 6.0) * self.scale * self.scale

    def sample(self, shape=()):
        out = self.rsample(shape)
        out.stop_gradient = True
        return out

    def rsample(self, shape=()):
        return _sampler(
            "gumbel_sample", _gumbel_sample, (self.loc, self.scale),
            self._extend_shape(shape), nondiff=False,
        )

    def log_prob(self, value):
        return dispatch.apply(
            "gumbel_logp", _gumbel_logp,
            (self.loc, self.scale, _as_tensor(value)), {"_": 0},
        )

    def entropy(self):
        from ..ops.math import log

        return log(self.scale) + 1.0 + _EULER


# ------------------------------------------------------------------ Cauchy
def _cauchy_sample(loc, scale, *, key, shape):
    return loc + scale * jax.random.cauchy(
        key, shape, dtype=jnp.result_type(loc)
    )


def _cauchy_logp(loc, scale, v, *, _):
    z = (v - loc) / scale
    return -jnp.log(math.pi * scale * (1.0 + z * z))


class Cauchy(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _as_tensor(loc)
        self.scale = _as_tensor(scale)
        super().__init__(
            jnp.broadcast_shapes(
                tuple(self.loc.shape), tuple(self.scale.shape)
            )
        )

    def sample(self, shape=()):
        out = self.rsample(shape)
        out.stop_gradient = True
        return out

    def rsample(self, shape=()):
        return _sampler(
            "cauchy_sample", _cauchy_sample, (self.loc, self.scale),
            self._extend_shape(shape), nondiff=False,
        )

    def log_prob(self, value):
        return dispatch.apply(
            "cauchy_logp", _cauchy_logp,
            (self.loc, self.scale, _as_tensor(value)), {"_": 0},
        )

    def entropy(self):
        from ..ops.math import log

        return log(4.0 * math.pi * self.scale)


# --------------------------------------------------------------- LogNormal
def _lognormal_logp(loc, scale, v, *, _):
    logv = jnp.log(v)
    return (
        -jnp.square(logv - loc) / (2.0 * jnp.square(scale))
        - jnp.log(scale) - _HALF_LOG_2PI - logv
    )


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _as_tensor(loc)
        self.scale = _as_tensor(scale)
        super().__init__(
            jnp.broadcast_shapes(
                tuple(self.loc.shape), tuple(self.scale.shape)
            )
        )

    @property
    def mean(self):
        from ..ops.math import exp

        return exp(self.loc + self.scale * self.scale / 2.0)

    @property
    def variance(self):
        from ..ops.math import exp

        s2 = self.scale * self.scale
        return (exp(s2) - 1.0) * exp(2.0 * self.loc + s2)

    def sample(self, shape=()):
        out = self.rsample(shape)
        out.stop_gradient = True
        return out

    def rsample(self, shape=()):
        from ..ops.math import exp

        base = _sampler(
            "normal_sample", _normal_sample, (self.loc, self.scale),
            self._extend_shape(shape), nondiff=False,
        )
        return exp(base)

    def log_prob(self, value):
        return dispatch.apply(
            "lognormal_logp", _lognormal_logp,
            (self.loc, self.scale, _as_tensor(value)), {"_": 0},
        )

    def entropy(self):
        from ..ops.math import log

        return 0.5 + _HALF_LOG_2PI + log(self.scale) + self.loc


# ---------------------------------------------------------------- StudentT
def _student_t_sample(df, loc, scale, *, key, shape):
    return loc + scale * jax.random.t(
        key, df, shape, dtype=jnp.result_type(loc)
    )


def _student_t_logp(df, loc, scale, v, *, _):
    z = (v - loc) / scale
    lg = jax.scipy.special.gammaln
    return (
        lg((df + 1.0) / 2.0) - lg(df / 2.0)
        - 0.5 * jnp.log(df * math.pi) - jnp.log(scale)
        - ((df + 1.0) / 2.0) * jnp.log1p(z * z / df)
    )


class StudentT(Distribution):
    def __init__(self, df, loc, scale, name=None):
        self.df = _as_tensor(df)
        self.loc = _as_tensor(loc)
        self.scale = _as_tensor(scale)
        super().__init__(
            jnp.broadcast_shapes(
                tuple(self.df.shape), tuple(self.loc.shape),
                tuple(self.scale.shape),
            )
        )

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return (
            self.scale * self.scale * self.df / (self.df - 2.0)
        )

    def sample(self, shape=()):
        out = _sampler(
            "student_t_sample", _student_t_sample,
            (self.df, self.loc, self.scale), self._extend_shape(shape),
        )
        return out

    def log_prob(self, value):
        return dispatch.apply(
            "student_t_logp", _student_t_logp,
            (self.df, self.loc, self.scale, _as_tensor(value)), {"_": 0},
        )
