"""Distribution base class.

Reference parity: python/paddle/distribution/distribution.py (unverified,
mount empty). Distributions are thin Python objects over the framework's
Tensor ops: parameters are Tensors, densities/entropies compose
dispatch-routed ops (so grads flow to parameters), and sampling draws
trace-safe PRNG keys from core.random (paddle.seed-deterministic).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor


def _as_tensor(v, dtype=None):
    if isinstance(v, Tensor):
        return v
    return Tensor(jnp.asarray(v, dtype=dtype or jnp.float32))


def _shape_tuple(shape):
    if shape is None:
        return ()
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    if isinstance(shape, int):
        return (shape,)
    return tuple(int(s) for s in shape)


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError(
            f"{type(self).__name__} does not implement rsample"
        )

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        from ..ops.math import exp

        return exp(self.log_prob(value))

    def probs(self, value):
        return self.prob(value)

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        from .kl import kl_divergence

        return kl_divergence(self, other)

    def _extend_shape(self, sample_shape):
        return (
            _shape_tuple(sample_shape) + self._batch_shape
            + self._event_shape
        )
