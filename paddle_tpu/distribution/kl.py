"""KL divergence registry (python/paddle/distribution/kl.py parity —
unverified): ``register_kl`` decorator + closed forms for the common
pairs, falling back on the most-derived registered match. All kernel fns
are module-level so dispatch's fn-identity jit cache hits every call."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dispatch
from .continuous import (
    Beta,
    Dirichlet,
    Exponential,
    Gamma,
    Laplace,
    LogNormal,
    Normal,
    Uniform,
)
from .discrete import Bernoulli, Categorical, Geometric, Poisson
from .multivariate import MultivariateNormal

_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    def decorator(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn

    return decorator


def kl_divergence(p, q):
    best = None
    best_depth = None
    for (pc, qc), fn in _KL_REGISTRY.items():
        if isinstance(p, pc) and isinstance(q, qc):
            depth = (
                type(p).__mro__.index(pc) + type(q).__mro__.index(qc)
            )
            if best is None or depth < best_depth:
                best, best_depth = fn, depth
    if best is None:
        raise NotImplementedError(
            f"no KL registered for ({type(p).__name__}, {type(q).__name__})"
        )
    return best(p, q)


def _gaussian_kl(pl, ps, ql, qs, *, _):
    var_ratio = jnp.square(ps / qs)
    t1 = jnp.square((pl - ql) / qs)
    return 0.5 * (var_ratio + t1 - 1.0 - jnp.log(var_ratio))


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    return dispatch.apply(
        "kl_normal", _gaussian_kl, (p.loc, p.scale, q.loc, q.scale), {"_": 0}
    )


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    from ..ops.math import log

    return log((q.high - q.low) / (p.high - p.low))


def _bernoulli_kl(pp, qp, *, _):
    def xlog(a, b, c):
        return jnp.where(a == 0, 0.0, a * (jnp.log(b) - jnp.log(c)))

    return xlog(pp, pp, qp) + xlog(1.0 - pp, 1.0 - pp, 1.0 - qp)


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    return dispatch.apply(
        "kl_bernoulli", _bernoulli_kl, (p.probs_param, q.probs_param),
        {"_": 0},
    )


def _categorical_kl(pl, ql, *, _):
    plog = jax.nn.log_softmax(pl, axis=-1)
    qlog = jax.nn.log_softmax(ql, axis=-1)
    return jnp.sum(jnp.exp(plog) * (plog - qlog), axis=-1)


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    return dispatch.apply(
        "kl_categorical", _categorical_kl, (p.logits, q.logits), {"_": 0}
    )


def _beta_kl(pa, pb, qa, qb, *, _):
    lg = jax.scipy.special.gammaln
    dg = jax.scipy.special.digamma

    def lbeta(a, b):
        return lg(a) + lg(b) - lg(a + b)

    return (
        lbeta(qa, qb) - lbeta(pa, pb)
        + (pa - qa) * dg(pa) + (pb - qb) * dg(pb)
        + (qa - pa + qb - pb) * dg(pa + pb)
    )


@register_kl(Beta, Beta)
def _kl_beta(p, q):
    return dispatch.apply(
        "kl_beta", _beta_kl, (p.alpha, p.beta, q.alpha, q.beta), {"_": 0}
    )


def _dirichlet_kl(pc, qc, *, _):
    lg = jax.scipy.special.gammaln
    dg = jax.scipy.special.digamma
    p0 = jnp.sum(pc, -1)
    q0 = jnp.sum(qc, -1)
    return (
        lg(p0) - lg(q0)
        - jnp.sum(lg(pc), -1) + jnp.sum(lg(qc), -1)
        + jnp.sum((pc - qc) * (dg(pc) - dg(p0)[..., None]), -1)
    )


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet(p, q):
    return dispatch.apply(
        "kl_dirichlet", _dirichlet_kl, (p.concentration, q.concentration),
        {"_": 0},
    )


def _gamma_kl(pa, pr, qa, qr, *, _):
    lg = jax.scipy.special.gammaln
    dg = jax.scipy.special.digamma
    return (
        (pa - qa) * dg(pa) - lg(pa) + lg(qa)
        + qa * (jnp.log(pr) - jnp.log(qr))
        + pa * (qr / pr - 1.0)
    )


@register_kl(Gamma, Gamma)
def _kl_gamma(p, q):
    return dispatch.apply(
        "kl_gamma", _gamma_kl,
        (p.concentration, p.rate, q.concentration, q.rate), {"_": 0},
    )


def _exponential_kl(pr, qr, *, _):
    return jnp.log(pr) - jnp.log(qr) + qr / pr - 1.0


@register_kl(Exponential, Exponential)
def _kl_exponential(p, q):
    return dispatch.apply(
        "kl_exponential", _exponential_kl, (p.rate, q.rate), {"_": 0}
    )


def _laplace_kl(pl, ps, ql, qs, *, _):
    d = jnp.abs(pl - ql)
    return (
        jnp.log(qs) - jnp.log(ps)
        + (ps * jnp.exp(-d / ps) + d) / qs - 1.0
    )


@register_kl(Laplace, Laplace)
def _kl_laplace(p, q):
    return dispatch.apply(
        "kl_laplace", _laplace_kl, (p.loc, p.scale, q.loc, q.scale), {"_": 0}
    )


def _geometric_kl(pp, qp, *, _):
    return (
        (1.0 / pp - 1.0) * (jnp.log1p(-pp) - jnp.log1p(-qp))
        + jnp.log(pp) - jnp.log(qp)
    )


@register_kl(Geometric, Geometric)
def _kl_geometric(p, q):
    return dispatch.apply(
        "kl_geometric", _geometric_kl, (p.probs_param, q.probs_param),
        {"_": 0},
    )


def _poisson_kl(pr, qr, *, _):
    return pr * (jnp.log(pr) - jnp.log(qr)) - pr + qr


@register_kl(Poisson, Poisson)
def _kl_poisson(p, q):
    return dispatch.apply("kl_poisson", _poisson_kl, (p.rate, q.rate), {"_": 0})


@register_kl(LogNormal, LogNormal)
def _kl_lognormal(p, q):
    # KL is invariant under the shared exp transform: reduce to the
    # underlying Gaussians
    return dispatch.apply(
        "kl_normal", _gaussian_kl, (p.loc, p.scale, q.loc, q.scale), {"_": 0}
    )


def _mvn_kl(pl, pt, ql, qt, *, _):
    d = pl.shape[-1]
    logdet_p = jnp.sum(jnp.log(jnp.diagonal(pt, axis1=-2, axis2=-1)), -1)
    logdet_q = jnp.sum(jnp.log(jnp.diagonal(qt, axis1=-2, axis2=-1)), -1)
    m = jax.scipy.linalg.solve_triangular(qt, pt, lower=True)
    tr = jnp.sum(jnp.square(m), (-2, -1))
    diff = ql - pl
    y = jax.scipy.linalg.solve_triangular(qt, diff[..., None], lower=True)
    maha = jnp.sum(jnp.square(y[..., 0]), -1)
    return 0.5 * (2.0 * (logdet_q - logdet_p) - d + tr + maha)


@register_kl(MultivariateNormal, MultivariateNormal)
def _kl_mvn(p, q):
    return dispatch.apply(
        "kl_mvn", _mvn_kl,
        (p.loc, p.scale_tril, q.loc, q.scale_tril), {"_": 0},
    )
