"""Bijective transforms + TransformedDistribution
(python/paddle/distribution/{transform,transformed_distribution}.py
parity — unverified). Transforms compose framework Tensor ops, so
forward/inverse/log_det are all differentiable."""
from __future__ import annotations

from .distribution import Distribution, _as_tensor


class Transform:
    def forward(self, x):
        raise NotImplementedError

    def inverse(self, y):
        raise NotImplementedError

    def forward_log_det_jacobian(self, x):
        raise NotImplementedError

    def inverse_log_det_jacobian(self, y):
        return -self.forward_log_det_jacobian(self.inverse(y))

    def __call__(self, x):
        return self.forward(x)


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _as_tensor(loc)
        self.scale = _as_tensor(scale)

    def forward(self, x):
        return self.loc + self.scale * x

    def inverse(self, y):
        return (y - self.loc) / self.scale

    def forward_log_det_jacobian(self, x):
        from ..ops.math import abs as _abs, log

        return log(_abs(self.scale)) + x * 0.0


class ExpTransform(Transform):
    def forward(self, x):
        from ..ops.math import exp

        return exp(x)

    def inverse(self, y):
        from ..ops.math import log

        return log(y)

    def forward_log_det_jacobian(self, x):
        return x


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = _as_tensor(power)

    def forward(self, x):
        return x ** self.power

    def inverse(self, y):
        return y ** (1.0 / self.power)

    def forward_log_det_jacobian(self, x):
        from ..ops.math import abs as _abs, log

        return log(_abs(self.power * x ** (self.power - 1.0)))


class SigmoidTransform(Transform):
    def forward(self, x):
        from ..ops.math import sigmoid

        return sigmoid(x)

    def inverse(self, y):
        from ..ops.math import log

        return log(y) - log(1.0 - y)

    def forward_log_det_jacobian(self, x):
        from ..nn.functional.activation import log_sigmoid

        return log_sigmoid(x) + log_sigmoid(-x)


class TanhTransform(Transform):
    def forward(self, x):
        from ..ops.math import tanh

        return tanh(x)

    def inverse(self, y):
        from ..ops.math import atanh

        return atanh(y)

    def forward_log_det_jacobian(self, x):
        import math

        from ..nn.functional.activation import softplus

        # log(1 - tanh(x)^2) = 2*(log 2 - x - softplus(-2x))
        return 2.0 * (math.log(2.0) - x - softplus(-2.0 * x))


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def forward(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def inverse(self, y):
        for t in reversed(self.transforms):
            y = t.inverse(y)
        return y

    def forward_log_det_jacobian(self, x):
        total = None
        for t in self.transforms:
            ld = t.forward_log_det_jacobian(x)
            total = ld if total is None else total + ld
            x = t.forward(x)
        return total


class TransformedDistribution(Distribution):
    def __init__(self, base, transforms, name=None):
        self.base = base
        if isinstance(transforms, Transform):
            transforms = [transforms]
        self.transforms = ChainTransform(transforms)
        super().__init__(base.batch_shape, base.event_shape)

    def sample(self, shape=()):
        out = self.transforms.forward(self.base.sample(shape))
        out.stop_gradient = True
        return out

    def rsample(self, shape=()):
        return self.transforms.forward(self.base.rsample(shape))

    def log_prob(self, value):
        value = _as_tensor(value)
        x = self.transforms.inverse(value)
        return (
            self.base.log_prob(x)
            - self.transforms.forward_log_det_jacobian(x)
        )
