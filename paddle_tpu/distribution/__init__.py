"""paddle.distribution namespace (python/paddle/distribution/__init__.py
parity — unverified): distributions over the framework's Tensor/autograd
stack, a transform family, and the KL registry."""
from .continuous import (  # noqa: F401
    Beta,
    Cauchy,
    Chi2,
    Dirichlet,
    Exponential,
    Gamma,
    Gumbel,
    Laplace,
    LogNormal,
    Normal,
    StudentT,
    Uniform,
)
from .discrete import (  # noqa: F401
    Bernoulli,
    Binomial,
    Categorical,
    Geometric,
    Multinomial,
    Poisson,
)
from .distribution import Distribution  # noqa: F401
from .kl import kl_divergence, register_kl  # noqa: F401
from .multivariate import MultivariateNormal  # noqa: F401
from .transform import (  # noqa: F401
    AffineTransform,
    ChainTransform,
    ExpTransform,
    PowerTransform,
    SigmoidTransform,
    TanhTransform,
    Transform,
    TransformedDistribution,
)
