"""paddle.metric parity (python/paddle/metric/metrics.py — unverified)."""
from .metrics import Accuracy, Auc, Metric, Precision, Recall, accuracy  # noqa: F401
