"""Training metrics.

Reference parity: python/paddle/metric/metrics.py (unverified, mount empty).
Metrics accumulate on host in numpy — they are observability, not compute,
so they never enter the compiled graph.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


def _np(x):
    return x.numpy() if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def __init__(self, name=None):
        self._name = name or type(self).__name__.lower()

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self._name

    def compute(self, *args):
        """Optional pre-processing of (pred, label) into update() args."""
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__(name or "acc")
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        if label_np.ndim == pred_np.ndim and label_np.shape[-1] == 1:
            label_np = label_np[..., 0]
        top = np.argsort(-pred_np, axis=-1)[..., : self.maxk]
        correct = top == label_np[..., None]
        return correct.astype(np.float32)

    def update(self, correct, *args):
        correct = _np(correct)
        n = correct.shape[0]
        for i, k in enumerate(self.topk):
            hits = correct[..., :k].max(axis=-1).sum()
            self.total[i] += float(hits)
            self.count[i] += n
        out = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return out[0] if len(out) == 1 else out

    def accumulate(self):
        out = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return out[0] if len(out) == 1 else out

    def name(self):
        if len(self.topk) == 1:
            return [self._name] if self.topk == (1,) else [f"{self._name}_top{self.topk[0]}"]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name=None):
        super().__init__(name or "precision")
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(_np(preds)).astype(np.int32).reshape(-1)
        labels = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels == 0)))

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0


class Recall(Metric):
    def __init__(self, name=None):
        super().__init__(name or "recall")
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(_np(preds)).astype(np.int32).reshape(-1)
        labels = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds == 0) & (labels == 1)))

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        super().__init__(name or "auc")
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        preds = _np(preds)
        labels = _np(labels).reshape(-1)
        if preds.ndim == 2 and preds.shape[1] == 2:
            pos_prob = preds[:, 1]
        else:
            pos_prob = preds.reshape(-1)
        bins = np.minimum(
            (pos_prob * self.num_thresholds).astype(np.int64), self.num_thresholds
        )
        for b, l in zip(bins, labels):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = 0.0
        tot_neg = 0.0
        auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_pos + tot_pos) * self._stat_neg[i] / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        denom = tot_pos * tot_neg
        return float(auc / denom) if denom else 0.0


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional paddle.metric.accuracy."""
    pred_np = _np(input)
    label_np = _np(label).reshape(-1)
    top = np.argsort(-pred_np, axis=-1)[:, :k]
    hit = (top == label_np[:, None]).max(axis=1).mean()
    import jax.numpy as jnp

    return Tensor(jnp.asarray(np.float32(hit)))
