"""paddle.inference parity: Config / create_predictor / Predictor.

Reference parity: paddle/fluid/inference/api/analysis_predictor.cc +
python/paddle/inference (unverified, mount empty): a deployment API that
loads a saved inference program + params, exposes named input/output
handles, and runs optimized inference.

TPU redesign: the "analysis + IR pass + engine" pipeline IS XLA — the
artifact produced by ``paddle_tpu.jit.save`` is batch-polymorphic
StableHLO, already optimized and retargetable, so the predictor's job
reduces to artifact loading + a handle-based execution surface. The
graph-optimization knobs on Config (IR optim, memory optim, TensorRT)
are accepted for API parity and recorded; they have no effect because
their work is absorbed by the XLA pipeline (documented per-method).
"""
from __future__ import annotations

import os

import numpy as np

from ..core.tensor import Tensor


class PrecisionType:
    Float32 = "float32"
    Half = "float16"
    Bfloat16 = "bfloat16"
    Int8 = "int8"


class PlaceType:
    CPU = "cpu"
    GPU = "gpu"
    TPU = "tpu"


class Config:
    """Holds artifact paths + deployment knobs (reference AnalysisConfig)."""

    def __init__(self, model_file=None, params_file=None, model_dir=None):
        if model_dir and not model_file:
            # find the jit.save prefix inside the directory
            hits = sorted(
                f for f in os.listdir(model_dir)
                if f.endswith(".stablehlo")
            ) if os.path.isdir(model_dir) else []
            if len(hits) == 1:
                model_file = os.path.join(model_dir, hits[0])
            elif hits:
                raise ValueError(
                    f"model_dir {model_dir!r} holds several artifacts "
                    f"({hits}); pass model_file explicitly"
                )
            else:
                model_file = os.path.join(model_dir, "__model__")
        self._model_file = model_file
        self._params_file = params_file
        self._device = "tpu"
        self._device_id = 0
        self._flags = {}

    # ------------------------------------------------------------- artifact
    def set_model(self, model_file, params_file=None):
        self._model_file = model_file
        self._params_file = params_file

    def model_file(self):
        return self._model_file

    def params_file(self):
        return self._params_file

    def prefix(self):
        """The jit.save path prefix (accepts the prefix itself or any of
        the three artifact files)."""
        p = self._model_file or ""
        for suffix in (".json", ".stablehlo", ".pdiparams", ".pdmodel"):
            if p.endswith(suffix):
                return p[: -len(suffix)]
        return p

    # ------------------------------------------------------------- devices
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        # accepted for parity; on this build accelerators mean TPU
        self._device, self._device_id = "tpu", device_id

    def enable_xpu(self, *a, **k):
        self._device = "tpu"

    def disable_gpu(self):
        self._device = "cpu"

    def use_gpu(self):
        return self._device != "cpu"

    # ------------------------------------ absorbed-by-XLA knobs (recorded)
    def switch_ir_optim(self, x=True):
        self._flags["ir_optim"] = x  # XLA passes always run

    def enable_memory_optim(self, x=True):
        self._flags["memory_optim"] = x  # XLA buffer assignment

    def enable_tensorrt_engine(self, *a, **k):
        self._flags["tensorrt"] = True  # XLA is the engine on TPU

    def enable_mkldnn(self):
        self._flags["mkldnn"] = True

    def set_cpu_math_library_num_threads(self, n):
        self._flags["cpu_threads"] = n

    def disable_glog_info(self):
        self._flags["glog_info"] = False

    def set_optim_cache_dir(self, d):
        self._flags["cache_dir"] = d  # XLA compile cache is process-global

    def summary(self):
        return (
            f"Config(model={self._model_file!r}, device={self._device}, "
            f"flags={self._flags})"
        )


class _IOHandle:
    """Named input/output tensor handle (reference paddle_infer::Tensor)."""

    def __init__(self, name):
        self.name = name
        self._value = None
        self._pending_shape = None

    # inputs
    def copy_from_cpu(self, arr):
        self._value = np.ascontiguousarray(arr)
        if self._pending_shape is not None:
            self._value = self._value.reshape(self._pending_shape)
            self._pending_shape = None

    def reshape(self, shape):
        """Reference call order is reshape-then-copy: record the shape
        and apply it to the next copy_from_cpu (or immediately if data
        is already present)."""
        if self._value is not None:
            self._value = self._value.reshape(shape)
        else:
            self._pending_shape = list(shape)
        return self

    def share_external_data(self, t):
        self._value = np.asarray(
            t.numpy() if hasattr(t, "numpy") else t
        )

    # outputs
    def copy_to_cpu(self):
        if self._value is None:
            raise RuntimeError(
                f"handle {self.name!r} has no value; call run() first"
            )
        return np.asarray(self._value)

    def shape(self):
        return list(self._value.shape) if self._value is not None else None


class Predictor:
    def __init__(self, config: Config):
        from ..jit.api import load as jit_load
        import json

        prefix = config.prefix()
        if not os.path.exists(prefix + ".stablehlo"):
            raise FileNotFoundError(
                f"no inference artifact at {prefix!r} (expected "
                f"{prefix}.stablehlo from paddle_tpu.jit.save)"
            )
        self._layer = jit_load(prefix, params_path=config.params_file())
        self._config = config
        with open(prefix + ".json") as f:
            meta = json.load(f)
        # kept whole: into_engine() reads the artifact's [B, S] shape
        self._input_specs = meta.get("input_specs", [])
        n_in = len(self._input_specs)
        names = meta.get("input_names")
        self._input_names = list(names) if names else [
            f"input_{i}" for i in range(n_in)
        ]
        self._inputs = {n: _IOHandle(n) for n in self._input_names}
        self._output_names = []
        self._outputs = {}

    def get_input_names(self):
        return list(self._input_names)

    def get_input_handle(self, name):
        return self._inputs[name]

    def run(self, inputs=None):
        """Execute. Either pass positional arrays here (new-style) or set
        them through handles first (reference style)."""
        if inputs is not None:
            vals = [np.asarray(
                x.numpy() if hasattr(x, "numpy") else x
            ) for x in inputs]
        else:
            missing = [
                n for n in self._input_names
                if self._inputs[n]._value is None
            ]
            if missing:
                raise RuntimeError(
                    f"inputs {missing} not set; use "
                    "get_input_handle(name).copy_from_cpu(arr)"
                )
            vals = [self._inputs[n]._value for n in self._input_names]
        out = self._layer(*(Tensor(np.asarray(v)) for v in vals))
        outs = out if isinstance(out, (list, tuple)) else [out]
        self._output_names = [f"output_{i}" for i in range(len(outs))]
        self._outputs = {}
        results = []
        for n, o in zip(self._output_names, outs):
            arr = np.asarray(o.numpy() if hasattr(o, "numpy") else o)
            h = _IOHandle(n)
            h._value = arr
            self._outputs[n] = h
            results.append(arr)
        return results

    def get_output_names(self):
        return list(self._output_names)

    def get_output_handle(self, name):
        return self._outputs[name]

    def clear_intermediate_tensor(self):
        pass  # XLA owns buffers

    def try_shrink_memory(self):
        pass

    # ------------------------------------------------------------ serving
    def into_engine(self, **kwargs):
        """Serve this saved decode artifact through the
        ``paddle_tpu.serving`` request surface: returns a
        :class:`serving.StaticBatchEngine` that queues requests with
        backpressure/deadlines/metrics and runs them in batches of the
        artifact's fixed batch size. (A saved program is one
        shape-specialized whole-decode computation, so true continuous
        batching needs the live net — ``serving.ServingEngine``.)

        ``paged=True`` accounts the artifact's KV residency through the
        serving page pool (claim while a batch is in flight, zero-leak
        when idle — same surface as ``PagedServingEngine``) and, via
        the per-token streaming callbacks every engine now carries,
        lets saved artifacts sit behind the HTTP/SSE front-end without
        code changes."""
        from ..serving import StaticBatchEngine

        return StaticBatchEngine(self, **kwargs)


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
