"""Pooling functionals via lax.reduce_window.

Reference parity: python/paddle/nn/functional/pooling.py (unverified, mount
empty). Channel-first layouts by default, adaptive variants included.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ...core import dispatch
from .conv import _conv_padding, _tuplize


def _window(nd, k, s, channel_last):
    if channel_last:
        dims = (1,) + k + (1,)
        strides = (1,) + s + (1,)
    else:
        dims = (1, 1) + k
        strides = (1, 1) + s
    return dims, strides


def _full_pad(nd, pad, channel_last, x, k, s, ceil_mode):
    """Expand spatial pad pairs to full-rank, adding right-side extra padding
    for ceil_mode (so the last partial window is kept, paddle parity)."""
    if isinstance(pad, str):
        return pad
    pad = [list(p) for p in pad]
    if ceil_mode:
        spatial_off = 1 if channel_last else 2
        for d in range(nd):
            in_s = x.shape[spatial_off + d]
            eff = in_s + pad[d][0] + pad[d][1]
            rem = (eff - k[d]) % s[d]
            if rem != 0:
                pad[d][1] += s[d] - rem
    pairs = tuple(tuple(p) for p in pad)
    if channel_last:
        return ((0, 0),) + pairs + ((0, 0),)
    return ((0, 0), (0, 0)) + pairs


def _max_pool(x, *, nd, k, s, pad, channel_last, ceil_mode):
    dims, strides = _window(nd, k, s, channel_last)
    padding = _full_pad(nd, pad, channel_last, x, k, s, ceil_mode)
    init = (
        -jnp.inf
        if jnp.issubdtype(x.dtype, jnp.floating)
        else jnp.iinfo(x.dtype).min
    )
    return jax.lax.reduce_window(x, init, jax.lax.max, dims, strides, padding)


def _avg_pool(x, *, nd, k, s, pad, channel_last, exclusive, ceil_mode):
    dims, strides = _window(nd, k, s, channel_last)
    padding = _full_pad(nd, pad, channel_last, x, k, s, ceil_mode)
    summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, dims, strides, padding)
    if exclusive:
        # divide each window by its in-bounds element count (string padding
        # included — 'SAME' zero-pads and paddle excludes those zeros)
        ones = jnp.ones_like(x)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, dims, strides, padding)
        return summed / counts
    return summed / float(np.prod(k))


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    if return_mask:
        if data_format != "NCL":
            raise ValueError("return_mask=True supports NCL only")
        k = _tuplize(kernel_size, 1)
        s = _tuplize(stride if stride is not None else kernel_size, 1)
        pad = _conv_padding(padding, 1)
        if isinstance(pad, str):
            raise ValueError(
                "max_pool1d(return_mask=True) needs explicit int padding"
            )
        return dispatch.apply(
            "max_pool1d_mask", _max_pool1d_with_mask, (x,),
            {"k": k, "s": s, "pad": pad, "ceil_mode": bool(ceil_mode)},
        )
    return _pool_entry(_max_pool, x, 1, kernel_size, stride, padding, data_format,
                       dict(ceil_mode=bool(ceil_mode)))


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    if return_mask:
        if data_format != "NCHW":
            raise ValueError("return_mask=True supports NCHW only")
        return max_pool2d_with_mask(
            x, kernel_size, stride, padding, ceil_mode
        )
    return _pool_entry(_max_pool, x, 2, kernel_size, stride, padding, data_format,
                       dict(ceil_mode=bool(ceil_mode)))


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    if return_mask:
        if data_format != "NCDHW":
            raise ValueError("return_mask=True supports NCDHW only")
        k = _tuplize(kernel_size, 3)
        s = _tuplize(stride if stride is not None else kernel_size, 3)
        pad = _conv_padding(padding, 3)
        if isinstance(pad, str):
            raise ValueError(
                "max_pool3d(return_mask=True) needs explicit int padding"
            )
        return dispatch.apply(
            "max_pool3d_mask", _max_pool3d_with_mask, (x,),
            {"k": k, "s": s, "pad": pad, "ceil_mode": bool(ceil_mode)},
        )
    return _pool_entry(_max_pool, x, 3, kernel_size, stride, padding, data_format,
                       dict(ceil_mode=bool(ceil_mode)))


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool_entry(_avg_pool, x, 1, kernel_size, stride, padding, data_format,
                       dict(exclusive=bool(exclusive), ceil_mode=bool(ceil_mode)))


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool_entry(_avg_pool, x, 2, kernel_size, stride, padding, data_format,
                       dict(exclusive=bool(exclusive), ceil_mode=bool(ceil_mode)))


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool_entry(_avg_pool, x, 3, kernel_size, stride, padding, data_format,
                       dict(exclusive=bool(exclusive), ceil_mode=bool(ceil_mode)))


def _pool_entry(fn, x, nd, kernel, stride, padding, data_format, extra):
    channel_last = not data_format.startswith("NC")
    k = _tuplize(kernel, nd)
    s = _tuplize(stride if stride is not None else kernel, nd)
    pad = _conv_padding(padding, nd)
    kw = {
        "nd": nd,
        "k": k,
        "s": s,
        "pad": pad if isinstance(pad, str) else tuple(tuple(p) for p in pad),
        "channel_last": channel_last,
    }
    kw.update(extra)
    return dispatch.apply(fn.__name__, fn, (x,), kw)


def _adaptive_pool(x, *, nd, out_sizes, channel_last, op):
    # general adaptive pooling via per-dim segment means/maxes
    spatial_off = 1 if channel_last else 2
    v = x
    for d in range(nd):
        axis = spatial_off + d
        in_s = v.shape[axis]
        out_s = out_sizes[d]
        if in_s == out_s:
            continue
        if in_s % out_s == 0:
            f = in_s // out_s
            new_shape = v.shape[:axis] + (out_s, f) + v.shape[axis + 1 :]
            vr = v.reshape(new_shape)
            v = (jnp.max if op == "max" else jnp.mean)(vr, axis=axis + 1)
        else:
            # non-divisible: gather per output index (paddle formula)
            starts = [int(np.floor(i * in_s / out_s)) for i in range(out_s)]
            ends = [int(np.ceil((i + 1) * in_s / out_s)) for i in range(out_s)]
            slices = []
            for st, en in zip(starts, ends):
                sl = jax.lax.slice_in_dim(v, st, en, axis=axis)
                slices.append(
                    (jnp.max if op == "max" else jnp.mean)(sl, axis=axis, keepdims=True)
                )
            v = jnp.concatenate(slices, axis=axis)
    return v


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_entry(x, 1, output_size, "NCL", "avg")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_entry(x, 2, output_size, data_format, "avg")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_entry(x, 3, output_size, data_format, "avg")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_entry(x, 1, output_size, "NCL", "max")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_entry(x, 2, output_size, "NCHW", "max")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_entry(x, 3, output_size, "NCDHW", "max")


def _adaptive_entry(x, nd, output_size, data_format, op):
    channel_last = not data_format.startswith("NC")
    out = _tuplize(output_size, nd)
    out = tuple(
        o if o is not None else x.shape[(1 if channel_last else 2) + i]
        for i, o in enumerate(out)
    )
    return dispatch.apply(
        f"adaptive_{op}_pool{nd}d",
        _adaptive_pool,
        (x,),
        {"nd": nd, "out_sizes": out, "channel_last": channel_last, "op": op},
    )


def _max_pool2d_with_mask(x, *, k, s, pad, ceil_mode):
    """Max pool that also returns the argmax flat index (per-channel
    H*W offset) — the reference's return_mask contract, consumed by
    max_unpool2d. Patches come from dtype-preserving strided slices and
    the flat index is reconstructed with exact integer arithmetic (no
    float32 index round-trip)."""
    n, c, h, w = x.shape
    padding = _full_pad(2, pad, False, x, k, s, ceil_mode)
    neg = (
        -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
        else jnp.iinfo(x.dtype).min
    )
    (ph0, ph1), (pw0, pw1) = padding[2], padding[3]
    xp = jnp.pad(
        x, ((0, 0), (0, 0), (ph0, ph1), (pw0, pw1)),
        constant_values=neg,
    )
    kh, kw = k
    hp, wp = xp.shape[2], xp.shape[3]
    oh = (hp - kh) // s[0] + 1
    ow = (wp - kw) // s[1] + 1
    taps = [
        xp[:, :, i:i + oh * s[0]:s[0], j:j + ow * s[1]:s[1]]
        for i in range(kh) for j in range(kw)
    ]
    xpat = jnp.stack(taps, axis=2)  # [N, C, kh*kw, oh, ow], input dtype
    am = jnp.argmax(xpat, axis=2)  # first-max tie-break, torch parity
    out = jnp.max(xpat, axis=2)
    # tap t at output (oy, ox) reads input (oy*s0 - ph0 + t//kw,
    # ox*s1 - pw0 + t%kw); flat per-channel index = iy*w + ix
    oy = jnp.arange(oh)[:, None]
    ox = jnp.arange(ow)[None, :]
    iy = oy * s[0] - ph0 + am // kw
    ix = ox * s[1] - pw0 + am % kw
    mask = (iy * w + ix).astype(jnp.int32)
    return out, mask


def max_pool2d_with_mask(x, kernel_size, stride=None, padding=0,
                         ceil_mode=False, name=None):
    k = _tuplize(kernel_size, 2)
    s = _tuplize(stride if stride is not None else kernel_size, 2)
    pad = _conv_padding(padding, 2)
    if isinstance(pad, str):
        raise ValueError(
            "max_pool2d(return_mask=True) needs explicit int padding"
        )
    return dispatch.apply(
        "max_pool2d_mask", _max_pool2d_with_mask, (x,),
        {"k": k, "s": s, "pad": pad, "ceil_mode": bool(ceil_mode)},
    )


def _max_pool1d_with_mask(x, *, k, s, pad, ceil_mode):
    """1-D analog of _max_pool2d_with_mask (flat per-channel L index)."""
    n, c, l = x.shape
    padding = _full_pad(1, pad, False, x, k, s, ceil_mode)
    neg = (
        -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
        else jnp.iinfo(x.dtype).min
    )
    (pl0, pl1) = padding[2]
    xp = jnp.pad(x, ((0, 0), (0, 0), (pl0, pl1)), constant_values=neg)
    kl, = k
    lp = xp.shape[2]
    ol = (lp - kl) // s[0] + 1
    taps = [xp[:, :, i:i + ol * s[0]:s[0]] for i in range(kl)]
    xpat = jnp.stack(taps, axis=2)  # [N, C, kl, ol]
    am = jnp.argmax(xpat, axis=2)
    out = jnp.max(xpat, axis=2)
    oi = jnp.arange(ol)[None, None, :]
    mask = (oi * s[0] - pl0 + am).astype(jnp.int32)
    return out, mask


def _max_pool3d_with_mask(x, *, k, s, pad, ceil_mode):
    """3-D analog of _max_pool2d_with_mask (flat per-channel D*H*W)."""
    n, c, d, h, w = x.shape
    padding = _full_pad(3, pad, False, x, k, s, ceil_mode)
    neg = (
        -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
        else jnp.iinfo(x.dtype).min
    )
    (pd0, pd1), (ph0, ph1), (pw0, pw1) = padding[2], padding[3], padding[4]
    xp = jnp.pad(
        x, ((0, 0), (0, 0), (pd0, pd1), (ph0, ph1), (pw0, pw1)),
        constant_values=neg,
    )
    kd, kh, kw = k
    dp, hp, wp = xp.shape[2], xp.shape[3], xp.shape[4]
    od = (dp - kd) // s[0] + 1
    oh = (hp - kh) // s[1] + 1
    ow = (wp - kw) // s[2] + 1
    taps = [
        xp[:, :, a:a + od * s[0]:s[0], i:i + oh * s[1]:s[1],
           j:j + ow * s[2]:s[2]]
        for a in range(kd) for i in range(kh) for j in range(kw)
    ]
    xpat = jnp.stack(taps, axis=2)  # [N, C, kd*kh*kw, od, oh, ow]
    am = jnp.argmax(xpat, axis=2)
    out = jnp.max(xpat, axis=2)
    oz = jnp.arange(od)[:, None, None]
    oy = jnp.arange(oh)[None, :, None]
    ox = jnp.arange(ow)[None, None, :]
    iz = oz * s[0] - pd0 + am // (kh * kw)
    iy = oy * s[1] - ph0 + (am // kw) % kh
    ix = ox * s[2] - pw0 + am % kw
    mask = ((iz * h + iy) * w + ix).astype(jnp.int32)
    return out, mask


def _max_unpool2d(x, mask, *, out_hw):
    n, c, oh, ow = x.shape
    h, w = out_hw
    flat = jnp.zeros((n, c, h * w), x.dtype)
    midx = mask.reshape(n, c, -1).astype(jnp.int32)
    vals = x.reshape(n, c, -1)
    flat = jax.vmap(jax.vmap(lambda f, m, v: f.at[m].set(v)))(
        flat, midx, vals
    )
    return flat.reshape(n, c, h, w)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    """Scatter pooled values back to their argmax positions (zeros
    elsewhere); inverse of max_pool2d(return_mask=True)."""
    k = _tuplize(kernel_size, 2)
    s = _tuplize(stride if stride is not None else kernel_size, 2)
    if output_size is not None:
        from ...ops._helpers import static_int_list

        osz = tuple(static_int_list(output_size))[-2:]
    else:
        oh, ow = int(x.shape[-2]), int(x.shape[-1])
        p = _conv_padding(padding, 2)
        ph = p[0][0] if not isinstance(p, str) else 0
        pw = p[1][0] if not isinstance(p, str) else 0
        osz = (
            (oh - 1) * s[0] - 2 * ph + k[0],
            (ow - 1) * s[1] - 2 * pw + k[1],
        )
    return dispatch.apply(
        "max_unpool2d", _max_unpool2d, (x, indices), {"out_hw": osz}
    )


def _max_unpool1d(x, mask, *, out_l):
    n, c, ol = x.shape
    flat = jnp.zeros((n, c, out_l), x.dtype)
    midx = mask.astype(jnp.int32)
    flat = jax.vmap(jax.vmap(lambda f, m, v: f.at[m].set(v)))(
        flat, midx, x
    )
    return flat


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    """Inverse of max_pool1d(return_mask=True)."""
    k = _tuplize(kernel_size, 1)
    s = _tuplize(stride if stride is not None else kernel_size, 1)
    if output_size is not None:
        from ...ops._helpers import static_int_list

        out_l = int(static_int_list(output_size)[-1])
    else:
        p = _conv_padding(padding, 1)
        pl = p[0][0] if not isinstance(p, str) else 0
        out_l = (int(x.shape[-1]) - 1) * s[0] - 2 * pl + k[0]
    return dispatch.apply(
        "max_unpool1d", _max_unpool1d, (x, indices), {"out_l": out_l}
    )


def _max_unpool3d(x, mask, *, out_dhw):
    n, c = x.shape[:2]
    d, h, w = out_dhw
    flat = jnp.zeros((n, c, d * h * w), x.dtype)
    midx = mask.reshape(n, c, -1).astype(jnp.int32)
    vals = x.reshape(n, c, -1)
    flat = jax.vmap(jax.vmap(lambda f, m, v: f.at[m].set(v)))(
        flat, midx, vals
    )
    return flat.reshape(n, c, d, h, w)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    """Inverse of max_pool3d(return_mask=True)."""
    k = _tuplize(kernel_size, 3)
    s = _tuplize(stride if stride is not None else kernel_size, 3)
    if output_size is not None:
        from ...ops._helpers import static_int_list

        osz = tuple(static_int_list(output_size))[-3:]
    else:
        p = _conv_padding(padding, 3)
        pads = [pp[0] if not isinstance(pp, str) else 0 for pp in p]
        osz = tuple(
            (int(x.shape[-3 + i]) - 1) * s[i] - 2 * pads[i] + k[i]
            for i in range(3)
        )
    return dispatch.apply(
        "max_unpool3d", _max_unpool3d, (x, indices), {"out_dhw": osz}
    )
