"""Loss functionals.

Reference parity: python/paddle/nn/functional/loss.py (unverified, mount
empty). cross_entropy mirrors paddle semantics: integer or soft labels,
ignore_index, per-class weight, reduction modes.
"""
from __future__ import annotations

import functools as _functools

import jax
import jax.numpy as jnp

from ...core import dispatch
from ...core import enforce as _enf


def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def _cross_entropy(logits, label, weight, *, soft_label, axis, ignore_index,
                   reduction, use_softmax, label_smoothing):
    axis_ = axis % logits.ndim
    if use_softmax:
        logp = jax.nn.log_softmax(logits, axis=axis_)
    else:
        logp = jnp.log(jnp.maximum(logits, 1e-30))
    n_classes = logits.shape[axis_]

    if soft_label or (label.ndim == logits.ndim and label.shape == logits.shape):
        soft = label
        if label_smoothing > 0:
            soft = soft * (1 - label_smoothing) + label_smoothing / n_classes
        loss = -jnp.sum(soft * logp, axis=axis_)
        if weight is not None:
            w = jnp.sum(soft * weight.reshape((1,) * axis_ + (-1,)), axis=axis_)
            loss = loss * w
        return _reduce(loss, reduction)

    lbl = label
    if lbl.ndim == logits.ndim and lbl.shape[axis_] == 1:
        lbl = jnp.squeeze(lbl, axis=axis_)
    lbl = lbl.astype(jnp.int32)
    safe_lbl = jnp.where(lbl == ignore_index, 0, lbl)
    picked = jnp.take_along_axis(
        logp, jnp.expand_dims(safe_lbl, axis_), axis=axis_
    )
    loss = -jnp.squeeze(picked, axis=axis_)
    valid = lbl != ignore_index
    if label_smoothing > 0:
        smooth_loss = -jnp.mean(logp, axis=axis_)
        loss = (1 - label_smoothing) * loss + label_smoothing * smooth_loss
    if weight is not None:
        w = weight[safe_lbl]
        loss = loss * w
    loss = jnp.where(valid, loss, 0.0)
    if reduction == "mean":
        if weight is not None:
            denom = jnp.sum(jnp.where(valid, weight[safe_lbl], 0.0))
        else:
            denom = jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
        return jnp.sum(loss) / denom
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def cross_entropy(
    input,
    label,
    weight=None,
    ignore_index=-100,
    reduction="mean",
    soft_label=False,
    axis=-1,
    use_softmax=True,
    label_smoothing=0.0,
    name=None,
):
    _enf.enforce(
        reduction in ("mean", "sum", "none"), "cross_entropy",
        "reduction must be 'mean', 'sum' or 'none', but received {!r}",
        reduction,
    )
    if not soft_label:
        _enf.check_int_dtype("cross_entropy", "label", label)
        if hasattr(input, "shape") and hasattr(label, "shape"):
            nd_in, nd_lbl = len(input.shape), len(label.shape)
            ok = nd_lbl == nd_in - 1 or (
                nd_lbl == nd_in
                and int(label.shape[int(axis) % nd_in]) == 1
            )
            _enf.enforce(
                ok, "cross_entropy",
                "hard label expected ndim {} (or {} with size 1 on the "
                "class axis), but received label shape {} for input "
                "shape {}",
                nd_in - 1, nd_in, tuple(label.shape), tuple(input.shape),
            )
    return dispatch.apply(
        "cross_entropy",
        _cross_entropy,
        (input, label, weight),
        {
            "soft_label": bool(soft_label),
            "axis": int(axis),
            "ignore_index": int(ignore_index),
            "reduction": reduction,
            "use_softmax": bool(use_softmax),
            "label_smoothing": float(label_smoothing),
        },
    )


def _nll_loss(logp, label, weight, *, ignore_index, reduction):
    lbl = label.astype(jnp.int32)
    safe = jnp.where(lbl == ignore_index, 0, lbl)
    picked = jnp.take_along_axis(logp, safe[:, None], axis=1)[:, 0]
    loss = -picked
    valid = lbl != ignore_index
    if weight is not None:
        loss = loss * weight[safe]
    loss = jnp.where(valid, loss, 0.0)
    if reduction == "mean":
        denom = (
            jnp.sum(jnp.where(valid, weight[safe], 0.0))
            if weight is not None
            else jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
        )
        return jnp.sum(loss) / denom
    return _reduce(loss, reduction)


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    return dispatch.apply(
        "nll_loss",
        _nll_loss,
        (input, label, weight),
        {"ignore_index": int(ignore_index), "reduction": reduction},
    )


def _mse_loss(x, y, *, reduction):
    return _reduce(jnp.square(x - y), reduction)


def mse_loss(input, label, reduction="mean", name=None):
    return dispatch.apply("mse_loss", _mse_loss, (input, label), {"reduction": reduction})


def _l1_loss(x, y, *, reduction):
    return _reduce(jnp.abs(x - y), reduction)


def l1_loss(input, label, reduction="mean", name=None):
    return dispatch.apply("l1_loss", _l1_loss, (input, label), {"reduction": reduction})


def _smooth_l1(x, y, *, reduction, delta):
    d = x - y
    ad = jnp.abs(d)
    loss = jnp.where(ad < delta, 0.5 * d * d / delta, ad - 0.5 * delta)
    return _reduce(loss, reduction)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    return dispatch.apply(
        "smooth_l1_loss",
        _smooth_l1,
        (input, label),
        {"reduction": reduction, "delta": float(delta)},
    )


def _bce(x, y, w, *, reduction):
    loss = -(y * jnp.log(jnp.maximum(x, 1e-12)) + (1 - y) * jnp.log(jnp.maximum(1 - x, 1e-12)))
    if w is not None:
        loss = loss * w
    return _reduce(loss, reduction)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    return dispatch.apply(
        "binary_cross_entropy", _bce, (input, label, weight), {"reduction": reduction}
    )


def _bce_logits(x, y, w, pos_w, *, reduction):
    max_val = jnp.maximum(-x, 0.0)
    if pos_w is not None:
        log_w = (pos_w - 1.0) * y + 1.0
        loss = (1 - y) * x + log_w * (
            jnp.log(jnp.exp(-max_val) + jnp.exp(-x - max_val)) + max_val
        )
    else:
        loss = (1 - y) * x + max_val + jnp.log(
            jnp.exp(-max_val) + jnp.exp(-x - max_val)
        )
    if w is not None:
        loss = loss * w
    return _reduce(loss, reduction)


def binary_cross_entropy_with_logits(
    logit, label, weight=None, reduction="mean", pos_weight=None, name=None
):
    return dispatch.apply(
        "bce_with_logits",
        _bce_logits,
        (logit, label, weight, pos_weight),
        {"reduction": reduction},
    )


def _kl_div(x, y, *, reduction, log_target):
    if log_target:
        loss = jnp.exp(y) * (y - x)
    else:
        loss = y * (jnp.log(jnp.maximum(y, 1e-12)) - x)
    if reduction == "batchmean":
        return jnp.sum(loss) / x.shape[0]
    return _reduce(loss, reduction)


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    return dispatch.apply(
        "kl_div",
        _kl_div,
        (input, label),
        {"reduction": reduction, "log_target": bool(log_target)},
    )


def _margin_ranking(x1, x2, lbl, *, margin, reduction):
    loss = jnp.maximum(0.0, -lbl * (x1 - x2) + margin)
    return _reduce(loss, reduction)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    return dispatch.apply(
        "margin_ranking_loss",
        _margin_ranking,
        (input, other, label),
        {"margin": float(margin), "reduction": reduction},
    )


def _hinge_embedding(x, y, *, margin, reduction):
    loss = jnp.where(y == 1, x, jnp.maximum(0.0, margin - x))
    return _reduce(loss, reduction)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    return dispatch.apply(
        "hinge_embedding_loss",
        _hinge_embedding,
        (input, label),
        {"margin": float(margin), "reduction": reduction},
    )


def _cosine_embedding(x1, x2, y, *, margin, reduction):
    cos = jnp.sum(x1 * x2, axis=-1) / jnp.maximum(
        jnp.linalg.norm(x1, axis=-1) * jnp.linalg.norm(x2, axis=-1), 1e-12
    )
    loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
    return _reduce(loss, reduction)


def cosine_embedding_loss(input1, input2, label, margin=0, reduction="mean", name=None):
    return dispatch.apply(
        "cosine_embedding_loss",
        _cosine_embedding,
        (input1, input2, label),
        {"margin": float(margin), "reduction": reduction},
    )


def _triplet_margin(a, p, n, *, margin, p_norm, swap, reduction):
    def dist(u, v):
        return jnp.power(
            jnp.sum(jnp.power(jnp.abs(u - v), p_norm), axis=-1), 1.0 / p_norm
        )

    d_ap = dist(a, p)
    d_an = dist(a, n)
    if swap:
        d_pn = dist(p, n)
        d_an = jnp.minimum(d_an, d_pn)
    loss = jnp.maximum(0.0, d_ap - d_an + margin)
    return _reduce(loss, reduction)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2, epsilon=1e-06,
                        swap=False, reduction="mean", name=None):
    return dispatch.apply(
        "triplet_margin_loss",
        _triplet_margin,
        (input, positive, negative),
        {
            "margin": float(margin),
            "p_norm": float(p),
            "swap": bool(swap),
            "reduction": reduction,
        },
    )


def _sigmoid_focal(logit, label, norm, *, alpha, gamma, reduction):
    p = jax.nn.sigmoid(logit)
    ce = jnp.maximum(logit, 0) - logit * label + jnp.log1p(jnp.exp(-jnp.abs(logit)))
    p_t = p * label + (1 - p) * (1 - label)
    a_t = alpha * label + (1 - alpha) * (1 - label)
    loss = a_t * jnp.power(1 - p_t, gamma) * ce
    if norm is not None:
        loss = loss / norm
    return _reduce(loss, reduction)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    return dispatch.apply(
        "sigmoid_focal_loss",
        _sigmoid_focal,
        (logit, label, normalizer),
        {"alpha": float(alpha), "gamma": float(gamma), "reduction": reduction},
    )


def square_error_cost(input, label):
    def _sec(x, y):
        return jnp.square(x - y)

    return dispatch.apply("square_error_cost", _sec, (input, label))


def log_loss(input, label, epsilon=0.0001, name=None):
    def _log_loss(x, y, *, eps):
        return -y * jnp.log(x + eps) - (1 - y) * jnp.log(1 - x + eps)

    return dispatch.apply(
        "log_loss", _log_loss, (input, label), {"eps": float(epsilon)}
    )


def _soft_margin(x, y, *, reduction):
    # log(1 + exp(-yx)) = -log_sigmoid(yx), stable for large |logits|
    return _reduce(-jax.nn.log_sigmoid(y.astype(x.dtype) * x), reduction)


def soft_margin_loss(input, label, reduction="mean", name=None):
    return dispatch.apply(
        "soft_margin_loss", _soft_margin, (input, label),
        {"reduction": reduction},
    )


def _multi_label_soft_margin(x, y, w, *, reduction):
    yf = y.astype(x.dtype)
    per_class = -(
        yf * jax.nn.log_sigmoid(x) + (1 - yf) * jax.nn.log_sigmoid(-x)
    )
    if w is not None:
        per_class = per_class * w
    return _reduce(jnp.mean(per_class, axis=-1), reduction)


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean",
                                 name=None):
    return dispatch.apply(
        "multi_label_soft_margin_loss", _multi_label_soft_margin,
        (input, label, weight), {"reduction": reduction},
    )


def _multi_margin(x, y, w, *, p, margin, reduction):
    n, c = x.shape
    correct = jnp.take_along_axis(x, y[:, None], axis=1)
    viol = jnp.maximum(0.0, margin - correct + x) ** p
    if w is not None:
        viol = viol * w[y][:, None]
    # the true-class term contributes margin^p; numpy-oracle parity drops it
    viol = viol * (1 - jax.nn.one_hot(y, c, dtype=x.dtype))
    return _reduce(jnp.sum(viol, axis=1) / c, reduction)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    return dispatch.apply(
        "multi_margin_loss", _multi_margin, (input, label, weight),
        {"p": int(p), "margin": float(margin), "reduction": reduction},
    )


def _poisson_nll(x, y, *, log_input, full, eps, reduction):
    if log_input:
        loss = jnp.exp(x) - y * x
    else:
        loss = x - y * jnp.log(x + eps)
    if full:
        stirling = y * jnp.log(y) - y + 0.5 * jnp.log(2 * jnp.pi * y)
        loss = loss + jnp.where(y > 1, stirling, 0.0)
    return _reduce(loss, reduction)


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    return dispatch.apply(
        "poisson_nll_loss", _poisson_nll, (input, label),
        {"log_input": bool(log_input), "full": bool(full),
         "eps": float(epsilon), "reduction": reduction},
    )


def _gaussian_nll(x, y, var, *, full, eps, reduction):
    var = jnp.maximum(var, eps)
    loss = 0.5 * (jnp.log(var) + jnp.square(x - y) / var)
    if full:
        loss = loss + 0.5 * jnp.log(2 * jnp.pi)
    return _reduce(loss, reduction)


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    return dispatch.apply(
        "gaussian_nll_loss", _gaussian_nll, (input, label, variance),
        {"full": bool(full), "eps": float(epsilon), "reduction": reduction},
    )


# ------------------------------------------------------------------- CTC
def _ctc_alpha_scan(logp, ext, ext_mask):
    """Log-space CTC alpha recursion for one sample.

    logp: [T, C] log-probabilities; ext: [S] blank-interleaved labels
    (S = 2*Lmax+1); ext_mask[s] = can skip from s-2 to s (ext[s] != blank
    and ext[s] != ext[s-2]).
    """
    T, _ = logp.shape
    S = ext.shape[0]
    neg_inf = jnp.asarray(-1e30, logp.dtype)
    alpha0 = jnp.full((S,), neg_inf).at[0].set(logp[0, ext[0]])
    alpha0 = alpha0.at[1].set(logp[0, ext[1]])

    def step(alpha, lp):
        stay = alpha
        prev1 = jnp.concatenate([jnp.full((1,), neg_inf), alpha[:-1]])
        prev2 = jnp.concatenate([jnp.full((2,), neg_inf), alpha[:-2]])
        prev2 = jnp.where(ext_mask, prev2, neg_inf)
        merged = jnp.logaddexp(jnp.logaddexp(stay, prev1), prev2)
        alpha_t = merged + lp[ext]
        return alpha_t, alpha_t

    _, alphas = jax.lax.scan(step, alpha0, logp[1:])
    return jnp.concatenate([alpha0[None], alphas], axis=0)  # [T, S]


def _ctc_loss(logits, labels, in_lens, lbl_lens, *, blank, reduction,
              norm_by_times):
    # logits [T, B, C] raw (softmax applied here), labels [B, Lmax]
    logp = jax.nn.log_softmax(logits, axis=-1)
    T, B, C = logp.shape
    Lmax = labels.shape[1]
    S = 2 * Lmax + 1
    pos = jnp.arange(S)
    ext = jnp.where(
        pos[:, None] % 2 == 0, blank,
        labels[:, jnp.minimum(pos // 2, Lmax - 1)].T
    ).T.astype(jnp.int32)  # [B, S]
    ext_prev2 = jnp.concatenate(
        [jnp.full((B, 2), -1, jnp.int32), ext[:, :-2]], axis=1
    )
    ext_mask = (ext != blank) & (ext != ext_prev2)

    alphas = jax.vmap(_ctc_alpha_scan, in_axes=(1, 0, 0))(
        logp, ext, ext_mask
    )  # [B, T, S]
    t_last = jnp.clip(in_lens - 1, 0, T - 1)
    alpha_last = jnp.take_along_axis(
        alphas, t_last[:, None, None], axis=1
    )[:, 0, :]  # [B, S]
    s_last = 2 * lbl_lens  # index of final blank
    end_blank = jnp.take_along_axis(alpha_last, s_last[:, None], axis=1)[:, 0]
    end_label = jnp.take_along_axis(
        alpha_last, jnp.maximum(s_last - 1, 0)[:, None], axis=1
    )[:, 0]
    # empty target: only the all-blank path exists; the clamped s_last-1
    # index would alias end_blank and double-count it
    end_label = jnp.where(lbl_lens > 0, end_label, -jnp.inf)
    ll = jnp.logaddexp(end_blank, end_label)
    loss = -ll
    if norm_by_times:
        loss = loss / jnp.maximum(in_lens.astype(loss.dtype), 1.0)
    if reduction == "mean":
        return jnp.mean(loss / jnp.maximum(lbl_lens.astype(loss.dtype), 1.0))
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False, name=None):
    """CTC loss (log-space forward recursion over the blank-interleaved
    label sequence, lax.scan over time; grads via autodiff).

    ``log_probs``: [max_T, batch, num_classes] raw logits (softmax is
    applied internally, matching the reference's warpctc contract).
    Reference parity: python/paddle/nn/functional/loss.py ctc_loss row.
    """
    return dispatch.apply(
        "ctc_loss", _ctc_loss,
        (log_probs, labels, input_lengths, label_lengths),
        {"blank": int(blank), "reduction": reduction,
         "norm_by_times": bool(norm_by_times)},
    )


def _dice_loss(x, lbl, *, eps):
    # x [N, ..., C] probabilities, lbl [N, ..., 1] int class ids
    lbl_onehot = jax.nn.one_hot(lbl[..., 0], x.shape[-1], dtype=x.dtype)
    reduce_dims = tuple(range(1, x.ndim))
    inter = 2.0 * jnp.sum(x * lbl_onehot, axis=reduce_dims)
    union = (
        jnp.sum(x, axis=reduce_dims) + jnp.sum(lbl_onehot, axis=reduce_dims)
    )
    return 1.0 - (inter + eps) / (union + eps)


def dice_loss(input, label, epsilon=1e-5, name=None):
    return dispatch.apply(
        "dice_loss", _dice_loss, (input, label), {"eps": float(epsilon)}
    )


def _npair_loss(anchor, positive, labels, *, l2_reg):
    # cross-entropy over anchor @ positive^T with same-label targets
    sim = jnp.matmul(anchor, positive.T)
    same = (labels[:, None] == labels[None, :]).astype(anchor.dtype)
    targets = same / jnp.sum(same, axis=1, keepdims=True)
    logp = jax.nn.log_softmax(sim, axis=1)
    xent = jnp.mean(-jnp.sum(targets * logp, axis=1))
    reg = l2_reg * 0.25 * (
        jnp.mean(jnp.sum(jnp.square(anchor), 1))
        + jnp.mean(jnp.sum(jnp.square(positive), 1))
    )
    return xent + reg


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    return dispatch.apply(
        "npair_loss", _npair_loss, (anchor, positive, labels),
        {"l2_reg": float(l2_reg)},
    )


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    """triplet_margin_loss with a user distance (reference:
    nn/functional/loss.py triplet_margin_with_distance_loss). The
    distance callable runs inside the dispatch trace, so any paddle ops
    it uses fuse into the same compiled step."""
    if distance_function is None:
        from .common import pairwise_distance

        distance_function = pairwise_distance
    d_pos = distance_function(input, positive)
    d_neg = distance_function(input, negative)
    if swap:
        from ...ops.math import minimum

        d_neg = minimum(d_neg, distance_function(positive, negative))
    from ...ops.math import maximum, subtract
    from ...ops.creation import zeros_like

    loss = maximum(subtract(d_pos, d_neg) + float(margin),
                   zeros_like(d_pos))
    if reduction == "mean":
        return loss.mean()
    if reduction == "sum":
        return loss.sum()
    return loss


def _hsigmoid(x, w, b, *, codes, signs):
    # x: [N, D]; w: [C-1, D]; codes: [N, L] int path-node ids (-1 = pad);
    # signs: [N, L] +-1 target code (0 on pads)
    logits = jnp.einsum("nd,nld->nl", x, w[codes.clip(0)])
    if b is not None:
        logits = logits + b[codes.clip(0)]
    mask = (codes >= 0).astype(x.dtype)
    # per-node BCE with target from the sign: -log sigmoid(sign * logit)
    loss = jnp.logaddexp(0.0, -signs * logits) * mask
    return jnp.sum(loss, axis=1, keepdims=True)  # [N, 1] (paddle contract)


@_functools.lru_cache(maxsize=64)
def _hsigmoid_tree(num_classes):
    """Complete-binary-tree path table for the default hsigmoid tree;
    depends only on num_classes, so cached across calls/steps."""
    import numpy as np

    n_inner = int(num_classes) - 1
    depth = max(1, int(np.ceil(np.log2(max(num_classes, 2)))))
    codes = np.full((num_classes, depth), -1, np.int32)
    signs = np.zeros((num_classes, depth), np.float32)
    for c in range(num_classes):
        node = c + n_inner  # leaf id in the implicit heap
        path = []
        while node > 0:
            parent = (node - 1) // 2
            path.append((parent, -1.0 if node == 2 * parent + 1 else 1.0))
            node = parent
        for li, (p, s) in enumerate(reversed(path)):
            if li < depth:
                codes[c, li] = p
                signs[c, li] = s
    return jnp.asarray(codes), jnp.asarray(signs)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss over a complete binary tree, returning
    the per-sample [N, 1] loss (reference: nn/functional/loss.py
    hsigmoid_loss; the custom-tree form takes path_table/path_code).
    Tree layout matches the reference default: internal node i has
    children 2i+1 / 2i+2, classes are the leaves, and each class's path
    is the route from the root."""
    if path_table is None:
        table_t, code_t = _hsigmoid_tree(int(num_classes))

        def fn(x, lbl, w, b):
            l = lbl.reshape(-1).astype(jnp.int32)
            return _hsigmoid(x, w, b, codes=table_t[l], signs=code_t[l])
    else:
        def fn(x, lbl, w, b, pt=path_table, pc=path_code):
            ptv = jnp.asarray(pt.value if hasattr(pt, "value") else pt)
            pcv = jnp.asarray(pc.value if hasattr(pc, "value") else pc)
            # paddle custom trees: path_code is the 0/1 branch bit
            signs = jnp.where(pcv > 0, 1.0, -1.0) * (ptv >= 0)
            return _hsigmoid(
                x, w, b, codes=ptv.astype(jnp.int32),
                signs=signs.astype(x.dtype),
            )

    args = (input, label, weight) + ((bias,) if bias is not None else ())

    def wrapped(x, lbl, w, *rest):
        return fn(x, lbl, w, rest[0] if rest else None)

    return dispatch.apply("hsigmoid_loss", wrapped, args, cache=False)


def _margin_ce(logits, lbl, *, m1, m2, m3, scale, reduction,
               return_softmax):
    n, c = logits.shape
    onehot = jax.nn.one_hot(lbl, c, dtype=logits.dtype)
    # stay strictly inside (-1, 1): d/dx arccos diverges at the bounds,
    # and saturated bf16 cosines hit exactly +-1.0 routinely under AMP
    eps = 1e-6
    cos = jnp.clip(logits, -1.0 + eps, 1.0 - eps)
    theta = jnp.arccos(cos)
    target = jnp.cos(m1 * theta + m2) - m3
    adjusted = jnp.where(onehot > 0, target.astype(logits.dtype), cos)
    scaled = adjusted * scale
    logp = jax.nn.log_softmax(scaled, axis=1)
    loss = -jnp.sum(onehot * logp, axis=1, keepdims=True)
    if reduction == "mean":
        loss = jnp.mean(loss)
    elif reduction == "sum":
        loss = jnp.sum(loss)
    if return_softmax:
        return loss, jnp.exp(logp)
    return loss


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    """ArcFace/CosFace-family margin softmax (reference:
    nn/functional/loss.py margin_cross_entropy). ``logits`` are
    cosine similarities in [-1, 1]. The reference's model-parallel
    ``group`` form shards classes over ranks; here class-sharded logits
    are handled by GSPMD when the call sits in a compiled step — the
    ``group`` arg is accepted and the math is identical (softmax over
    the full class axis)."""
    return dispatch.apply(
        "margin_cross_entropy", _margin_ce, (logits, label),
        {"m1": float(margin1), "m2": float(margin2), "m3": float(margin3),
         "scale": float(scale), "reduction": reduction,
         "return_softmax": bool(return_softmax)},
    )
