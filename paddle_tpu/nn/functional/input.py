"""Embedding / one_hot functionals.

Reference parity: python/paddle/nn/functional/input.py (unverified, mount
empty). embedding is a gather — XLA lowers it to an efficient dynamic-gather
on TPU; the VJP is a scatter-add, no custom grad kernel needed.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...core import dispatch
from ...core import enforce as _enf
from ...ops.creation import one_hot  # noqa: F401  (paddle exposes F.one_hot)


def _embedding(weight, x, *, padding_idx, sparse):
    out = jnp.take(weight, x, axis=0)
    if padding_idx is not None:
        mask = (x != padding_idx)[..., None]
        out = out * mask.astype(out.dtype)
    return out


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    _enf.check_ndim("embedding", "weight", weight, exact_ndim=2)
    _enf.check_int_dtype("embedding", "x", x)
    return dispatch.apply(
        "embedding",
        _embedding,
        (weight, x),
        {
            "padding_idx": None if padding_idx is None else int(padding_idx),
            "sparse": bool(sparse),
        },
    )
