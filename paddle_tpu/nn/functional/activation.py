"""Activation functionals (python/paddle/nn/functional/activation.py parity,
unverified, mount empty). Pure jnp compositions — XLA fuses these into
adjacent matmuls on TPU, which is why no hand-written fused kernels exist."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core import dispatch
from ...ops._helpers import unary

relu = unary("relu", jax.nn.relu)
relu6 = unary("relu6", jax.nn.relu6)
sigmoid = unary("sigmoid", jax.nn.sigmoid)
tanh = unary("tanh", jnp.tanh)
silu = unary("silu", jax.nn.silu)
swish = silu
mish = unary("mish", jax.nn.mish)
softsign = unary("softsign", jax.nn.soft_sign)
tanhshrink = unary("tanhshrink", lambda x: x - jnp.tanh(x))
hardswish = unary("hardswish", jax.nn.hard_swish)
log_sigmoid = unary("log_sigmoid", jax.nn.log_sigmoid)
hardsigmoid = unary("hardsigmoid", lambda x: jnp.clip(x / 6.0 + 0.5, 0.0, 1.0))


def _gelu(x, *, approximate):
    return jax.nn.gelu(x, approximate=approximate)


def gelu(x, approximate=False, name=None):
    return dispatch.apply("gelu", _gelu, (x,), {"approximate": bool(approximate)})


def _leaky_relu(x, *, slope):
    return jax.nn.leaky_relu(x, slope)


def leaky_relu(x, negative_slope=0.01, name=None):
    return dispatch.apply(
        "leaky_relu", _leaky_relu, (x,), {"slope": float(negative_slope)}
    )


def _elu(x, *, alpha):
    return jax.nn.elu(x, alpha)


def elu(x, alpha=1.0, name=None):
    return dispatch.apply("elu", _elu, (x,), {"alpha": float(alpha)})


def _celu(x, *, alpha):
    return jax.nn.celu(x, alpha)


def celu(x, alpha=1.0, name=None):
    return dispatch.apply("celu", _celu, (x,), {"alpha": float(alpha)})


def _selu(x, *, scale, alpha):
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


def selu(
    x,
    scale=1.0507009873554805,
    alpha=1.6732632423543772,
    name=None,
):
    return dispatch.apply(
        "selu", _selu, (x,), {"scale": float(scale), "alpha": float(alpha)}
    )


def _softmax(x, *, axis):
    return jax.nn.softmax(x, axis=axis)


def softmax(x, axis=-1, dtype=None, name=None):
    out = dispatch.apply("softmax", _softmax, (x,), {"axis": int(axis)})
    if dtype is not None:
        out = out.astype(dtype)
    return out


def _log_softmax(x, *, axis):
    return jax.nn.log_softmax(x, axis=axis)


def log_softmax(x, axis=-1, dtype=None, name=None):
    out = dispatch.apply("log_softmax", _log_softmax, (x,), {"axis": int(axis)})
    if dtype is not None:
        out = out.astype(dtype)
    return out


def _softplus(x, *, beta, threshold):
    scaled = beta * x
    return jnp.where(scaled > threshold, x, jax.nn.softplus(scaled) / beta)


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return dispatch.apply(
        "softplus", _softplus, (x,), {"beta": float(beta), "threshold": float(threshold)}
    )


def _softshrink(x, *, threshold):
    return jnp.where(
        x > threshold, x - threshold, jnp.where(x < -threshold, x + threshold, 0.0)
    )


def softshrink(x, threshold=0.5, name=None):
    return dispatch.apply(
        "softshrink", _softshrink, (x,), {"threshold": float(threshold)}
    )


def _hardshrink(x, *, threshold):
    return jnp.where(jnp.abs(x) > threshold, x, 0.0)


def hardshrink(x, threshold=0.5, name=None):
    return dispatch.apply(
        "hardshrink", _hardshrink, (x,), {"threshold": float(threshold)}
    )


def _hardtanh(x, *, mn, mx):
    return jnp.clip(x, mn, mx)


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return dispatch.apply("hardtanh", _hardtanh, (x,), {"mn": float(min), "mx": float(max)})


def _thresholded_relu(x, *, threshold, value):
    return jnp.where(x > threshold, x, value)


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return dispatch.apply(
        "thresholded_relu",
        _thresholded_relu,
        (x,),
        {"threshold": float(threshold), "value": float(value)},
    )


def _prelu(x, w):
    if w.size == 1:
        return jnp.where(x >= 0, x, w.reshape(()) * x)
    # channel-wise: weight has num_channels elements; data is NC...
    shape = [1] * x.ndim
    shape[1] = w.size
    return jnp.where(x >= 0, x, w.reshape(shape) * x)


def prelu(x, weight, data_format="NCHW", name=None):
    return dispatch.apply("prelu", _prelu, (x, weight))


def _rrelu_eval(x, *, lower, upper):
    return jnp.where(x >= 0, x, 0.5 * (lower + upper) * x)


def rrelu(x, lower=0.125, upper=0.3333333333333333, training=False, name=None):
    if training:
        from ...core import random as random_mod

        k = random_mod.next_key()

        def _rrelu_train(xv):
            a = jax.random.uniform(
                k, xv.shape, xv.dtype, minval=lower, maxval=upper
            )
            return jnp.where(xv >= 0, xv, a * xv)

        return dispatch.apply("rrelu_train", _rrelu_train, (x,), cache=False)
    return dispatch.apply(
        "rrelu", _rrelu_eval, (x,), {"lower": float(lower), "upper": float(upper)}
    )


def _glu(x, *, axis):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


def glu(x, axis=-1, name=None):
    return dispatch.apply("glu", _glu, (x,), {"axis": int(axis)})


def _maxout(x, *, groups, axis):
    shape = list(x.shape)
    c = shape[axis]
    shape[axis] = c // groups
    shape.insert(axis + 1, groups)
    return jnp.max(x.reshape(shape), axis=axis + 1)


def maxout(x, groups, axis=1, name=None):
    return dispatch.apply(
        "maxout", _maxout, (x,), {"groups": int(groups), "axis": int(axis)}
    )


def _softmax_with_cross_entropy(logits, label, *, soft_label, axis, ignore_index):
    logp = jax.nn.log_softmax(logits, axis=axis)
    if soft_label:
        return -jnp.sum(label * logp, axis=axis, keepdims=True)
    lbl = label
    squeeze = False
    if lbl.ndim == logits.ndim:
        lbl = jnp.squeeze(lbl, axis=axis)
        squeeze = True
    picked = jnp.take_along_axis(
        logp, jnp.expand_dims(lbl, axis), axis=axis
    )
    loss = -picked
    if ignore_index >= 0:
        mask = jnp.expand_dims(lbl, axis) != ignore_index
        loss = jnp.where(mask, loss, 0.0)
    return loss


def softmax_with_cross_entropy(
    logits,
    label,
    soft_label=False,
    ignore_index=-100,
    numeric_stable_mode=True,
    return_softmax=False,
    axis=-1,
):
    loss = dispatch.apply(
        "softmax_with_cross_entropy",
        _softmax_with_cross_entropy,
        (logits, label),
        {
            "soft_label": bool(soft_label),
            "axis": int(axis),
            "ignore_index": int(ignore_index),
        },
    )
    if return_softmax:
        return loss, softmax(logits, axis=axis)
    return loss


def _gumbel_softmax(x, key, *, temperature, hard, axis):
    g = -jnp.log(-jnp.log(jax.random.uniform(key, x.shape) + 1e-20) + 1e-20)
    y = jax.nn.softmax((x + g) / temperature, axis=axis)
    if hard:
        idx = jnp.argmax(y, axis=axis, keepdims=True)
        y_hard = jnp.zeros_like(y)
        y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis, inplace=False)
        y = y_hard - jax.lax.stop_gradient(y) + y
    return y


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...core import random as random_mod

    key = random_mod.next_key()  # raw key array: non-Tensor, non-diff arg

    def _gs(xv, kv):
        return _gumbel_softmax(
            xv, kv, temperature=temperature, hard=hard, axis=axis
        )

    return dispatch.apply("gumbel_softmax", _gs, (x, key), cache=False)
