"""Enforce coverage for the nn.functional surface.

Reference parity: every phi kernel is wrapped in PADDLE_ENFORCE_*
precondition checks (paddle/phi/core/enforce.h — unverified, mount
empty); the Python layer mirrors them via check_variable_and_dtype in
data_feeder.py. Reproducing that breadth one hand-written check at a
time does not scale, so this module is a declarative table: each entry
names an op, the argument positions to validate, the dtype class, and
the ndim contract. ``install`` wraps the already-imported functions in
the package namespace — internal modules that import from the
submodules directly skip the wrapper (no double-checking on internal
call chains); the public ``paddle.nn.functional`` surface gets it.

Checks run per call on the eager path and once per trace under jit;
they exist for message quality — XLA remains the correctness backstop.
"""
from __future__ import annotations

import functools

from ...core.enforce import check_dtype, check_int_dtype, check_ndim

_MISSING = object()

# (arg_index, arg_name, dtype_kind, ndim_spec)
#   dtype_kind: "float" | "int" | None
#   ndim_spec:  None | int (min_ndim) | ("exact", n_or_tuple)
_X_FLOAT = [(0, "x", "float", None)]


def _conv(n):
    return [(0, "x", "float", ("exact", n)),
            (1, "weight", "float", ("exact", n))]


def _pool(n):
    return [(0, "x", "float", ("exact", n))]


TABLE = {
    # --------------------------------------------------- activations
    **{name: _X_FLOAT for name in (
        "celu", "elu", "gelu", "hardshrink", "hardsigmoid", "hardswish",
        "hardtanh", "leaky_relu", "log_sigmoid", "log_softmax", "mish",
        "relu", "relu6", "rrelu", "selu", "sigmoid", "silu", "softmax",
        "softplus", "softshrink", "softsign", "swish", "tanh",
        "tanhshrink", "thresholded_relu",
    )},
    "glu": [(0, "x", "float", 1)],
    "maxout": [(0, "x", "float", ("exact", 4))],
    "prelu": [(0, "x", "float", None), (1, "weight", "float", None)],
    "gumbel_softmax": [(0, "x", "float", 1)],
    # -------------------------------------------------------- common
    "linear": [(0, "x", "float", 1), (1, "weight", "float", ("exact", 2))],
    "bilinear": [(0, "x1", "float", ("exact", 2)),
                 (1, "x2", "float", ("exact", 2))],
    "cosine_similarity": [(0, "x1", "float", 1), (1, "x2", "float", 1)],
    "dropout": _X_FLOAT,
    "dropout2d": [(0, "x", "float", ("exact", 4))],
    "dropout3d": [(0, "x", "float", ("exact", 5))],
    "alpha_dropout": _X_FLOAT,
    "pad": [(0, "x", None, 1)],
    "interpolate": [(0, "x", "float", 3)],
    "upsample": [(0, "x", "float", 3)],
    "fold": [(0, "x", "float", ("exact", 3))],
    "unfold": [(0, "x", "float", ("exact", 4))],
    "pixel_shuffle": [(0, "x", "float", ("exact", 4))],
    "pixel_unshuffle": [(0, "x", "float", ("exact", 4))],
    "channel_shuffle": [(0, "x", "float", ("exact", 4))],
    "zeropad2d": [(0, "x", None, ("exact", 4))],
    "label_smooth": [(0, "label", "float", 1)],
    # --------------------------------------------------- conv / pool
    "conv1d": _conv(3),
    "conv2d": _conv(4),
    "conv3d": _conv(5),
    "conv1d_transpose": _conv(3),
    "conv2d_transpose": _conv(4),
    "conv3d_transpose": _conv(5),
    "avg_pool1d": _pool(3),
    "avg_pool2d": _pool(4),
    "avg_pool3d": _pool(5),
    "max_pool1d": _pool(3),
    "max_pool2d": _pool(4),
    "max_pool3d": _pool(5),
    "adaptive_avg_pool1d": _pool(3),
    "adaptive_avg_pool2d": _pool(4),
    "adaptive_avg_pool3d": _pool(5),
    "adaptive_max_pool1d": _pool(3),
    "adaptive_max_pool2d": _pool(4),
    "adaptive_max_pool3d": _pool(5),
    # ---------------------------------------------------------- norm
    "batch_norm": [(0, "x", "float", 2)],
    "layer_norm": [(0, "x", "float", 1)],
    "instance_norm": [(0, "x", "float", 3)],
    "group_norm": [(0, "x", "float", 2)],
    "local_response_norm": [(0, "x", "float", 3)],
    "normalize": [(0, "x", "float", 1)],
    "rms_norm": [(0, "x", "float", 1)],
    # ---------------------------------------------------------- loss
    "cross_entropy": [(0, "input", "float", 1)],
    "mse_loss": [(0, "input", "float", None), (1, "label", "float", None)],
    "l1_loss": [(0, "input", "float", None), (1, "label", "float", None)],
    "smooth_l1_loss": [(0, "input", "float", None),
                       (1, "label", "float", None)],
    "kl_div": [(0, "input", "float", None), (1, "label", "float", None)],
    "nll_loss": [(0, "input", "float", 2), (1, "label", "int", 1)],
    "binary_cross_entropy": [(0, "input", "float", None),
                             (1, "label", "float", None)],
    "binary_cross_entropy_with_logits": [
        (0, "logit", "float", None), (1, "label", "float", None)],
    "margin_ranking_loss": [(0, "input", "float", None),
                            (1, "other", "float", None)],
    "hinge_embedding_loss": [(0, "input", "float", None)],
    "triplet_margin_loss": [(0, "input", "float", 1)],
    "cosine_embedding_loss": [(0, "input1", "float", 1),
                              (1, "input2", "float", 1)],
    # --------------------------------------------------------- input
    "embedding": [(0, "x", "int", None),
                  (1, "weight", "float", ("exact", 2))],
    "one_hot": [(0, "x", "int", None)],
    # ----------------------------------------------------- attention
    "scaled_dot_product_attention": [
        (0, "query", "float", ("exact", 4)),
        (1, "key", "float", ("exact", 4)),
        (2, "value", "float", ("exact", 4)),
    ],
}


def _wrap(fn, op, checks):
    @functools.wraps(fn)
    def inner(*args, **kwargs):
        for idx, name, kind, nd in checks:
            v = args[idx] if idx < len(args) else kwargs.get(name, _MISSING)
            if v is _MISSING or v is None or isinstance(
                v, (int, float, bool)
            ):
                continue  # scalars broadcast; absent args -> fn's error
            if kind == "float":
                check_dtype(op, name, v)
            elif kind == "int":
                check_int_dtype(op, name, v)
            if isinstance(nd, int):
                check_ndim(op, name, v, min_ndim=nd)
            elif isinstance(nd, tuple):
                check_ndim(op, name, v, exact_ndim=nd[1])
        return fn(*args, **kwargs)

    inner.__enforced__ = True
    return inner


def install(namespace):
    """Wrap every TABLE entry present in ``namespace`` (the package's
    globals()). Missing names are an error — the table must not drift
    from the surface it claims to cover."""
    missing = [k for k in TABLE if k not in namespace]
    if missing:
        raise RuntimeError(
            f"enforce table names absent from nn.functional: {missing}"
        )
    for op, checks in TABLE.items():
        fn = namespace[op]
        if not getattr(fn, "__enforced__", False):
            namespace[op] = _wrap(fn, op, checks)
