"""Vision functionals: affine_grid / grid_sample / temporal_shift
(reference: python/paddle/nn/functional/vision.py — unverified).

grid_sample is a bilinear/nearest gather — XLA lowers it to gathers +
fused arithmetic; no dynamic shapes."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core import dispatch
from ...ops.tail import temporal_shift  # noqa: F401  (re-export)


def _affine_grid(theta, *, size, align_corners):
    n, _, h, w = size
    if align_corners:
        ys = jnp.linspace(-1.0, 1.0, h)
        xs = jnp.linspace(-1.0, 1.0, w)
    else:
        ys = (jnp.arange(h) * 2.0 + 1.0) / h - 1.0
        xs = (jnp.arange(w) * 2.0 + 1.0) / w - 1.0
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1).reshape(-1, 3)  # [H*W, 3]
    out = jnp.einsum("nij,pj->npi", theta.astype(base.dtype), base)
    return out.reshape(n, h, w, 2)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """theta [N, 2, 3] -> sampling grid [N, H, W, 2] (x, y order)."""
    from ...ops._helpers import static_int_list

    size = tuple(static_int_list(out_shape))
    if len(size) != 4:
        raise ValueError(f"affine_grid expects NCHW out_shape, got {size}")
    return dispatch.apply(
        "affine_grid", _affine_grid, (theta,),
        {"size": size, "align_corners": bool(align_corners)},
    )


def _unnormalize(coord, size, align_corners):
    if align_corners:
        return (coord + 1.0) / 2.0 * (size - 1)
    return ((coord + 1.0) * size - 1.0) / 2.0


def _reflect_coord(v, size, align_corners):
    """Reflect a continuous coordinate into the valid range: around
    pixel centers [0, size-1] (align_corners) or the pixel-edge box
    [-0.5, size-0.5] (torch/paddle convention)."""
    if size == 1:
        return jnp.zeros_like(v)
    lo = 0.0 if align_corners else -0.5
    hi = (size - 1.0) if align_corners else (size - 0.5)
    period = 2.0 * (hi - lo)
    vf = (v - lo) % period
    vf = jnp.minimum(vf, period - vf) + lo
    return jnp.clip(vf, 0.0, size - 1.0)


def _grid_sample(x, grid, *, mode, padding_mode, align_corners):
    n, c, h, w = x.shape
    gx = _unnormalize(grid[..., 0], w, align_corners)  # [N, Hg, Wg]
    gy = _unnormalize(grid[..., 1], h, align_corners)
    if padding_mode == "reflection":
        # reflect the CONTINUOUS coordinate, then sample border-style
        gx = _reflect_coord(gx, w, align_corners)
        gy = _reflect_coord(gy, h, align_corners)

    def pixel(img, iy, ix):
        # img [C, H, W]; iy/ix int grids
        if padding_mode in ("border", "reflection"):
            iyc = jnp.clip(iy, 0, h - 1)
            ixc = jnp.clip(ix, 0, w - 1)
            return img[:, iyc, ixc]
        # zeros
        inb = (iy >= 0) & (iy <= h - 1) & (ix >= 0) & (ix <= w - 1)
        iyc = jnp.clip(iy, 0, h - 1)
        ixc = jnp.clip(ix, 0, w - 1)
        return img[:, iyc, ixc] * inb.astype(img.dtype)

    def sample_one(img, sy, sx):
        if mode == "nearest":
            return pixel(
                img, jnp.round(sy).astype(jnp.int32),
                jnp.round(sx).astype(jnp.int32),
            )
        y0 = jnp.floor(sy)
        x0 = jnp.floor(sx)
        wy1 = (sy - y0).astype(img.dtype)
        wx1 = (sx - x0).astype(img.dtype)
        y0i = y0.astype(jnp.int32)
        x0i = x0.astype(jnp.int32)
        return (
            pixel(img, y0i, x0i) * (1 - wy1) * (1 - wx1)
            + pixel(img, y0i, x0i + 1) * (1 - wy1) * wx1
            + pixel(img, y0i + 1, x0i) * wy1 * (1 - wx1)
            + pixel(img, y0i + 1, x0i + 1) * wy1 * wx1
        )

    return jax.vmap(sample_one)(x, gy, gx)  # [N, C, Hg, Wg]


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"grid_sample: unsupported mode {mode!r}")
    if padding_mode not in ("zeros", "border", "reflection"):
        raise ValueError(
            f"grid_sample: unsupported padding_mode {padding_mode!r}"
        )
    return dispatch.apply(
        "grid_sample", _grid_sample, (x, grid),
        {"mode": mode, "padding_mode": padding_mode,
         "align_corners": bool(align_corners)},
    )
