"""Common functionals: linear, dropout, interpolate, pad, unfold, cosine_sim.

Reference parity: python/paddle/nn/functional/common.py (unverified, mount
empty). linear keeps paddle's [in, out] weight layout — a straight MXU matmul.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core import dispatch
from ...core import enforce as _enf
from ...core import random as random_mod
from ...ops.manipulation import pad  # re-export, paddle exposes F.pad  # noqa: F401


def _linear(x, w, b):
    # AMP O3: inside an armed fp8 context (CompiledTrainStep traces
    # with amp_level="O3") the matmul runs with e4m3 operands and
    # delayed per-tensor scaling; one thread-local read otherwise
    from ...amp import fp8

    if fp8.active():
        return fp8.fp8_linear_value(x, w, b)
    y = jnp.matmul(x, w)
    if b is not None:
        y = y + b
    return y


def linear(x, weight, bias=None, name=None):
    _enf.check_ndim("linear", "weight", weight, exact_ndim=2)
    _enf.check_same_trailing("linear", "x", x, "weight", weight)
    return dispatch.apply("linear", _linear, (x, weight, bias))


def _dropout_train(x, key, *, p, upscale):
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, x.shape)
    if upscale:
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)
    return jnp.where(mask, x, 0.0).astype(x.dtype)


def _dropout_downscale_infer(x, *, p):
    return x * (1.0 - p)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if p == 0.0 or not training:
        if mode == "downgrade_in_infer" and not training and p > 0:
            return dispatch.apply(
                "dropout_infer", _dropout_downscale_infer, (x,), {"p": float(p)}
            )
        return x
    key = random_mod.next_key()
    if axis is not None:
        axes = (axis,) if isinstance(axis, int) else tuple(axis)

        def _dropout_axis(xv, kv):
            shape = [
                s if i in axes else 1 for i, s in enumerate(xv.shape)
            ]
            keep = 1.0 - p
            mask = jax.random.bernoulli(kv, keep, shape)
            if mode == "upscale_in_train":
                return jnp.where(mask, xv / keep, 0.0).astype(xv.dtype)
            return jnp.where(mask, xv, 0.0).astype(xv.dtype)

        return dispatch.apply("dropout_axis", _dropout_axis, (x, key), cache=False)
    return dispatch.apply(
        "dropout",
        _dropout_train,
        (x, key),
        {"p": float(p), "upscale": mode == "upscale_in_train"},
    )


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    if not training or p == 0.0:
        return x
    axis = (0, 1) if data_format == "NCHW" else (0, 3)
    return dropout(x, p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    if not training or p == 0.0:
        return x
    axis = (0, 1) if data_format == "NCDHW" else (0, 4)
    return dropout(x, p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    key = random_mod.next_key()

    def _alpha_dropout(xv, kv):
        keep = 1.0 - p
        mask = jax.random.bernoulli(kv, keep, xv.shape)
        a = (keep + p * alpha_p**2 * keep) ** -0.5
        b = -a * alpha_p * p
        return (a * jnp.where(mask, xv, alpha_p) + b).astype(xv.dtype)

    return dispatch.apply("alpha_dropout", _alpha_dropout, (x, key), cache=False)


def _cosine_similarity(x1, x2, *, axis, eps):
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.linalg.norm(x1, axis=axis)
    n2 = jnp.linalg.norm(x2, axis=axis)
    return dot / jnp.maximum(n1 * n2, eps)


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    return dispatch.apply(
        "cosine_similarity",
        _cosine_similarity,
        (x1, x2),
        {"axis": int(axis), "eps": float(eps)},
    )


def _interp_size(x, size, scale_factor, data_format):
    nd = x.ndim - 2
    if data_format.startswith("NC"):
        spatial = x.shape[2:]
    else:
        spatial = x.shape[1:-1]
    if size is not None:
        if hasattr(size, "tolist"):
            size = size.tolist()
        out = tuple(int(s) for s in (size if isinstance(size, (list, tuple)) else [size] * nd))
    else:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * nd
        out = tuple(int(s * f) for s, f in zip(spatial, sf))
    return out


def interpolate(
    x,
    size=None,
    scale_factor=None,
    mode="nearest",
    align_corners=False,
    align_mode=0,
    data_format="NCHW",
    name=None,
):
    out_spatial = _interp_size(x, size, scale_factor, data_format)
    channel_first = data_format.startswith("NC")
    jmode = {
        "nearest": "nearest",
        "bilinear": "linear",
        "linear": "linear",
        "trilinear": "linear",
        "bicubic": "cubic",
        "area": "linear",
    }[mode]

    def _interp(xv):
        v = xv
        if channel_first:
            # jax.image.resize wants explicit full shape
            full = v.shape[:2] + out_spatial
        else:
            full = (v.shape[0],) + out_spatial + (v.shape[-1],)
        if mode == "nearest":
            return jax.image.resize(v, full, method="nearest")
        return jax.image.resize(v, full, method=jmode)

    return dispatch.apply("interpolate", _interp, (x,), cache=False)


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
             align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def _unfold(x, *, k, s, p, d):
    n, c, h, w = x.shape
    xp = jnp.pad(x, ((0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])))
    kh, kw = k
    oh = (xp.shape[2] - (d[0] * (kh - 1) + 1)) // s[0] + 1
    ow = (xp.shape[3] - (d[1] * (kw - 1) + 1)) // s[1] + 1
    patches = jax.lax.conv_general_dilated_patches(
        xp,
        filter_shape=(kh, kw),
        window_strides=s,
        padding="VALID",
        rhs_dilation=d,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )
    return patches.reshape(n, c * kh * kw, oh * ow)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    def _pair(v, n=2):
        return tuple(v) if isinstance(v, (list, tuple)) else (v,) * n

    k = _pair(kernel_sizes)
    s = _pair(strides)
    d = _pair(dilations)
    p = _pair(paddings, 4)
    if len(p) == 2:
        p = (p[0], p[0], p[1], p[1])
    return dispatch.apply(
        "unfold", _unfold, (x,), {"k": k, "s": s, "p": p, "d": d}
    )


def _fold(x, *, output_sizes, k, s, p, d):
    n, ckk, l = x.shape
    c = ckk // (k[0] * k[1])
    oh, ow = output_sizes
    ph = oh + p[0] + p[1]
    pw = ow + p[2] + p[3]
    lh = (ph - (d[0] * (k[0] - 1) + 1)) // s[0] + 1
    lw = (pw - (d[1] * (k[1] - 1) + 1)) // s[1] + 1
    xr = x.reshape(n, c, k[0], k[1], lh, lw)
    out = jnp.zeros((n, c, ph, pw), x.dtype)
    for i in range(k[0]):
        for j in range(k[1]):
            out = out.at[
                :, :, i * d[0] : i * d[0] + lh * s[0] : s[0],
                j * d[1] : j * d[1] + lw * s[1] : s[1],
            ].add(xr[:, :, i, j])
    return out[:, :, p[0] : p[0] + oh, p[2] : p[2] + ow]


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    def _pair(v, n=2):
        return tuple(v) if isinstance(v, (list, tuple)) else (v,) * n

    k = _pair(kernel_sizes)
    s = _pair(strides)
    d = _pair(dilations)
    p = _pair(paddings, 4)
    if len(p) == 2:
        p = (p[0], p[0], p[1], p[1])
    return dispatch.apply(
        "fold",
        _fold,
        (x,),
        {"output_sizes": tuple(output_sizes), "k": k, "s": s, "p": p, "d": d},
    )


def _pixel_shuffle(x, *, r):
    n, c, h, w = x.shape
    oc = c // (r * r)
    xv = x.reshape(n, oc, r, r, h, w)
    xv = jnp.transpose(xv, (0, 1, 4, 2, 5, 3))
    return xv.reshape(n, oc, h * r, w * r)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    return dispatch.apply(
        "pixel_shuffle", _pixel_shuffle, (x,), {"r": int(upscale_factor)}
    )


def _pixel_unshuffle(x, *, r):
    n, c, h, w = x.shape
    oh, ow = h // r, w // r
    xv = x.reshape(n, c, oh, r, ow, r)
    xv = jnp.transpose(xv, (0, 1, 3, 5, 2, 4))
    return xv.reshape(n, c * r * r, oh, ow)


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    return dispatch.apply(
        "pixel_unshuffle", _pixel_unshuffle, (x,), {"r": int(downscale_factor)}
    )


def _label_smooth(label, *, epsilon):
    k = label.shape[-1]
    return (1.0 - epsilon) * label + epsilon / k


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    if prior_dist is not None:
        def _ls_prior(lv, pv):
            return (1.0 - epsilon) * lv + epsilon * pv

        return dispatch.apply("label_smooth_prior", _ls_prior, (label, prior_dist), cache=False)
    return dispatch.apply(
        "label_smooth", _label_smooth, (label,), {"epsilon": float(epsilon)}
    )


def _bilinear(x1, x2, w, b):
    # w: [out, in1, in2]
    y = jnp.einsum("bi,oij,bj->bo", x1, w, x2)
    if b is not None:
        y = y + b
    return y


def bilinear(x1, x2, weight, bias=None, name=None):
    return dispatch.apply("bilinear", _bilinear, (x1, x2, weight, bias))


def _channel_shuffle(x, *, groups, nchw):
    if nchw:
        n, c, h, w = x.shape
        x = x.reshape(n, groups, c // groups, h, w)
        x = jnp.swapaxes(x, 1, 2)
        return x.reshape(n, c, h, w)
    n, h, w, c = x.shape
    x = x.reshape(n, h, w, groups, c // groups)
    x = jnp.swapaxes(x, 3, 4)
    return x.reshape(n, h, w, c)


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    c_axis = 1 if data_format == "NCHW" else 3
    if int(x.shape[c_axis]) % int(groups) != 0:
        raise ValueError(
            f"channel_shuffle: channels {x.shape[c_axis]} not divisible by "
            f"groups {groups}"
        )
    return dispatch.apply(
        "channel_shuffle", _channel_shuffle, (x,),
        {"groups": int(groups), "nchw": data_format == "NCHW"},
    )


def _pairwise_distance(x, y, *, p, eps, keepdim):
    d = jnp.abs(x - y + eps)
    if p == float("inf"):
        return jnp.max(d, axis=-1, keepdims=keepdim)
    if p == float("-inf"):
        return jnp.min(d, axis=-1, keepdims=keepdim)
    if p == 0:
        return jnp.sum((d != 0).astype(x.dtype), axis=-1, keepdims=keepdim)
    return jnp.power(jnp.sum(jnp.power(d, p), axis=-1, keepdims=keepdim),
                     1.0 / p)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    return dispatch.apply(
        "pairwise_distance", _pairwise_distance, (x, y),
        {"p": float(p), "eps": float(epsilon), "keepdim": bool(keepdim)},
    )


def _sequence_mask(lens, *, maxlen, dt):
    return (
        jnp.arange(maxlen)[None, :] < lens.reshape(-1, 1)
    ).astype(dt).reshape(tuple(lens.shape) + (maxlen,))


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    from ...core.dtypes import convert_dtype

    if maxlen is None:
        import numpy as _np

        maxlen = int(_np.asarray(x.numpy()).max())
    return dispatch.apply(
        "sequence_mask", _sequence_mask, (x,),
        {"maxlen": int(maxlen), "dt": jnp.dtype(convert_dtype(dtype))},
    )


def _zeropad2d(x, *, padding, nchw):
    l, r, t, b = padding
    cfg = (
        [(0, 0), (0, 0), (t, b), (l, r)] if nchw
        else [(0, 0), (t, b), (l, r), (0, 0)]
    )
    return jnp.pad(x, cfg)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    from ...ops._helpers import static_int_list

    pad4 = static_int_list(padding)
    if isinstance(pad4, int):
        pad4 = (pad4,) * 4
    return dispatch.apply(
        "zeropad2d", _zeropad2d, (x,),
        {"padding": tuple(pad4), "nchw": data_format == "NCHW"},
    )


def _gather_tree(ids, parents):
    # ids/parents: [max_time, batch, beam]; walk parents backward from
    # the last step reconstructing each beam's token path
    T_, B, W = ids.shape

    def step(beams, inputs):
        ids_t, parents_t = inputs  # [B, W]
        tokens = jnp.take_along_axis(ids_t, beams, axis=1)
        next_beams = jnp.take_along_axis(parents_t, beams, axis=1)
        return next_beams, tokens

    init = jnp.broadcast_to(jnp.arange(W, dtype=parents.dtype), (B, W))
    _, toks = jax.lax.scan(
        step, init,
        (jnp.flip(ids, 0), jnp.flip(parents, 0)),
    )
    return jnp.flip(toks, 0)


def gather_tree(ids, parents, name=None):
    """Beam-search backtrace (reference gather_tree): follow parent
    pointers from the final step to emit each beam's full token path."""
    return dispatch.apply(
        "gather_tree", _gather_tree, (ids, parents), nondiff=True
    )
