"""Convolution functionals over lax.conv_general_dilated.

Reference parity: python/paddle/nn/functional/conv.py + phi conv kernels
(unverified, mount empty). Weight layout matches paddle: [out_c, in_c/groups,
*kernel]; data formats NCL/NCHW/NCDHW (channel-first default) and NHWC-style.
XLA lowers these directly onto the MXU — no im2col or cuDNN-algo selection
machinery is needed.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core import dispatch
from ...core import enforce as _enf


def _tuplize(v, n):
    if isinstance(v, (list, tuple)):
        if len(v) == n:
            return tuple(int(x) for x in v)
        if len(v) == 1:
            return tuple(int(v[0]) for _ in range(n))
        return tuple(int(x) for x in v)
    return (int(v),) * n


def _conv_padding(padding, n):
    """Paddle padding spec -> lax padding: int, list, 'SAME'/'VALID'."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (list, tuple)):
        flat = list(padding)
        if len(flat) == n:
            return [(int(p), int(p)) for p in flat]
        if len(flat) == 2 * n:
            return [(int(flat[2 * i]), int(flat[2 * i + 1])) for i in range(n)]
        if all(isinstance(p, (list, tuple)) for p in flat):
            # full-dim spec incl batch/channel: take spatial entries
            spatial = flat[-n:]
            return [(int(a), int(b)) for a, b in spatial]
    return [(int(padding), int(padding))] * n


def _dn(nd, channel_last):
    if nd == 1:
        return ("NWC", "OIW", "NWC") if channel_last else ("NCW", "OIW", "NCW")
    if nd == 2:
        return ("NHWC", "OIHW", "NHWC") if channel_last else ("NCHW", "OIHW", "NCHW")
    return ("NDHWC", "OIDHW", "NDHWC") if channel_last else ("NCDHW", "OIDHW", "NCDHW")


def _conv_nd(x, w, b, *, nd, stride, padding, dilation, groups, channel_last):
    dn = _dn(nd, channel_last)
    out = jax.lax.conv_general_dilated(
        x,
        w,
        window_strides=stride,
        padding=padding,
        rhs_dilation=dilation,
        dimension_numbers=dn,
        feature_group_count=groups,
    )
    if b is not None:
        if channel_last:
            out = out + b.reshape((1,) * (nd + 1) + (-1,))
        else:
            out = out + b.reshape((1, -1) + (1,) * nd)
    return out


def _conv(x, w, b, nd, stride, padding, dilation, groups, data_format):
    op = f"conv{nd}d"
    channel_last = not data_format.startswith("NC")
    _enf.check_ndim(op, "x", x, exact_ndim=nd + 2)
    _enf.check_ndim(op, "weight", w, exact_ndim=nd + 2)
    if hasattr(x, "shape") and hasattr(w, "shape"):
        in_c = int(x.shape[-1] if channel_last else x.shape[1])
        _enf.enforce(
            in_c == int(w.shape[1]) * int(groups), op,
            "input channels {} != weight in-channels {} x groups {} "
            "(x shape {}, weight shape {}, data_format {})",
            in_c, int(w.shape[1]), int(groups), tuple(x.shape),
            tuple(w.shape), data_format,
        )
    kw = {
        "nd": nd,
        "stride": _tuplize(stride, nd),
        "padding": _freeze_pad(_conv_padding(padding, nd)),
        "dilation": _tuplize(dilation, nd),
        "groups": int(groups),
        "channel_last": channel_last,
    }
    return dispatch.apply(f"conv{nd}d", _conv_nd, (x, w, b), kw)


def _freeze_pad(p):
    return p if isinstance(p, str) else tuple(tuple(q) for q in p)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _conv(x, weight, bias, 1, stride, padding, dilation, groups, data_format)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, 2, stride, padding, dilation, groups, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, 3, stride, padding, dilation, groups, data_format)


def _conv_transpose_nd(
    x, w, b, *, nd, stride, padding, output_padding, dilation, groups, channel_last
):
    dn = _dn(nd, channel_last)
    # paddle transpose-conv weight layout: [in_c, out_c/groups, *k]
    # lax.conv_transpose wants IO layout handled via dimension_numbers; use
    # gradient-based formulation: conv_transpose == lhs-dilated conv
    pad = padding
    if isinstance(pad, str):
        lax_pad = pad
    else:
        k = [w.shape[2 + i] for i in range(nd)]
        lax_pad = [
            (
                dilation[i] * (k[i] - 1) - pad[i][0],
                dilation[i] * (k[i] - 1) - pad[i][1] + output_padding[i],
            )
            for i in range(nd)
        ]
    # weight [in, out/g, *k] -> flip spatial, swap to [out, in/g, *k]
    wf = jnp.flip(w, axis=tuple(range(2, 2 + nd)))
    if groups == 1:
        wt = jnp.swapaxes(wf, 0, 1)
    else:
        ic, ocg = w.shape[0], w.shape[1]
        wg = wf.reshape((groups, ic // groups, ocg) + w.shape[2:])
        wg = jnp.swapaxes(wg, 1, 2)
        wt = wg.reshape((groups * ocg, ic // groups) + w.shape[2:])
    out = jax.lax.conv_general_dilated(
        x,
        wt,
        window_strides=(1,) * nd,
        padding=lax_pad,
        lhs_dilation=stride,
        rhs_dilation=dilation,
        dimension_numbers=_dn(nd, channel_last),
        feature_group_count=groups,
    )
    if b is not None:
        if channel_last:
            out = out + b.reshape((1,) * (nd + 1) + (-1,))
        else:
            out = out + b.reshape((1, -1) + (1,) * nd)
    return out


def _conv_transpose(x, w, b, nd, stride, padding, output_padding, dilation,
                    groups, data_format, output_size=None):
    channel_last = not data_format.startswith("NC")
    kw = {
        "nd": nd,
        "stride": _tuplize(stride, nd),
        "padding": _freeze_pad(_conv_padding(padding, nd)),
        "output_padding": _tuplize(output_padding, nd),
        "dilation": _tuplize(dilation, nd),
        "groups": int(groups),
        "channel_last": channel_last,
    }
    return dispatch.apply(f"conv{nd}d_transpose", _conv_transpose_nd, (x, w, b), kw)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCL",
                     name=None):
    return _conv_transpose(x, weight, bias, 1, stride, padding, output_padding,
                           dilation, groups, data_format, output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCHW",
                     name=None):
    return _conv_transpose(x, weight, bias, 2, stride, padding, output_padding,
                           dilation, groups, data_format, output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCDHW",
                     name=None):
    return _conv_transpose(x, weight, bias, 3, stride, padding, output_padding,
                           dilation, groups, data_format, output_size)
