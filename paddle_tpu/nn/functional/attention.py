"""Attention functionals: scaled_dot_product_attention / flash_attention.

Reference parity: python/paddle/nn/functional/flash_attention.py wrapping the
phi FlashAttnKernel (paddle/phi/kernels/gpu/flash_attn_kernel.cu — unverified,
mount empty). TPU redesign: the fused path is a Pallas flash-attention kernel
(paddle_tpu/kernels/flash_attention.py); this module is the API surface that
picks Pallas on TPU and the jnp composed fallback elsewhere. Layouts follow
paddle: q/k/v are [batch, seqlen, num_heads, head_dim].
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core import dispatch
from ...core import random as random_mod


def _sdpa_ref(q, k, v, mask, *, causal, scale, dropout_p, key):
    # q,k,v: [B, S, H, D] -> compute in [B, H, S, D]
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        causal_mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(causal_mask, s, -jnp.inf)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            s = jnp.where(mask, s, -jnp.inf)
        else:
            s = s + mask
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and key is not None:
        keep = jax.random.bernoulli(key, 1.0 - dropout_p, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_p), 0.0).astype(q.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, vt)
    return jnp.swapaxes(out, 1, 2)


def _use_pallas(q):
    """Pallas flash attention on real TPU; composed jnp elsewhere (CPU CI)."""
    try:
        import jax as _j

        return any(d.platform != "cpu" for d in _j.devices())
    except Exception:
        return False


def scaled_dot_product_attention(
    query,
    key,
    value,
    attn_mask=None,
    dropout_p=0.0,
    is_causal=False,
    training=True,
    name=None,
):
    scale = 1.0 / math.sqrt(query.shape[-1])
    dp = float(dropout_p) if training else 0.0
    rng = random_mod.next_key() if dp > 0.0 else None

    if attn_mask is None and dp == 0.0 and _use_pallas(query):
        from ...kernels import flash_attention as fa

        def _fa(qv, kv, vv):
            return fa.flash_attention_fwd(qv, kv, vv, causal=is_causal, scale=scale)

        return dispatch.apply("flash_attention", _fa, (query, key, value), cache=False)

    def _sdpa(qv, kv, vv, mv):
        return _sdpa_ref(
            qv, kv, vv, mv, causal=is_causal, scale=scale, dropout_p=dp, key=rng
        )

    return dispatch.apply(
        "scaled_dot_product_attention",
        _sdpa,
        (query, key, value, attn_mask),
        cache=False,
    )


def flash_attention(
    query,
    key,
    value,
    dropout=0.0,
    causal=False,
    return_softmax=False,
    fixed_seed_offset=None,
    rng_name="",
    training=True,
    name=None,
):
    """paddle.nn.functional.flash_attention.flash_attention parity."""
    out = scaled_dot_product_attention(
        query, key, value, None, dropout, causal, training
    )
    if return_softmax:
        return out, None
    return out, None if return_softmax else None


def flash_attn_unpadded(
    query, key, value, cu_seqlens_q, cu_seqlens_k, max_seqlen_q, max_seqlen_k,
    scale, dropout=0.0, causal=False, return_softmax=False, training=True,
    name=None,
):
    """Varlen flash attention: segment-masked single-sequence attention.

    The packed [total_tokens, H, D] layout is attended with a block-diagonal
    mask derived from cu_seqlens (reference: phi FlashAttnUnpaddedKernel).
    """
    import numpy as np

    cu_q = np.asarray(
        cu_seqlens_q.numpy() if hasattr(cu_seqlens_q, "numpy") else cu_seqlens_q
    )

    def _varlen(qv, kv, vv):
        total = qv.shape[0]
        seg = jnp.zeros((total,), jnp.int32)
        for i in range(len(cu_q) - 1):
            seg = seg.at[cu_q[i] : cu_q[i + 1]].set(i)
        s = jnp.einsum("qhd,khd->hqk", qv, kv) * scale
        seg_mask = seg[:, None] == seg[None, :]
        if causal:
            pos = jnp.arange(total)
            seg_mask = seg_mask & (pos[None, :] <= pos[:, None])
        s = jnp.where(seg_mask[None], s, -jnp.inf)
        p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(qv.dtype)
        return jnp.einsum("hqk,khd->qhd", p, vv)

    out = dispatch.apply("flash_attn_unpadded", _varlen, (query, key, value), cache=False)
    return out, None
