"""Normalization functionals.

Reference parity: python/paddle/nn/functional/norm.py + phi fused norm
kernels (unverified, mount empty). The reference ships hand-fused CUDA
RMS/LayerNorm kernels (paddle/phi/kernels/fusion/gpu/fused_layernorm_kernel.cu
— unverified); here the default path is plain jnp (XLA fuses it well) and
paddle_tpu.kernels provides Pallas versions behind the same API for the
cases XLA's fusion leaves bandwidth on the table.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core import dispatch
from ...core import enforce as _enf


def _batch_norm_infer(x, mean, var, w, b, *, eps, channel_axis):
    shape = [1] * x.ndim
    shape[channel_axis] = x.shape[channel_axis]
    inv = jax.lax.rsqrt(var.reshape(shape) + eps)
    out = (x - mean.reshape(shape)) * inv
    if w is not None:
        out = out * w.reshape(shape)
    if b is not None:
        out = out + b.reshape(shape)
    return out


def _batch_norm_train(x, w, b, *, eps, channel_axis):
    axes = tuple(i for i in range(x.ndim) if i != channel_axis)
    mean = jnp.mean(x, axis=axes)
    var = jnp.var(x, axis=axes)
    shape = [1] * x.ndim
    shape[channel_axis] = x.shape[channel_axis]
    inv = jax.lax.rsqrt(var.reshape(shape) + eps)
    out = (x - mean.reshape(shape)) * inv
    if w is not None:
        out = out * w.reshape(shape)
    if b is not None:
        out = out + b.reshape(shape)
    return out, mean, var


def batch_norm(
    x,
    running_mean,
    running_var,
    weight=None,
    bias=None,
    training=False,
    momentum=0.9,
    epsilon=1e-05,
    data_format="NCHW",
    use_global_stats=None,
    name=None,
):
    channel_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    use_global = (use_global_stats is True) or not training
    if use_global:
        return dispatch.apply(
            "batch_norm_infer",
            _batch_norm_infer,
            (x, running_mean, running_var, weight, bias),
            {"eps": float(epsilon), "channel_axis": channel_axis},
        )
    out, batch_mean, batch_var = dispatch.apply(
        "batch_norm_train",
        _batch_norm_train,
        (x, weight, bias),
        {"eps": float(epsilon), "channel_axis": channel_axis},
    )
    # update running stats in place (paddle: r = m*r + (1-m)*batch)
    if running_mean is not None:
        from ...core import tape

        with tape.no_grad():
            running_mean.value = (
                momentum * running_mean.value + (1 - momentum) * batch_mean.value
            )
            running_var.value = (
                momentum * running_var.value + (1 - momentum) * batch_var.value
            )
    return out


def _layer_norm(x, w, b, *, eps, begin_axis):
    axes = tuple(range(begin_axis, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + eps)
    if w is not None:
        out = out * w.reshape(x.shape[begin_axis:])
    if b is not None:
        out = out + b.reshape(x.shape[begin_axis:])
    return out


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05, name=None):
    ns = (
        (normalized_shape,)
        if isinstance(normalized_shape, int)
        else tuple(normalized_shape)
    )
    begin_axis = x.ndim - len(ns)
    _enf.enforce(
        begin_axis >= 0 and tuple(
            int(d) for d in x.shape[begin_axis:]
        ) == tuple(int(d) for d in ns),
        "layer_norm",
        "normalized_shape {} must match the trailing dims of input "
        "shape {}", tuple(ns), tuple(x.shape),
    )
    return dispatch.apply(
        "layer_norm",
        _layer_norm,
        (x, weight, bias),
        {"eps": float(epsilon), "begin_axis": begin_axis},
    )


def _rms_norm(x, w, b, *, eps, begin_axis):
    axes = tuple(range(begin_axis, x.ndim))
    ms = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=axes, keepdims=True)
    out = (x.astype(jnp.float32) * jax.lax.rsqrt(ms + eps)).astype(x.dtype)
    if w is not None:
        out = out * w
    if b is not None:
        out = out + b
    return out


def _fused_rms_available(x, weight, bias, begin_axis):
    """Pallas fused path: TPU, last-axis norm, weight-only. fp16 is
    excluded — the Mosaic TPU dialect rejects f16 ('Unsupported type in
    mosaic dialect'); fp16 AMP runs use the composed path, which XLA
    fuses anyway."""
    if bias is not None or weight is None:
        return False
    if begin_axis != x.ndim - 1:
        return False
    if str(getattr(x, "dtype", "")) == "float16":
        return False
    import jax as _j

    return any(d.platform != "cpu" for d in _j.devices())


def rms_norm(x, weight=None, bias=None, epsilon=1e-6, begin_norm_axis=-1, name=None):
    begin_axis = begin_norm_axis % x.ndim
    if _fused_rms_available(x, weight, bias, begin_axis):
        from ...kernels.rms_norm import rms_norm_fused

        def _fused(xv, wv):
            return rms_norm_fused(xv, wv, float(epsilon))

        return dispatch.apply(
            "fused_rms_norm", _fused, (x, weight), cache=False
        )
    return dispatch.apply(
        "rms_norm",
        _rms_norm,
        (x, weight, bias),
        {"eps": float(epsilon), "begin_axis": begin_axis},
    )


def _group_norm(x, w, b, *, groups, eps, channel_axis):
    if channel_axis != 1:
        x = jnp.moveaxis(x, channel_axis, 1)
    n, c = x.shape[:2]
    spatial = x.shape[2:]
    xg = x.reshape((n, groups, c // groups) + spatial)
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    out = ((xg - mean) * jax.lax.rsqrt(var + eps)).reshape(x.shape)
    shape = (1, c) + (1,) * len(spatial)
    if w is not None:
        out = out * w.reshape(shape)
    if b is not None:
        out = out + b.reshape(shape)
    if channel_axis != 1:
        out = jnp.moveaxis(out, 1, channel_axis)
    return out


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None,
               data_format="NCHW", name=None):
    channel_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    return dispatch.apply(
        "group_norm",
        _group_norm,
        (x, weight, bias),
        {"groups": int(num_groups), "eps": float(epsilon), "channel_axis": channel_axis},
    )


def _instance_norm(x, w, b, *, eps):
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + eps)
    if w is not None:
        shape = (1, x.shape[1]) + (1,) * (x.ndim - 2)
        out = out * w.reshape(shape)
    if b is not None:
        shape = (1, x.shape[1]) + (1,) * (x.ndim - 2)
        out = out + b.reshape(shape)
    return out


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-05,
                  data_format="NCHW", name=None):
    return dispatch.apply(
        "instance_norm", _instance_norm, (x, weight, bias), {"eps": float(eps)}
    )


def _normalize(x, *, p, axis, eps):
    if p == 2:
        n = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True))
    else:
        n = jnp.power(
            jnp.sum(jnp.power(jnp.abs(x), p), axis=axis, keepdims=True), 1.0 / p
        )
    return x / jnp.maximum(n, eps)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    return dispatch.apply(
        "normalize",
        _normalize,
        (x,),
        {"p": float(p), "axis": int(axis), "eps": float(epsilon)},
    )


def local_response_norm(x, size, alpha=0.0001, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    def _lrn(xv):
        sq = jnp.square(xv)
        half = size // 2
        c = xv.shape[1]
        pads = [(0, 0)] * xv.ndim
        pads[1] = (half, size - half - 1)
        sq_p = jnp.pad(sq, pads)
        acc = sum(
            jax.lax.slice_in_dim(sq_p, i, i + c, axis=1) for i in range(size)
        )
        return xv / jnp.power(k + alpha * acc, beta)

    return dispatch.apply("local_response_norm", _lrn, (x,), cache=False)
