"""Weight initializers.

Reference parity: python/paddle/nn/initializer/ (unverified, mount empty).
Initializers are callables producing jax arrays; Layer.create_parameter
invokes them with an explicit PRNG key derived from the global seed.
"""
from __future__ import annotations

import math

import numpy as np

import jax
import jax.numpy as jnp

from ..core import random as random_mod
from ..core.dtypes import convert_dtype


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, dtype=dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        k = random_mod.next_key()
        return self.mean + self.std * jax.random.normal(k, shape, dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        k = random_mod.next_key()
        return self.mean + self.std * jax.random.truncated_normal(
            k, self.a, self.b, shape, dtype
        )


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        k = random_mod.next_key()
        return jax.random.uniform(
            k, shape, dtype, minval=self.low, maxval=self.high
        )


def _fans(shape):
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    # paddle convention: linear weights are [in, out]; conv [out, in, *k]
    receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
    fan_in = shape[1] * receptive if len(shape) > 2 else shape[0]
    fan_out = shape[0] * receptive if len(shape) > 2 else shape[1]
    return fan_in, fan_out


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        k = random_mod.next_key()
        return std * jax.random.normal(k, shape, dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        k = random_mod.next_key()
        return jax.random.uniform(k, shape, dtype, minval=-limit, maxval=limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = (
            math.sqrt(2.0 / (1 + self.negative_slope**2))
            if self.nonlinearity in ("relu", "leaky_relu")
            else 1.0
        )
        std = gain / math.sqrt(fi)
        k = random_mod.next_key()
        return std * jax.random.normal(k, shape, dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = (
            math.sqrt(2.0 / (1 + self.negative_slope**2))
            if self.nonlinearity in ("relu", "leaky_relu")
            else 1.0
        )
        limit = gain * math.sqrt(3.0 / fi)
        k = random_mod.next_key()
        return jax.random.uniform(k, shape, dtype, minval=-limit, maxval=limit)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        arr = np.asarray(
            self.value.numpy() if hasattr(self.value, "numpy") else self.value
        )
        assert tuple(arr.shape) == tuple(shape), (
            f"Assign initializer shape {arr.shape} != parameter shape {shape}"
        )
        return jnp.asarray(arr, dtype=dtype)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        k = random_mod.next_key()
        return self.gain * jax.nn.initializers.orthogonal()(k, shape, dtype)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype):
        arr = np.zeros(shape, dtype=np.dtype(convert_dtype(dtype)))
        oc, ic = shape[0], shape[1]
        mid = tuple(s // 2 for s in shape[2:])
        for i in range(min(oc, ic * self.groups)):
            arr[(i, i % ic) + mid] = 1.0
        return jnp.asarray(arr)


# paddle exposes these both as classes and lowercase aliases
constant = Constant
normal = Normal
uniform = Uniform


def calculate_gain(nonlinearity, param=None):
    gains = {
        "sigmoid": 1.0,
        "linear": 1.0,
        "conv1d": 1.0,
        "conv2d": 1.0,
        "conv3d": 1.0,
        "tanh": 5.0 / 3.0,
        "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + (param or 0.01) ** 2)),
        "selu": 3.0 / 4.0,
    }
    return gains[nonlinearity]


def set_global_initializer(weight_init, bias_init=None):
    from . import layer as _layer_pkg

    _layer_pkg.layers._GLOBAL_INIT[0] = weight_init
    _layer_pkg.layers._GLOBAL_INIT[1] = bias_init
