"""paddle.nn namespace parity (python/paddle/nn/__init__.py — unverified)."""
from . import functional  # noqa: F401
from . import utils  # noqa: F401
from . import initializer  # noqa: F401
from .layer import *  # noqa: F401,F403
from .layer import Layer, ParamAttr  # noqa: F401


class ClipGradByGlobalNorm:
    """Forward decl — real implementation in paddle_tpu.optimizer.clip;
    re-exported there. Kept import-light to avoid cycles."""

    def __new__(cls, *args, **kwargs):
        from ..optimizer.clip import ClipGradByGlobalNorm as Impl

        return Impl(*args, **kwargs)


class ClipGradByNorm:
    def __new__(cls, *args, **kwargs):
        from ..optimizer.clip import ClipGradByNorm as Impl

        return Impl(*args, **kwargs)


class ClipGradByValue:
    def __new__(cls, *args, **kwargs):
        from ..optimizer.clip import ClipGradByValue as Impl

        return Impl(*args, **kwargs)
