"""paddle.nn.utils parity (python/paddle/nn/utils/ — unverified):
weight/spectral norm reparameterizations + parameter vector helpers +
gradient clipping utilities.

Reparameterizations use forward-pre-hooks: the effective ``weight`` is
recomputed from the stored factors right before each forward, so the
recomputation traces into compiled steps and XLA fuses it.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ...core.tensor import Parameter, Tensor


def _norm_except_dim(v, dim):
    axes = tuple(i for i in range(v.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(jnp.square(v), axis=axes, keepdims=True))


def weight_norm(layer, name="weight", dim=0):
    """w = g * v / ||v||  (per-slice along ``dim``; dim=None -> global)."""
    w = getattr(layer, name)
    if w is None:
        raise ValueError(f"weight_norm: layer has no parameter {name!r}")
    wv = jnp.asarray(w.value)
    d = dim if dim is not None else -1
    if dim is None:
        norm = jnp.sqrt(jnp.sum(jnp.square(wv)))
        g0 = norm.reshape(1)
    else:
        norm = _norm_except_dim(wv, dim)
        g0 = norm
    delattr(layer, name)
    g_param = Parameter(jnp.asarray(g0))
    v_param = Parameter(wv)
    layer.add_parameter(f"{name}_g", g_param)
    layer.add_parameter(f"{name}_v", v_param)

    def hook(lyr, inputs):
        v = getattr(lyr, f"{name}_v")
        g = getattr(lyr, f"{name}_g")
        if dim is None:
            from ...ops.math import multiply
            from ...ops.linalg import norm as _pnorm

            w_eff = v * (g / _pnorm(v))
        else:
            from ...core import dispatch

            w_eff = dispatch.apply(
                "weight_norm", _weight_norm_fn, (v, g), {"dim": d}
            )
        object.__setattr__(lyr, name, w_eff)
        return inputs

    handle = layer.register_forward_pre_hook(hook)
    layer._weight_norm_handles = getattr(layer, "_weight_norm_handles", {})
    layer._weight_norm_handles[name] = (handle, dim)
    hook(layer, ())  # materialize immediately (reference parity)
    return layer


def _weight_norm_fn(v, g, *, dim):
    return v * (g / jnp.maximum(_norm_except_dim(v, dim), 1e-12))


def remove_weight_norm(layer, name="weight"):
    handles = getattr(layer, "_weight_norm_handles", {})
    if name not in handles:
        raise ValueError(f"weight_norm was not applied to {name!r}")
    handle, dim = handles.pop(name)
    handle.remove()
    v = getattr(layer, f"{name}_v")
    g = getattr(layer, f"{name}_g")
    vv, gv = jnp.asarray(v.value), jnp.asarray(g.value)
    if dim is None:
        w = vv * (gv / jnp.sqrt(jnp.sum(jnp.square(vv))))
    else:
        w = vv * (gv / jnp.maximum(_norm_except_dim(vv, dim), 1e-12))
    delattr(layer, f"{name}_g")
    delattr(layer, f"{name}_v")
    layer.add_parameter(name, Parameter(w))
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=0):
    """w / sigma_max(w) via power iteration (persistent u buffer)."""
    w = getattr(layer, name)
    wv = jnp.asarray(w.value)
    mat = jnp.moveaxis(wv, dim, 0).reshape(wv.shape[dim], -1)
    rng = np.random.RandomState(0)
    u0 = rng.randn(mat.shape[0]).astype(np.float32)
    u0 /= np.linalg.norm(u0) + eps
    delattr(layer, name)
    layer.add_parameter(f"{name}_orig", Parameter(wv))
    layer.register_buffer(f"{name}_u", Tensor(jnp.asarray(u0)))

    def hook(lyr, inputs):
        from ...core import dispatch

        w_orig = getattr(lyr, f"{name}_orig")
        u = getattr(lyr, f"{name}_u")
        w_eff, u_new = dispatch.apply(
            "spectral_norm", _spectral_norm_fn, (w_orig, u),
            {"dim": dim, "iters": int(n_power_iterations),
             "eps": float(eps)},
        )
        lyr._buffers[f"{name}_u"] = Tensor(
            jnp.asarray(u_new.value if isinstance(u_new, Tensor)
                        else u_new)
        )
        object.__setattr__(lyr, name, w_eff)
        return inputs

    handle = layer.register_forward_pre_hook(hook)
    layer._spectral_norm_handles = getattr(
        layer, "_spectral_norm_handles", {}
    )
    layer._spectral_norm_handles[name] = handle
    hook(layer, ())
    return layer


def _spectral_norm_fn(w, u, *, dim, iters, eps):
    import jax

    mat = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
    mat_ng = jax.lax.stop_gradient(mat)
    # derive v from the stored u so iters=0 still yields a valid sigma
    v = mat_ng.T @ u
    v = v / (jnp.linalg.norm(v) + eps)
    for _ in range(iters):
        v = mat_ng.T @ u
        v = v / (jnp.linalg.norm(v) + eps)
        u = mat_ng @ v
        u = u / (jnp.linalg.norm(u) + eps)
    sigma = u @ (mat @ v)
    return w / sigma, jax.lax.stop_gradient(u)


def parameters_to_vector(parameters, name=None):
    params = list(parameters)
    return Tensor(jnp.concatenate([
        jnp.ravel(jnp.asarray(p.value)) for p in params
    ]))


def vector_to_parameters(vec, parameters, name=None):
    v = jnp.asarray(vec.value if isinstance(vec, Tensor) else vec)
    off = 0
    for p in parameters:
        n = int(np.prod(p.shape))
        p.set_value(v[off:off + n].reshape(tuple(p.shape)).astype(
            p.value.dtype
        ))
        off += n


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    """In-place global-norm gradient clip; returns the total norm."""
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([
            jnp.max(jnp.abs(g.value)) for g in grads
        ]))
    else:
        total = jnp.power(
            sum(jnp.sum(jnp.power(jnp.abs(g.value), norm_type))
                for g in grads),
            1.0 / norm_type,
        )
    if error_if_nonfinite and not bool(jnp.isfinite(total)):
        raise RuntimeError(
            f"clip_grad_norm_: non-finite total norm {total}"
        )
    scale = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in parameters:
        if p.grad is not None:
            p.grad = Tensor(p.grad.value * scale)
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in parameters:
        if p.grad is not None:
            p.grad = Tensor(jnp.clip(
                p.grad.value, -clip_value, clip_value
            ))
