"""Recurrent layers: SimpleRNN / LSTM / GRU (+Cells, RNN wrapper).

Reference parity: python/paddle/nn/layer/rnn.py (unverified, mount empty).
TPU-first: the time loop is a single ``lax.scan`` — one compiled loop body
rather than a Python-unrolled op sequence, which is the idiomatic XLA
formulation (the reference relies on cuDNN RNN kernels here). Gate orders
match paddle: LSTM [i, f, g(c~), o]; GRU [r, z, c].
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core import dispatch
from ...core.tensor import Tensor
from .. import initializer as I
from .layers import Layer


def _lstm_cell(x, h, c, w_ih, w_hh, b_ih, b_hh):
    gates = x @ w_ih.T + h @ w_hh.T
    if b_ih is not None:
        gates = gates + b_ih + b_hh
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c2 = f * c + i * g
    h2 = o * jnp.tanh(c2)
    return h2, c2


def _gru_cell(x, h, w_ih, w_hh, b_ih, b_hh):
    xg = x @ w_ih.T + (b_ih if b_ih is not None else 0.0)
    hg = h @ w_hh.T + (b_hh if b_hh is not None else 0.0)
    xr, xz, xc = jnp.split(xg, 3, axis=-1)
    hr, hz, hc = jnp.split(hg, 3, axis=-1)
    r = jax.nn.sigmoid(xr + hr)
    z = jax.nn.sigmoid(xz + hz)
    c = jnp.tanh(xc + r * hc)
    return (1 - z) * c + z * h


def _simple_cell(x, h, w_ih, w_hh, b_ih, b_hh, act):
    pre = x @ w_ih.T + h @ w_hh.T
    if b_ih is not None:
        pre = pre + b_ih + b_hh
    return jnp.tanh(pre) if act == "tanh" else jax.nn.relu(pre)


def _scan_layer(mode, act, x, h0, c0, w_ih, w_hh, b_ih, b_hh, reverse):
    """x: [T, B, in] -> (outputs [T, B, H], h_T, c_T)."""

    def step(carry, xt):
        if mode == "LSTM":
            h, c = carry
            h2, c2 = _lstm_cell(xt, h, c, w_ih, w_hh, b_ih, b_hh)
            return (h2, c2), h2
        h = carry
        if mode == "GRU":
            h2 = _gru_cell(xt, h, w_ih, w_hh, b_ih, b_hh)
        else:
            h2 = _simple_cell(xt, h, w_ih, w_hh, b_ih, b_hh, act)
        return h2, h2

    init = (h0, c0) if mode == "LSTM" else h0
    carry, outs = jax.lax.scan(step, init, x, reverse=reverse)
    if reverse:
        pass  # scan(reverse=True) already emits outputs aligned to input order
    if mode == "LSTM":
        return outs, carry[0], carry[1]
    return outs, carry, None


class RNNBase(Layer):
    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.activation = activation
        bidirect = direction in ("bidirect", "bidirectional")
        self.num_directions = 2 if bidirect else 1
        self.direction = direction
        gate_mult = {"LSTM": 4, "GRU": 3, "RNN": 1}[mode]
        std = 1.0 / math.sqrt(hidden_size)
        self._param_names = []
        for layer in range(num_layers):
            for d in range(self.num_directions):
                in_size = input_size if layer == 0 else hidden_size * self.num_directions
                sfx = f"_l{layer}" + ("_reverse" if d else "")
                wih = self.create_parameter(
                    [gate_mult * hidden_size, in_size], attr=weight_ih_attr,
                    default_initializer=I.Uniform(-std, std))
                whh = self.create_parameter(
                    [gate_mult * hidden_size, hidden_size], attr=weight_hh_attr,
                    default_initializer=I.Uniform(-std, std))
                bih = self.create_parameter(
                    [gate_mult * hidden_size], attr=bias_ih_attr, is_bias=True,
                    default_initializer=I.Uniform(-std, std))
                bhh = self.create_parameter(
                    [gate_mult * hidden_size], attr=bias_hh_attr, is_bias=True,
                    default_initializer=I.Uniform(-std, std))
                self.add_parameter(f"weight_ih{sfx}", wih)
                self.add_parameter(f"weight_hh{sfx}", whh)
                self.add_parameter(f"bias_ih{sfx}", bih)
                self.add_parameter(f"bias_hh{sfx}", bhh)
                self._param_names.append(sfx)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        mode = self.mode
        nl, nd, H = self.num_layers, self.num_directions, self.hidden_size
        # inter-layer dropout (paddle parity: applied to each stacked layer's
        # input except the first, training only)
        drop_p = float(self.dropout) if (self.training and self.dropout) else 0.0
        drop_keys = None
        if drop_p > 0.0 and nl > 1:
            from ...core import random as random_mod

            drop_keys = [random_mod.next_key() for _ in range(nl - 1)]

        if initial_states is None:
            h0 = c0 = None
        elif mode == "LSTM":
            h0, c0 = initial_states
        else:
            h0, c0 = initial_states, None

        params = []
        for layer in range(nl):
            for d in range(nd):
                sfx = f"_l{layer}" + ("_reverse" if d else "")
                params.append(tuple(
                    getattr(self, f"{n}{sfx}")
                    for n in ("weight_ih", "weight_hh", "bias_ih", "bias_hh")
                ))

        act = self.activation
        tm = self.time_major

        def _run(xv, h0v, c0v, *flat_w):
            ws = [flat_w[i * 4 : (i + 1) * 4] for i in range(nl * nd)]
            x = xv if tm else jnp.swapaxes(xv, 0, 1)  # -> [T, B, in]
            B = x.shape[1]
            if h0v is None:
                h0v = jnp.zeros((nl * nd, B, H), x.dtype)
            if c0v is None and mode == "LSTM":
                c0v = jnp.zeros((nl * nd, B, H), x.dtype)
            h_finals, c_finals = [], []
            cur = x
            for layer in range(nl):
                if layer > 0 and drop_keys is not None:
                    keep = 1.0 - drop_p
                    mask = jax.random.bernoulli(
                        drop_keys[layer - 1], keep, cur.shape
                    )
                    cur = jnp.where(mask, cur / keep, 0.0).astype(cur.dtype)
                outs_dir = []
                for d in range(nd):
                    idx = layer * nd + d
                    wih, whh, bih, bhh = ws[idx]
                    outs, hT, cT = _scan_layer(
                        mode, act, cur, h0v[idx],
                        c0v[idx] if mode == "LSTM" else None,
                        wih, whh, bih, bhh, reverse=bool(d),
                    )
                    outs_dir.append(outs)
                    h_finals.append(hT)
                    if mode == "LSTM":
                        c_finals.append(cT)
                cur = outs_dir[0] if nd == 1 else jnp.concatenate(outs_dir, axis=-1)
            y = cur if tm else jnp.swapaxes(cur, 0, 1)
            hN = jnp.stack(h_finals)
            if mode == "LSTM":
                return y, hN, jnp.stack(c_finals)
            return y, hN

        args = [inputs, h0, c0] + [w for p in params for w in p]
        out = dispatch.apply(f"rnn_{mode.lower()}", _run, tuple(args), cache=False)
        if mode == "LSTM":
            y, hN, cN = out
            return y, (hN, cN)
        y, hN = out
        return y, hN


class SimpleRNN(RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kw):
        super().__init__("RNN", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, activation, **kw)


class LSTM(RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kw):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kw)


class GRU(RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kw):
        super().__init__("GRU", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kw)


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        import paddle_tpu as paddle

        B = batch_ref.shape[batch_dim_idx]
        return paddle.full([B, self.hidden_size], init_value,
                           dtype or "float32")


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        self.weight_ih = self.create_parameter(
            [hidden_size, input_size], attr=weight_ih_attr,
            default_initializer=I.Uniform(-std, std))
        self.weight_hh = self.create_parameter(
            [hidden_size, hidden_size], attr=weight_hh_attr,
            default_initializer=I.Uniform(-std, std))
        self.bias_ih = self.create_parameter(
            [hidden_size], attr=bias_ih_attr, is_bias=True,
            default_initializer=I.Uniform(-std, std))
        self.bias_hh = self.create_parameter(
            [hidden_size], attr=bias_hh_attr, is_bias=True,
            default_initializer=I.Uniform(-std, std))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def _cell(x, h, wih, whh, bih, bhh):
            return _simple_cell(x, h, wih, whh, bih, bhh, self.activation)

        out = dispatch.apply(
            "simple_rnn_cell", _cell,
            (inputs, states, self.weight_ih, self.weight_hh, self.bias_ih,
             self.bias_hh), cache=False)
        return out, out


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        self.weight_ih = self.create_parameter(
            [4 * hidden_size, input_size], attr=weight_ih_attr,
            default_initializer=I.Uniform(-std, std))
        self.weight_hh = self.create_parameter(
            [4 * hidden_size, hidden_size], attr=weight_hh_attr,
            default_initializer=I.Uniform(-std, std))
        self.bias_ih = self.create_parameter(
            [4 * hidden_size], attr=bias_ih_attr, is_bias=True,
            default_initializer=I.Uniform(-std, std))
        self.bias_hh = self.create_parameter(
            [4 * hidden_size], attr=bias_hh_attr, is_bias=True,
            default_initializer=I.Uniform(-std, std))

    def forward(self, inputs, states=None):
        if states is None:
            h = self.get_initial_states(inputs)
            c = self.get_initial_states(inputs)
        else:
            h, c = states

        out = dispatch.apply(
            "lstm_cell", _lstm_cell,
            (inputs, h, c, self.weight_ih, self.weight_hh, self.bias_ih,
             self.bias_hh), cache=False)
        h2, c2 = out
        return h2, (h2, c2)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        self.weight_ih = self.create_parameter(
            [3 * hidden_size, input_size], attr=weight_ih_attr,
            default_initializer=I.Uniform(-std, std))
        self.weight_hh = self.create_parameter(
            [3 * hidden_size, hidden_size], attr=weight_hh_attr,
            default_initializer=I.Uniform(-std, std))
        self.bias_ih = self.create_parameter(
            [3 * hidden_size], attr=bias_ih_attr, is_bias=True,
            default_initializer=I.Uniform(-std, std))
        self.bias_hh = self.create_parameter(
            [3 * hidden_size], attr=bias_hh_attr, is_bias=True,
            default_initializer=I.Uniform(-std, std))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        out = dispatch.apply(
            "gru_cell", _gru_cell,
            (inputs, states, self.weight_ih, self.weight_hh, self.bias_ih,
             self.bias_hh), cache=False)
        return out, out


class RNN(Layer):
    """Wrap a cell into a recurrent layer (paddle.nn.RNN parity)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...ops import manipulation as M

        axis = 0 if self.time_major else 1
        T = inputs.shape[axis]
        steps = range(T - 1, -1, -1) if self.is_reverse else range(T)
        states = initial_states
        outs = []
        for t in steps:
            xt = inputs[:, t] if axis == 1 else inputs[t]
            out, states = self.cell(xt, states)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        y = M.stack(outs, axis=axis)
        return y, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.fw = RNN(cell_fw, False, time_major)
        self.bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...ops import manipulation as M

        sf = initial_states[0] if initial_states else None
        sb = initial_states[1] if initial_states else None
        yf, stf = self.fw(inputs, sf)
        yb, stb = self.bw(inputs, sb)
        return M.concat([yf, yb], axis=-1), (stf, stb)
