"""Common layers: Linear, Dropout, Embedding, Flatten, Pad, Upsample, ...

Reference parity: python/paddle/nn/layer/common.py (unverified, mount empty).
"""
from __future__ import annotations

import math

from ...core.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from .layers import Layer, ParamAttr


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Linear(Layer):
    """y = x @ W + b with paddle's [in_features, out_features] weight."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        bound = 1.0 / math.sqrt(in_features)
        self.weight = self.create_parameter(
            [in_features, out_features],
            attr=weight_attr,
            default_initializer=I.XavierUniform(fan_in=in_features, fan_out=out_features),
        )
        self.bias = self.create_parameter(
            [out_features],
            attr=bias_attr,
            is_bias=True,
            default_initializer=I.Uniform(-bound, bound) if bias_attr is None else None,
        )

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.in_features}, out_features={self.out_features}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, self.p, axis=self.axis, training=self.training,
                         mode=self.mode)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, training=self.training)


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = padding_idx
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim],
            attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0)
            if weight_attr is None or ParamAttr._to_attr(weight_attr).initializer is None
            else None,
        )
        if padding_idx is not None:
            with_pad = self.weight.numpy().copy()
            with_pad[padding_idx] = 0
            self.weight.set_value(with_pad)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        from ...ops.manipulation import flatten

        return flatten(x, self.start_axis, self.stop_axis)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.align_mode = align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode,
                             self.data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class _PadNd(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW"):
        super().__init__()
        self._pad = padding
        self._mode = mode
        self._value = value
        self._data_format = data_format

    def forward(self, x):
        return F.pad(x, self._pad, self._mode, self._value, self._data_format)


class Pad1D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL",
                 name=None):
        super().__init__(padding, mode, value, data_format)


class Pad2D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW",
                 name=None):
        super().__init__(padding, mode, value, data_format)


class Pad3D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCDHW",
                 name=None):
        super().__init__(padding, mode, value, data_format)


class ZeroPad2D(Pad2D):
    pass


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [out_features, in1_features, in2_features], attr=weight_attr
        )
        self.bias = self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True
        )

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.factor, self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.factor = downscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.factor, self.data_format)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.output_sizes = output_sizes
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.fold(x, self.output_sizes, *self.args)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self._groups = groups
        self._data_format = data_format

    def forward(self, x):
        return F.channel_shuffle(x, self._groups, self._data_format)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self._kw = dict(p=p, epsilon=epsilon, keepdim=keepdim)

    def forward(self, x, y):
        return F.pairwise_distance(x, y, **self._kw)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self._axis = int(axis)
        self._shape = list(shape)

    def forward(self, x):
        from ...ops.manipulation import reshape

        nd = len(x.shape)
        ax = self._axis % nd
        new_shape = (
            list(x.shape[:ax]) + list(self._shape)
            + list(x.shape[ax + 1:])
        )
        return reshape(x, new_shape)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
        super().__init__()
        self._kw = dict(kernel_size=kernel_size, stride=stride,
                        padding=padding, data_format=data_format,
                        output_size=output_size)

    def forward(self, x, indices):
        return F.max_unpool2d(x, indices, **self._kw)


class Softmax2D(Layer):
    """Softmax over the channel axis of NCHW inputs."""

    def forward(self, x):
        if len(x.shape) not in (3, 4):
            raise ValueError(
                f"Softmax2D expects 3-D or 4-D input, got {len(x.shape)}-D"
            )
        return F.softmax(x, axis=-3)
