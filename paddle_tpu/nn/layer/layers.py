"""The Layer base class (module system).

Reference parity: python/paddle/nn/layer/layers.py (unverified, mount
empty): parameters, buffers, sublayers, hooks, state_dict, train/eval,
apply/to, create_parameter with ParamAttr. TPU-specific addition:
``functional_state()``/``load_functional_state()`` snapshot the full
parameter+buffer pytree so whole layers can cross jax.jit boundaries — the
bridge between the imperative Layer API and functional transforms.
"""
from __future__ import annotations

import collections

import numpy as np

import jax.numpy as jnp

from ...core.dtypes import convert_dtype, get_default_dtype
from ...core.tensor import Parameter, Tensor
from .. import initializer as init_mod

_GLOBAL_INIT = [None, None]  # [weight_init, bias_init] via set_global_initializer


class ParamAttr:
    """Parameter attribute bundle (python/paddle/framework ParamAttr parity)."""

    def __init__(
        self,
        name=None,
        initializer=None,
        learning_rate=1.0,
        regularizer=None,
        trainable=True,
        do_model_average=True,
        need_clip=True,
    ):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, init_mod.Initializer):
            return ParamAttr(initializer=attr)
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if attr is False:
            return False
        raise TypeError(f"cannot convert {attr!r} to ParamAttr")


class Layer:
    _name_counters: dict = collections.defaultdict(int)

    def __init__(self, name_scope=None, dtype="float32"):
        cls = type(self).__name__.lower()
        idx = Layer._name_counters[cls]
        Layer._name_counters[cls] += 1
        object.__setattr__(self, "_full_name", name_scope or f"{cls}_{idx}")
        object.__setattr__(self, "_dtype", convert_dtype(dtype) or get_default_dtype())
        object.__setattr__(self, "_parameters", collections.OrderedDict())
        object.__setattr__(self, "_buffers", collections.OrderedDict())
        object.__setattr__(self, "_non_persistable_buffer_names", set())
        object.__setattr__(self, "_sub_layers", collections.OrderedDict())
        object.__setattr__(self, "training", True)
        object.__setattr__(self, "_forward_pre_hooks", collections.OrderedDict())
        object.__setattr__(self, "_forward_post_hooks", collections.OrderedDict())
        object.__setattr__(self, "_casted_by_pure_fp16", False)

    # ------------------------------------------------------------ attribute
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning params")
            params[name] = value
            buffers.pop(name, None) if buffers else None
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            layers[name] = value
            self.__dict__.pop(name, None)
        elif params is not None and name in params:
            if value is None:
                del params[name]
                object.__setattr__(self, name, None)
            elif isinstance(value, Tensor):
                params[name].set_value(value)
            else:
                raise TypeError(f"cannot assign {type(value)} to parameter {name}")
        elif buffers is not None and name in buffers:
            if isinstance(value, Tensor):
                buffers[name] = value
            elif value is None:
                del buffers[name]
                object.__setattr__(self, name, None)
            else:
                object.__setattr__(self, name, value)
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'"
        )

    def __delattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        extra = []
        for store in ("_parameters", "_buffers", "_sub_layers"):
            extra += list(self.__dict__.get(store, ()))
        return list(super().__dir__()) + extra

    # ------------------------------------------------------------- creation
    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias=False,
        default_initializer=None,
    ):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = convert_dtype(dtype) or self._dtype
        initializer = attr.initializer or default_initializer
        if initializer is None:
            initializer = _GLOBAL_INIT[1 if is_bias else 0]
        if initializer is None:
            initializer = (
                init_mod.Constant(0.0) if is_bias else init_mod.XavierUniform()
            )
        from ...core import lazy as lazy_mod

        if lazy_mod.in_lazy_mode():
            # LazyGuard: abstract parameter — no allocation, no init
            # compute; materializable later, lowerable immediately
            value = lazy_mod.abstract_like(
                tuple(int(s) for s in shape), dtype
            )
            p = Parameter(value, trainable=attr.trainable, name=attr.name)
            p._lazy_initializer = initializer  # for materialize()
            # creation order, so materialize() replays the RNG stream in
            # the exact sequence eager init would have consumed it
            p._lazy_seq = lazy_mod.next_seq()
            p.optimize_attr = {"learning_rate": attr.learning_rate}
            p.regularizer = attr.regularizer
            p.need_clip = getattr(attr, "need_clip", True)
            self._maybe_lazy = True  # checked (then cleared) on __call__
            return p
        value = initializer(tuple(int(s) for s in shape), dtype)
        p = Parameter(value, trainable=attr.trainable, name=attr.name)
        p.optimize_attr = {"learning_rate": attr.learning_rate}
        p.regularizer = attr.regularizer
        p.need_clip = getattr(attr, "need_clip", True)
        return p

    def materialize(self):
        """Materialize every LazyGuard-created (abstract) parameter.

        Each parameter's recorded initializer is compiled with the
        parameter's sharding as ``out_shardings`` — on a device mesh the
        weight is initialized SHARD-LOCAL on its owning chips and a full
        host-resident copy never exists, which is the only way a
        LazyGuard-built 7B-class model can come up on real hardware.
        Initializers run in parameter CREATION order (not traversal
        order), so under the same seed materialize() reproduces eager
        init exactly. No-op for parameters that are already concrete.
        """
        import jax

        from ...core import lazy as lazy_mod

        todo = [
            p for _, p in self.named_parameters()
            if lazy_mod.is_abstract(p.value)
        ]
        todo.sort(key=lambda p: getattr(p, "_lazy_seq", 0))
        for p in todo:
            init = getattr(p, "_lazy_initializer", None)
            if init is None:
                init = init_mod.XavierUniform()
            shape = tuple(p.value.shape)
            dt = p.value.dtype
            sharding = getattr(p.value, "sharding", None)
            if sharding is not None:
                p.value = jax.jit(
                    lambda i=init, s=shape, d=dt: i(s, d),
                    out_shardings=sharding,
                )()
            else:
                p.value = init(shape, dt)
        for l in self.sublayers(include_self=True):
            l.__dict__.pop("_maybe_lazy", None)
        return self

    def create_tensor(self, name=None, dtype=None, default_initializer=None):
        dtype = convert_dtype(dtype) or self._dtype
        return Tensor(jnp.zeros([], dtype), name=name)

    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # ------------------------------------------------------------ traversal
    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, sub, p in self._walk("_parameters", prefix, include_sublayers):
            if p is not None and id(p) not in seen:
                seen.add(id(p))
                yield name, p

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for name, sub, b in self._walk("_buffers", prefix, include_sublayers):
            if b is not None and id(b) not in seen:
                seen.add(id(b))
                yield name, b

    def _walk(self, store, prefix, include_sublayers):
        for name, obj in getattr(self, store).items():
            yield (prefix + name if not prefix else f"{prefix}.{name}"), self, obj
        if include_sublayers:
            for lname, sub in self._sub_layers.items():
                if sub is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                yield from sub._walk(store, sub_prefix, True)

    def children(self):
        yield from (l for _, l in self.named_children())

    def named_children(self):
        for name, sub in self._sub_layers.items():
            if sub is not None:
                yield name, sub

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            p = f"{prefix}.{name}" if prefix else name
            yield from sub.named_sublayers(prefix=p, include_self=True)

    def apply(self, fn):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    # ------------------------------------------------------------ state
    def state_dict(
        self,
        destination=None,
        include_sublayers=True,
        structured_name_prefix="",
        use_hook=True,
    ):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(
            prefix=structured_name_prefix.rstrip("."),
            include_sublayers=include_sublayers,
        ):
            dest[name] = p
        for name, b in self.named_buffers(
            prefix=structured_name_prefix.rstrip("."),
            include_sublayers=include_sublayers,
        ):
            short = name.rsplit(".", 1)[-1]
            # skip non-persistable buffers (paddle parity)
            owner = self._locate_owner(name)
            if owner is not None and short in owner._non_persistable_buffer_names:
                continue
            dest[name] = b
        return dest

    def _locate_owner(self, dotted):
        parts = dotted.split(".")
        layer = self
        for p in parts[:-1]:
            layer = layer._sub_layers.get(p)
            if layer is None:
                return None
        return layer

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing = [k for k in own if k not in state_dict]
        unexpected = [k for k in state_dict if k not in own]
        for k, t in own.items():
            if k not in state_dict:
                continue
            v = state_dict[k]
            arr = v.numpy() if isinstance(v, Tensor) else np.asarray(v)
            if tuple(arr.shape) != tuple(t.shape):
                raise ValueError(
                    f"shape mismatch for {k}: got {arr.shape}, expected {tuple(t.shape)}"
                )
            t.set_value(arr)
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    # ------------------------------------------------------ functional bridge
    def functional_state(self):
        """(params, buffers) pytrees of raw jax arrays, keyed by state name."""
        params = {k: p.value for k, p in self.named_parameters()}
        buffers = {k: b.value for k, b in self.named_buffers()}
        return params, buffers

    def load_functional_state(self, params=None, buffers=None):
        if params:
            lookup = dict(self.named_parameters())
            for k, v in params.items():
                lookup[k].value = v
        if buffers:
            lookup = dict(self.named_buffers())
            for k, v in buffers.items():
                lookup[k].value = v

    # ------------------------------------------------------------- modes
    def train(self):
        for l in self.sublayers(include_self=True):
            l.training = True
        return self

    def eval(self):
        for l in self.sublayers(include_self=True):
            l.training = False
        return self

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            d = convert_dtype(dtype)
            for p in self.parameters():
                p.value = p.value.astype(d)
            for b in self.buffers():
                if jnp.issubdtype(b.value.dtype, jnp.floating):
                    b.value = b.value.astype(d)
        if device is not None:
            import jax as _jax

            from ...core import device as device_mod
            from ...core.tensor import _parse_place

            dev = device_mod.jax_device(
                _parse_place(device) if isinstance(device, str) else device
            )
            for t in list(self.parameters()) + list(self.buffers()):
                t.value = _jax.device_put(t.value, dev)
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def half(self):
        return self.to(dtype="float16")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # ------------------------------------------------------------- hooks
    def register_forward_pre_hook(self, hook):
        handle = _HookHandle(self._forward_pre_hooks)
        self._forward_pre_hooks[handle.id] = hook
        return handle

    def register_forward_post_hook(self, hook):
        handle = _HookHandle(self._forward_post_hooks)
        self._forward_post_hooks[handle.id] = hook
        return handle

    # ------------------------------------------------------------- call
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        if self.__dict__.get("_maybe_lazy"):
            self._check_lazy_executable()
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    def _check_lazy_executable(self):
        """One-time (flag-gated) guard: a LazyGuard-built layer must be
        materialized — or have concrete values loaded — before it can
        execute; without this the failure is a raw jax TypeError deep in
        dispatch. Clears the flag once all parameters are concrete (e.g.
        after set_state_dict), so the walk never repeats."""
        from ...core import lazy as lazy_mod

        for k, p in self.named_parameters():
            if lazy_mod.is_abstract(p.value):
                raise RuntimeError(
                    f"parameter {k!r} is still abstract (built under "
                    "paddle.LazyGuard): call .materialize() or load a "
                    "checkpoint before running the layer. Abstract "
                    "networks can only be lowered (jit(...).lower), "
                    "not executed."
                )
        for l in self.sublayers(include_self=True):
            l.__dict__.pop("_maybe_lazy", None)

    def full_name(self):
        return self._full_name

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = [f"{type(self).__name__}({extra}"]
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).replace("\n", "\n  ")
            lines.append(f"  ({name}): {sub_repr}")
        return "\n".join(lines) + ")" if len(lines) > 1 else lines[0] + ")"

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()


class _HookHandle:
    _next_id = [0]

    def __init__(self, store):
        self.id = _HookHandle._next_id[0]
        _HookHandle._next_id[0] += 1
        self._store = store

    def remove(self):
        self._store.pop(self.id, None)
