"""Activation layers (python/paddle/nn/layer/activation.py parity —
unverified)."""
from __future__ import annotations

from .. import functional as F
from .. import initializer as I
from .layers import Layer


def _act_layer(name, fn, **default_kw):
    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            kw = dict(default_kw)
            # positional args map onto the functional's kwargs in order
            keys = list(default_kw)
            for k, v in zip(keys, args):
                kw[k] = v
            for k, v in kwargs.items():
                if k in kw:
                    kw[k] = v
            self._kw = kw

        def forward(self, x):
            return fn(x, **self._kw)

    _Act.__name__ = name
    _Act.__qualname__ = name
    return _Act


ReLU = _act_layer("ReLU", F.relu)
ReLU6 = _act_layer("ReLU6", F.relu6)
Sigmoid = _act_layer("Sigmoid", F.sigmoid)
Tanh = _act_layer("Tanh", F.tanh)
Silu = _act_layer("Silu", F.silu)
Swish = _act_layer("Swish", F.swish)
Mish = _act_layer("Mish", F.mish)
GELU = _act_layer("GELU", F.gelu, approximate=False)
LeakyReLU = _act_layer("LeakyReLU", F.leaky_relu, negative_slope=0.01)
ELU = _act_layer("ELU", F.elu, alpha=1.0)
CELU = _act_layer("CELU", F.celu, alpha=1.0)
SELU = _act_layer("SELU", F.selu)
Hardswish = _act_layer("Hardswish", F.hardswish)
Hardsigmoid = _act_layer("Hardsigmoid", F.hardsigmoid)
Hardtanh = _act_layer("Hardtanh", F.hardtanh, min=-1.0, max=1.0)
Hardshrink = _act_layer("Hardshrink", F.hardshrink, threshold=0.5)
Softshrink = _act_layer("Softshrink", F.softshrink, threshold=0.5)
Softplus = _act_layer("Softplus", F.softplus, beta=1.0, threshold=20.0)
Softsign = _act_layer("Softsign", F.softsign)
Tanhshrink = _act_layer("Tanhshrink", F.tanhshrink)
ThresholdedReLU = _act_layer("ThresholdedReLU", F.thresholded_relu, threshold=1.0)
LogSigmoid = _act_layer("LogSigmoid", lambda x, **kw: F.softplus(-x).__neg__())
Softmax = _act_layer("Softmax", F.softmax, axis=-1)
LogSoftmax = _act_layer("LogSoftmax", F.log_softmax, axis=-1)
Maxout = _act_layer("Maxout", F.maxout, groups=2, axis=1)
GLU = _act_layer("GLU", F.glu, axis=-1)
RReLU = _act_layer("RReLU", F.rrelu, lower=0.125, upper=1.0 / 3.0)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [num_parameters],
            attr=weight_attr,
            default_initializer=I.Constant(init),
        )
        self._data_format = data_format

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)
