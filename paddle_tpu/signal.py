"""paddle.signal: stft / istft (python/paddle/signal.py parity —
unverified).

Framing + FFT compose jnp primitives through core.dispatch; the FFT
itself is XLA's native implementation. istft uses the standard
overlap-add with window-envelope normalization (NOLA), matching the
reference/torch semantics.
"""
from __future__ import annotations

import jax.numpy as jnp

from .core import dispatch
from .core.tensor import Tensor


def _frame(x, n_fft, hop, center, pad_mode):
    if center:
        pad = [(0, 0)] * (x.ndim - 1) + [(n_fft // 2, n_fft // 2)]
        x = jnp.pad(x, pad, mode=pad_mode)
    n = x.shape[-1]
    n_frames = 1 + (n - n_fft) // hop
    starts = jnp.arange(n_frames) * hop
    idx = starts[:, None] + jnp.arange(n_fft)[None, :]
    return x[..., idx]  # [..., n_frames, n_fft]


def _stft(x, window, *, n_fft, hop, center, pad_mode, normalized, onesided):
    frames = _frame(x, n_fft, hop, center, pad_mode)
    if window is not None:
        frames = frames * window
    if onesided:
        spec = jnp.fft.rfft(frames, n=n_fft, axis=-1)
    else:
        spec = jnp.fft.fft(frames, n=n_fft, axis=-1)
    if normalized:
        spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
    # [..., freq, n_frames] like the reference
    return jnp.swapaxes(spec, -1, -2)


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    hop = int(hop_length) if hop_length is not None else n_fft // 4
    win_length = int(win_length) if win_length is not None else int(n_fft)
    args = [x]
    if window is not None:
        if not isinstance(window, Tensor):
            window = Tensor(jnp.asarray(window))
        if int(window.shape[-1]) != win_length:
            raise ValueError(
                f"stft: window length {window.shape[-1]} != "
                f"win_length {win_length}"
            )
        if win_length < n_fft:  # center-pad the window to n_fft
            lpad = (n_fft - win_length) // 2
            window = Tensor(jnp.pad(
                window.value, (lpad, n_fft - win_length - lpad)
            ))
        args.append(window)
    else:
        args.append(None)
    return dispatch.apply(
        "stft", _stft, tuple(args),
        {"n_fft": int(n_fft), "hop": hop, "center": bool(center),
         "pad_mode": pad_mode, "normalized": bool(normalized),
         "onesided": bool(onesided)},
    )


def _istft(spec, window, *, n_fft, hop, center, normalized, onesided,
           length, return_complex):
    frames = jnp.swapaxes(spec, -1, -2)  # [..., n_frames, freq]
    if normalized:
        frames = frames * jnp.sqrt(jnp.asarray(n_fft, frames.real.dtype))
    if onesided:
        sig = jnp.fft.irfft(frames, n=n_fft, axis=-1)
    else:
        sig = jnp.fft.ifft(frames, n=n_fft, axis=-1)
        if not return_complex:
            sig = sig.real
    if window is None:
        window = jnp.ones((n_fft,), sig.real.dtype)
    sig = sig * window
    n_frames = sig.shape[-2]
    out_len = n_fft + hop * (n_frames - 1)
    shape = sig.shape[:-2] + (out_len,)
    out = jnp.zeros(shape, sig.dtype)
    env = jnp.zeros((out_len,), jnp.asarray(window).real.dtype)
    idx = (
        jnp.arange(n_frames)[:, None] * hop
        + jnp.arange(n_fft)[None, :]
    )
    out = out.at[..., idx].add(sig)
    env = env.at[idx].add(jnp.square(window))
    out = out / jnp.where(env > 1e-11, env, 1.0)
    if center:
        out = out[..., n_fft // 2:]
        if length is not None:
            if out.shape[-1] < length:  # torch zero-pads to `length`
                pad = [(0, 0)] * (out.ndim - 1) + [
                    (0, length - out.shape[-1])
                ]
                out = jnp.pad(out, pad)
            out = out[..., :length]
        else:
            # trim exactly n_fft//2 from each end (front trim already
            # removed n_fft//2; for odd n_fft this keeps one extra sample
            # vs out_len - n_fft, matching torch/paddle).
            out = out[..., : out_len - 2 * (n_fft // 2)]
    elif length is not None:
        if out.shape[-1] < length:
            pad = [(0, 0)] * (out.ndim - 1) + [(0, length - out.shape[-1])]
            out = jnp.pad(out, pad)
        out = out[..., :length]
    return out


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    hop = int(hop_length) if hop_length is not None else n_fft // 4
    win_length = int(win_length) if win_length is not None else int(n_fft)
    args = [x]
    if window is not None:
        if not isinstance(window, Tensor):
            window = Tensor(jnp.asarray(window))
        if int(window.shape[-1]) != win_length:
            raise ValueError(
                f"istft: window length {window.shape[-1]} != "
                f"win_length {win_length}"
            )
        if win_length < n_fft:
            lpad = (n_fft - win_length) // 2
            window = Tensor(jnp.pad(
                window.value, (lpad, n_fft - win_length - lpad)
            ))
        args.append(window)
    else:
        args.append(None)
    return dispatch.apply(
        "istft", _istft, tuple(args),
        {"n_fft": int(n_fft), "hop": hop, "center": bool(center),
         "normalized": bool(normalized), "onesided": bool(onesided),
         "length": None if length is None else int(length),
         "return_complex": bool(return_complex)},
    )
