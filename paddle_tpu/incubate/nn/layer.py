"""Fused transformer layers (paddle.incubate.nn parity).

Reference parity: python/paddle/incubate/nn/layer/fused_transformer.py
(FusedMultiHeadAttention, FusedFeedForward — unverified, mount empty).
On TPU "fused" means: the whole block is expressed as a handful of large
ops (qkv as one gemm, flash attention, gemm+epilogue) that XLA/Pallas fuse
— matching the intent of the reference's cublasLt/fmha fusions.
"""
from __future__ import annotations

import math

from ...nn import functional as F
from ...nn.layer.layers import Layer
from ...nn import initializer as I


class FusedMultiHeadAttention(Layer):
    """Pre/post-LN multi-head self-attention with a single QKV gemm and
    flash attention (paddle.incubate.nn.FusedMultiHeadAttention parity)."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        if need_weights:
            raise NotImplementedError("need_weights=True is not supported")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self._dropout_rate = dropout_rate
        self._attn_dropout_rate = attn_dropout_rate
        self.normalize_before = normalize_before
        self._epsilon = epsilon
        # single fused QKV weight, reference layout [3, H, dim, dim/H] kept
        # flat here: [dim, 3*dim]
        self.qkv_weight = self.create_parameter(
            [embed_dim, 3 * embed_dim], attr=qkv_weight_attr
        )
        self.qkv_bias = self.create_parameter(
            [3 * embed_dim], attr=qkv_bias_attr, is_bias=True,
            default_initializer=I.Constant(0.0),
        )
        self.linear_weight = self.create_parameter(
            [embed_dim, embed_dim], attr=linear_weight_attr
        )
        self.linear_bias = self.create_parameter(
            [embed_dim], attr=linear_bias_attr, is_bias=True,
            default_initializer=I.Constant(0.0),
        )
        self.pre_ln_scale = self.create_parameter(
            [embed_dim], attr=pre_ln_scale_attr,
            default_initializer=I.Constant(1.0),
        )
        self.pre_ln_bias = self.create_parameter(
            [embed_dim], attr=pre_ln_bias_attr, is_bias=True,
            default_initializer=I.Constant(0.0),
        )
        self.ln_scale = self.create_parameter(
            [embed_dim], attr=ln_scale_attr,
            default_initializer=I.Constant(1.0),
        )
        self.ln_bias = self.create_parameter(
            [embed_dim], attr=ln_bias_attr, is_bias=True,
            default_initializer=I.Constant(0.0),
        )

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        from . import functional as IF

        return IF.fused_multi_head_attention(
            query, self.qkv_weight, self.linear_weight,
            pre_layer_norm=self.normalize_before,
            pre_ln_scale=self.pre_ln_scale, pre_ln_bias=self.pre_ln_bias,
            ln_scale=self.ln_scale, ln_bias=self.ln_bias,
            pre_ln_epsilon=self._epsilon, qkv_bias=self.qkv_bias,
            linear_bias=self.linear_bias, cache_kv=cache,
            attn_mask=attn_mask, dropout_rate=self._dropout_rate,
            attn_dropout_rate=self._attn_dropout_rate,
            ln_epsilon=self._epsilon, training=self.training,
            num_heads=self.num_heads,
        )


class FusedFeedForward(Layer):
    """Pre/post-LN MLP block (paddle.incubate.nn.FusedFeedForward parity)."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self._d_model = d_model
        self._dropout_rate = dropout_rate
        self._act_dropout_rate = (
            dropout_rate if act_dropout_rate is None else act_dropout_rate
        )
        self._act = activation
        self._epsilon = epsilon
        self.normalize_before = normalize_before
        self.linear1_weight = self.create_parameter(
            [d_model, dim_feedforward], attr=linear1_weight_attr
        )
        self.linear1_bias = self.create_parameter(
            [dim_feedforward], attr=linear1_bias_attr, is_bias=True,
            default_initializer=I.Constant(0.0),
        )
        self.linear2_weight = self.create_parameter(
            [dim_feedforward, d_model], attr=linear2_weight_attr
        )
        self.linear2_bias = self.create_parameter(
            [d_model], attr=linear2_bias_attr, is_bias=True,
            default_initializer=I.Constant(0.0),
        )
        self.ln1_scale = self.create_parameter(
            [d_model], attr=ln1_scale_attr,
            default_initializer=I.Constant(1.0),
        )
        self.ln1_bias = self.create_parameter(
            [d_model], attr=ln1_bias_attr, is_bias=True,
            default_initializer=I.Constant(0.0),
        )
        self.ln2_scale = self.create_parameter(
            [d_model], attr=ln2_scale_attr,
            default_initializer=I.Constant(1.0),
        )
        self.ln2_bias = self.create_parameter(
            [d_model], attr=ln2_bias_attr, is_bias=True,
            default_initializer=I.Constant(0.0),
        )

    def forward(self, src, cache=None):
        from . import functional as IF

        return IF.fused_feedforward(
            src, self.linear1_weight, self.linear2_weight,
            linear1_bias=self.linear1_bias,
            linear2_bias=self.linear2_bias,
            ln1_scale=self.ln1_scale, ln1_bias=self.ln1_bias,
            ln2_scale=self.ln2_scale, ln2_bias=self.ln2_bias,
            dropout1_rate=self._act_dropout_rate,
            dropout2_rate=self._dropout_rate,
            activation=self._act, ln1_epsilon=self._epsilon,
            ln2_epsilon=self._epsilon,
            pre_layer_norm=self.normalize_before, training=self.training,
        )
