"""Fused-op functional API (paddle.incubate.nn.functional parity).

Reference parity: python/paddle/incubate/nn/functional/* backed by the phi
fusion kernels (paddle/phi/kernels/fusion/gpu/ — unverified, mount empty):
fused_rms_norm, fused_layer_norm, fused_rotary_position_embedding, swiglu,
fused_dropout_add, fused_linear, fused_linear_activation.

TPU design: on TPU the heavy ones (rms_norm, rope) route to Pallas kernels
(paddle_tpu/kernels/); the rest are composed jnp that XLA fuses inside
compiled steps. Layouts follow paddle: attention tensors are
[batch, seq, heads, head_dim].
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ....core import dispatch
from ....core import random as random_mod
from ....core.tensor import Tensor


# ----------------------------------------------------------------- rms norm
def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, bias=None, residual=None,
                   quant_scale=-1, name=None):
    """paddle.incubate.nn.functional.fused_rms_norm parity.

    Optionally adds ``bias`` and ``residual`` to ``x`` first (the fused
    bias+residual+norm pattern), then RMS-normalizes over the trailing
    axes from ``begin_norm_axis``. Returns (out, residual_out) when a
    residual is passed, else out — matching the reference.
    """
    if quant_scale != -1:
        raise NotImplementedError("quantized fused_rms_norm is not supported")
    from ....nn import functional as F

    if bias is not None:
        x = x + bias
    if residual is not None:
        x = x + residual
        residual_out = x
    out = F.rms_norm(
        x, norm_weight, norm_bias, epsilon=epsilon,
        begin_norm_axis=begin_norm_axis,
    )
    if residual is not None:
        return out, residual_out
    return out


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5,
                     begin_norm_axis=-1, bias=None, residual=None,
                     quant_scale=-1, name=None):
    """paddle.incubate.nn.functional.fused_layer_norm parity."""
    if quant_scale != -1:
        raise NotImplementedError("quantized fused_layer_norm is not supported")
    from ....nn import functional as F

    if bias is not None:
        x = x + bias
    if residual is not None:
        x = x + residual
        residual_out = x
    axis = begin_norm_axis % x.ndim
    shape = tuple(int(s) for s in x.shape[axis:])
    out = F.layer_norm(x, shape, weight=norm_weight, bias=norm_bias,
                       epsilon=epsilon)
    if residual is not None:
        return out, residual_out
    return out


# --------------------------------------------------------------------- rope
def _rope_neox(tv, c, s):
    if str(tv.dtype) == "float16":
        # Mosaic TPU rejects f16 ('Unsupported type in mosaic dialect');
        # composed rotation instead — XLA fuses it
        half = tv.shape[-1] // 2
        x1, x2 = tv[..., :half], tv[..., half:]
        o1 = x1 * c - x2 * s
        o2 = x2 * c + x1 * s
        return jnp.concatenate([o1, o2], axis=-1).astype(tv.dtype)
    from ....kernels.rope import rope_fused

    return rope_fused(tv, c, s)


def _rope_gptj(tv, c, s):
    # GPT-J interleaved style: pairs are (x[2i], x[2i+1])
    x1 = tv[..., 0::2]
    x2 = tv[..., 1::2]
    o1 = x1 * c - x2 * s
    o2 = x2 * c + x1 * s
    return jnp.stack([o1, o2], axis=-1).reshape(tv.shape)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True,
                                    time_major=False, rotary_emb_base=10000.0,
                                    name=None):
    """paddle.incubate.nn.functional.fused_rotary_position_embedding parity.

    q/k/v: [B, S, H, D]. sin/cos: broadcastable [1, S, 1, D] (reference
    layout) or half-dim [1, S, 1, D/2] tables, or None to derive from
    ``rotary_emb_base``. Returns the (q, k, v) tuple with the rotation
    applied to each non-None input. On TPU the neox-style rotation runs
    in the Pallas rope kernel (paddle_tpu/kernels/rope.py).
    """
    if time_major:
        raise NotImplementedError("time_major=True is not supported")
    lead = q if q is not None else (k if k is not None else v)
    if lead is None:
        return None, None, None
    S, D = int(lead.shape[1]), int(lead.shape[3])

    if cos is None or sin is None:
        from ....kernels.rope import build_rope_cache

        cos_h, sin_h = build_rope_cache(S, D, base=rotary_emb_base)
    else:
        cos_v = cos.value if isinstance(cos, Tensor) else jnp.asarray(cos)
        sin_v = sin.value if isinstance(sin, Tensor) else jnp.asarray(sin)
        cos_v = cos_v.reshape(1, -1, 1, cos_v.shape[-1])
        sin_v = sin_v.reshape(1, -1, 1, sin_v.shape[-1])
        if cos_v.shape[-1] == D:  # full-dim tables: two mirrored halves
            cos_h, sin_h = cos_v[..., : D // 2], sin_v[..., : D // 2]
        else:
            cos_h, sin_h = cos_v, sin_v
    if position_ids is not None:
        pid = (
            position_ids.value
            if isinstance(position_ids, Tensor)
            else jnp.asarray(position_ids)
        )
        cos_h = jnp.take(cos_h[0, :, 0, :], pid, axis=0)[:, :, None, :]
        sin_h = jnp.take(sin_h[0, :, 0, :], pid, axis=0)[:, :, None, :]

    fn = _rope_neox if use_neox_rotary_style else _rope_gptj
    op = "fused_rope" if use_neox_rotary_style else "fused_rope_gptj"
    cos_t, sin_t = Tensor(cos_h), Tensor(sin_h)

    def _one(t):
        if t is None:
            return None
        return dispatch.apply(op, fn, (t, cos_t, sin_t))

    return _one(q), _one(k), _one(v)


# ------------------------------------------------------------------- swiglu
def _swiglu_split(xv):
    x1, x2 = jnp.split(xv, 2, axis=-1)
    return jax.nn.silu(x1) * x2


def _swiglu2(xv, yv):
    return jax.nn.silu(xv) * yv


def swiglu(x, y=None, name=None):
    """paddle.incubate.nn.functional.swiglu parity: silu(x) * y.

    With y=None, x is split in half on the last axis: silu(x1) * x2.
    """
    if y is None:
        return dispatch.apply("swiglu_split", _swiglu_split, (x,))
    return dispatch.apply("swiglu", _swiglu2, (x, y))


# ------------------------------------------------------------ dropout + add
def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    """paddle.incubate.nn.functional.fused_dropout_add parity."""
    pv = float(p)
    if not training or pv == 0.0:
        return x + y
    key = random_mod.next_key()
    upscale = mode == "upscale_in_train"

    def _fn(xv, yv):
        keep = jax.random.bernoulli(key, 1.0 - pv, xv.shape)
        if upscale:
            dropped = jnp.where(keep, xv / (1.0 - pv), 0.0)
        else:
            dropped = jnp.where(keep, xv, 0.0)
        return dropped.astype(xv.dtype) + yv

    # per-call rng key -> closure, uncached (same pattern as sdpa dropout)
    return dispatch.apply("fused_dropout_add", _fn, (x, y), cache=False)


# ------------------------------------------------------------------- linear
def _linear_fn(xv, wv, bv, *, trans_w):
    w = wv.T if trans_w else wv
    y = jnp.matmul(xv, w)
    return y if bv is None else y + bv


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    """paddle.incubate.nn.functional.fused_linear parity (cublasLt fused
    gemm+epilogue upstream; one XLA fusion here)."""
    return dispatch.apply(
        "fused_linear", _linear_fn, (x, weight, bias),
        {"trans_w": bool(transpose_weight)},
    )


_ACTS = {"gelu": jax.nn.gelu, "relu": jax.nn.relu, "none": lambda v: v}


def _linear_act_fn(xv, yv, bv, *, trans_x, trans_y, act):
    a = xv.T if trans_x else xv
    b = yv.T if trans_y else yv
    y = jnp.matmul(a, b)
    if bv is not None:  # None keeps the activation dtype (no f32 zeros)
        y = y + bv
    return _ACTS[act](y)


def fused_linear_activation(x, y, bias, trans_x=False, trans_y=False,
                            activation="gelu", name=None):
    """fused gemm + bias + activation epilogue."""
    return dispatch.apply(
        "fused_linear_activation", _linear_act_fn, (x, y, bias),
        {"trans_x": bool(trans_x), "trans_y": bool(trans_y),
         "act": activation},
    )


def fused_bias_dropout_residual_layer_norm(
    x, residual, bias=None, ln_scale=None, ln_bias=None, dropout_rate=0.5,
    ln_epsilon=1e-5, training=True, mode="upscale_in_train", name=None,
):
    """paddle.incubate.nn.functional.fused_bias_dropout_residual_layer_norm."""
    from ....nn import functional as F

    h = x if bias is None else x + bias
    h = fused_dropout_add(h, residual, p=dropout_rate, training=training,
                          mode=mode)
    shape = (int(h.shape[-1]),)
    return F.layer_norm(h, shape, weight=ln_scale, bias=ln_bias,
                        epsilon=ln_epsilon)


def ring_flash_attention(q, k, v, causal=True, axis=None, name=None):
    """Exact attention over a sep-sharded sequence (ring KV rotation).
    See paddle_tpu.parallel.sep_ops for the design notes."""
    from ....parallel.sep_ops import ring_flash_attention as _ring

    return _ring(q, k, v, causal=causal, axis=axis)


def ulysses_attention(q, k, v, causal=True, axis=None, name=None):
    """Exact attention over a sep-sharded sequence (head<->seq all-to-all)."""
    from ....parallel.sep_ops import ulysses_attention as _uly

    return _uly(q, k, v, causal=causal, axis=axis)


def fused_multi_head_attention(
    x, qkv_weight, linear_weight, pre_layer_norm=False, pre_ln_scale=None,
    pre_ln_bias=None, ln_scale=None, ln_bias=None, pre_ln_epsilon=1e-5,
    qkv_bias=None, linear_bias=None, cache_kv=None, attn_mask=None,
    dropout_rate=0.5, attn_dropout_rate=0.5, ln_epsilon=1e-5,
    training=True, mode="upscale_in_train", ring_id=-1, add_residual=True,
    num_heads=None, name=None,
):
    """paddle.incubate.nn.functional.fused_multi_head_attention parity:
    (pre/post-LN) -> one QKV gemm -> attention -> out proj -> dropout +
    residual. qkv_weight accepts the reference [3, H, D, E] layout or a
    flat [E, 3E] (qkv_bias correspondingly [3, H, D] or [3E])."""
    from ....nn import functional as F

    if cache_kv is not None:
        raise NotImplementedError(
            "cache_kv (decode-time KV caching) is not supported here"
        )
    if ring_id not in (-1, None):
        raise NotImplementedError(
            "ring_id tensor parallelism: use the fleet mp_layers instead"
        )
    e = int(x.shape[-1])
    qw = qkv_weight
    if len(qw.shape) == 4:  # [3, H, D, E] -> [E, 3E]
        if num_heads is None:
            num_heads = int(qw.shape[1])
        qw = qw.reshape([3 * num_heads * int(qw.shape[2]), e]).t()
        if qkv_bias is not None and len(qkv_bias.shape) == 3:
            qkv_bias = qkv_bias.reshape([-1])  # [3, H, D] -> [3E]
    elif num_heads is None:
        raise ValueError("num_heads is required with a flat qkv_weight")
    head_dim = e // num_heads

    residual = x
    h = x
    if pre_layer_norm:
        h = F.layer_norm(h, (e,), weight=pre_ln_scale, bias=pre_ln_bias,
                         epsilon=pre_ln_epsilon)
    b, s = int(h.shape[0]), int(h.shape[1])
    qkv = fused_linear(h, qw, qkv_bias)
    qkv = qkv.reshape([b, s, 3, num_heads, head_dim])
    out = F.scaled_dot_product_attention(
        qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2], attn_mask=attn_mask,
        dropout_p=attn_dropout_rate, training=training,
    )
    out = fused_linear(out.reshape([b, s, e]), linear_weight, linear_bias)
    if add_residual:
        out = fused_dropout_add(out, residual, p=dropout_rate,
                                training=training, mode=mode)
    else:
        out = F.dropout(out, p=dropout_rate, training=training, mode=mode)
    if not pre_layer_norm:
        out = F.layer_norm(out, (e,), weight=ln_scale, bias=ln_bias,
                           epsilon=ln_epsilon)
    return out


def fused_feedforward(
    x, linear1_weight, linear2_weight, linear1_bias=None, linear2_bias=None,
    ln1_scale=None, ln1_bias=None, ln2_scale=None, ln2_bias=None,
    dropout1_rate=0.5, dropout2_rate=0.5, activation="relu",
    ln1_epsilon=1e-5, ln2_epsilon=1e-5, pre_layer_norm=False,
    training=True, mode="upscale_in_train", ring_id=-1,
    add_residual=True, name=None,
):
    """paddle.incubate.nn.functional.fused_feedforward parity:
    (pre/post-LN) -> linear+act -> dropout -> linear -> dropout +
    residual."""
    from ....nn import functional as F

    if activation not in ("gelu", "relu"):
        raise ValueError(
            f"fused_feedforward supports gelu/relu, got {activation!r}"
        )
    e = int(x.shape[-1])
    residual = x
    h = x
    if pre_layer_norm:
        h = F.layer_norm(h, (e,), weight=ln1_scale, bias=ln1_bias,
                         epsilon=ln1_epsilon)
    h = fused_linear_activation(
        h, linear1_weight, linear1_bias, activation=activation
    )
    h = F.dropout(h, p=dropout1_rate, training=training, mode=mode)
    h = fused_linear(h, linear2_weight, linear2_bias)
    if add_residual:
        out = fused_dropout_add(h, residual, p=dropout2_rate,
                                training=training, mode=mode)
    else:
        out = F.dropout(h, p=dropout2_rate, training=training, mode=mode)
    if not pre_layer_norm:
        out = F.layer_norm(out, (e,), weight=ln2_scale, bias=ln2_bias,
                           epsilon=ln2_epsilon)
    return out
