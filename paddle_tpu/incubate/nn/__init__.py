"""paddle.incubate.nn parity: fused layers + functional namespace."""
from . import functional  # noqa: F401
from .layer import FusedFeedForward, FusedMultiHeadAttention  # noqa: F401
