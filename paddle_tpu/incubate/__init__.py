"""paddle.incubate parity namespace.

Reference parity: python/paddle/incubate/ (unverified, mount empty) — the
staging ground for fused-op APIs and experimental distributed models. The
TPU build backs these with Pallas kernels (paddle_tpu/kernels/) on TPU and
composed jnp elsewhere; XLA fusion makes the composed paths one kernel in
compiled steps either way, so both tiers are "fused" in the sense that
matters (no extra HBM round trips).
"""
from . import distributed  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from ..ops.tail import (  # noqa: F401
    segment_max,
    segment_mean,
    segment_min,
    segment_sum,
)
