"""paddle.incubate.optimizer parity: fused / multi-tensor optimizers.

The multi-tensor fused Adam path (reference: fused_adam_kernel.cu +
paddle.optimizer use_multi_tensor) lives in paddle_tpu/kernels/fused_adam.py
and is wired into paddle_tpu.optimizer.Adam/AdamW via use_multi_tensor=True:
one jitted whole-tree update per step instead of one dispatch per parameter.
"""
from ...kernels.fused_adam import fused_adam_update  # noqa: F401


class LookAhead:
    """paddle.incubate.LookAhead (reference: python/paddle/incubate/
    optimizer/lookahead.py — unverified): k fast steps with the inner
    optimizer, then interpolate slow weights toward fast weights by
    alpha and reset the fast weights to the slow ones."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._step_count = 0
        self._slow = None

    def _params(self):
        return [p for _, p in self.inner_optimizer._all_params()]

    def step(self):
        import jax.numpy as jnp

        if self._slow is None:
            # deep copies: optimizer steps donate the old param buffers
            self._slow = [
                jnp.array(p.value, copy=True) for p in self._params()
            ]
        self.inner_optimizer.step()
        self._step_count += 1
        if self._step_count % self.k == 0:
            for i, p in enumerate(self._params()):
                slow = self._slow[i] + self.alpha * (
                    p.value - self._slow[i]
                )
                # keep an independent copy: the next optimizer step
                # donates (deletes) the buffer handed to the param
                self._slow[i] = jnp.array(slow, copy=True)
                p.set_value(slow)

    def clear_grad(self, set_to_zero=False):
        self.inner_optimizer.clear_grad(set_to_zero)

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def state_dict(self):
        sd = self.inner_optimizer.state_dict()
        sd["lookahead_slow"] = [
            None if s is None else s for s in (self._slow or [])
        ]
        sd["lookahead_step"] = self._step_count
        return sd


class ModelAverage:
    """paddle.incubate.ModelAverage (reference: python/paddle/incubate/
    optimizer/modelaverage.py — unverified): maintain a running average
    of parameters; apply()/restore() swap it in and out for eval."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        if parameters is None:
            raise ValueError("ModelAverage requires parameters")
        self._params = list(parameters)
        self._sums = None
        self._count = 0
        self._backup = None

    def step(self):
        import jax.numpy as jnp

        if self._sums is None:
            self._sums = [jnp.zeros_like(p.value) for p in self._params]
        for i, p in enumerate(self._params):
            self._sums[i] = self._sums[i] + p.value
        self._count += 1

    def apply(self, executor=None, need_restore=True):
        """Swap in the averaged weights. Usable as a context manager
        (``with avg.apply(): evaluate()``) which restores on exit when
        need_restore is True; double-apply without restore is rejected
        (it would back up the averaged weights and lose the trained
        ones)."""
        import contextlib

        import jax.numpy as jnp

        @contextlib.contextmanager
        def _ctx():
            try:
                yield
            finally:
                if need_restore:
                    self.restore()

        if self._count:
            if self._backup is not None:
                raise RuntimeError(
                    "ModelAverage.apply called twice without restore()"
                )
            self._backup = [
                jnp.array(p.value, copy=True) for p in self._params
            ]
            for p, s in zip(self._params, self._sums):
                p.set_value(s / float(self._count))
        return _ctx()

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p, b in zip(self._params, self._backup):
            p.set_value(b)
        self._backup = None
