"""paddle.incubate.optimizer parity: fused / multi-tensor optimizers.

The multi-tensor fused Adam path (reference: fused_adam_kernel.cu +
paddle.optimizer use_multi_tensor) lives in paddle_tpu/kernels/fused_adam.py
and is wired into paddle_tpu.optimizer.Adam/AdamW via use_multi_tensor=True:
one jitted whole-tree update per step instead of one dispatch per parameter.
"""
from ...kernels.fused_adam import fused_adam_update  # noqa: F401
