"""Mixture-of-experts with expert parallelism (config #5 surface).

Reference parity: python/paddle/incubate/distributed/models/moe/
(unverified, mount empty). See moe_layer.py for the TPU-first design notes
(stacked ep-sharded experts, einsum dispatch -> XLA all-to-all).
"""
from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate  # noqa: F401
from .grad_clip import (  # noqa: F401
    ClipGradForMOEByGlobalNorm,
    ClipGradForMoEByGlobalNorm,
)
from .moe_layer import ExpertLayer, MoELayer  # noqa: F401
