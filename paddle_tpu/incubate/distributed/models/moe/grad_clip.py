"""Expert-aware global-norm gradient clipping.

Reference parity: python/paddle/incubate/distributed/models/moe/grad_clip.py
(ClipGradForMOEByGlobalNorm — unverified, mount empty). In the reference,
each rank of the moe_group owns a DIFFERENT slice of the experts, so the
correct global norm is sqrt(|shared|^2 + allreduce_ep(|local experts|^2))
— a hand-written norm partition + collective.

TPU redesign: parameters (including stacked expert weights) are global
jax.Arrays under SPMD — every process addresses the full logical tensor and
XLA partitions the norm reduction across shards automatically. The plain
global norm therefore IS the expert-aware norm; this subclass only keeps
the reference constructor surface. It remains a ClipGradByGlobalNorm
instance, so CompiledTrainStep fuses it into the compiled step unchanged.
"""
from __future__ import annotations

from .....optimizer.clip import ClipGradByGlobalNorm


class ClipGradForMOEByGlobalNorm(ClipGradByGlobalNorm):
    def __init__(self, clip_norm, is_expert_param_func=None, moe_group=None,
                 group_name="default_moe_group"):
        super().__init__(clip_norm, group_name=group_name)
        self.is_expert_param_func = is_expert_param_func
        self.moe_group = moe_group


ClipGradForMoEByGlobalNorm = ClipGradForMOEByGlobalNorm
