"""MoELayer — mixture-of-experts with expert parallelism over the mesh.

Reference parity: python/paddle/incubate/distributed/models/moe/moe_layer.py
(unverified, mount empty): MoELayer(d_model, experts, gate, moe_group, ...)
routes tokens through per-rank expert MLPs with an all-to-all exchange
(MOEScatter/MOEGather over global_scatter/global_gather CUDA ops).

TPU-first redesign (GShard-on-XLA):

- Expert weights live STACKED with a leading expert dim — e.g. the default
  FFN expert is ``w1 [E, d, h]`` — and that dim is sharded over the ``ep``
  mesh axes with a NamedSharding.  Each "rank" therefore stores E/ep
  experts, exactly the reference's ownership model, but as one logical
  array (which also makes distributed checkpointing trivial).
- The gate emits dense dispatch/combine masks (see gate.py); the dispatch
  einsum  tokens[N,d] x dispatch[N,E,C] -> [E,C,d]  moves each token to its
  expert's capacity slot.  Because [E,C,d] is sharded over ep on dim 0 and
  tokens are sharded over dp on dim 0, XLA lowers this contraction to the
  all-to-all the reference hand-writes — no ProcessGroup calls here.
- Expert compute is ONE batched matmul pair over the expert dim (MXU
  friendly), not a Python loop; custom expert Layers fall back to a
  per-expert loop (unrolled under jit).
- ``recompute_interval > 0`` wraps the expert compute in jax.checkpoint via
  fleet.recompute, bounding activation memory like the reference's
  recompute hooks.

The layer records its load-balance auxiliary loss on ``self.l_aux`` each
forward; add ``model.moe.l_aux`` (scaled) into the training loss inside the
same step/trace.
"""
from __future__ import annotations

import numpy as np

from .....nn import functional as F
from .....nn.layer.layers import Layer
from .....nn.layer.container import LayerList
from .....nn import initializer as I
from .....ops import linalg as ops_linalg
from .....ops import math as ops_math
from .....parallel import mesh as mesh_mod
from .gate import GATE_TYPES, BaseGate


def _ep_axes(moe_group, num_expert):
    """Mesh axes the expert dim shards over.

    Priority: an explicit moe_group's mesh_axis; a dedicated 'ep' axis; the
    reference's default of folding experts over the data-parallel axes
    (dp × sharding).  Axes whose product does not divide num_expert are
    dropped (weights stay replicated rather than unevenly sharded).
    """
    if moe_group is not None and getattr(moe_group, "mesh_axis", None):
        axes = [moe_group.mesh_axis]
    else:
        shape = mesh_mod.global_mesh_shape() if mesh_mod.mesh_defined() else {}
        if shape.get("ep", 1) > 1:
            axes = ["ep"]
        else:
            axes = [a for a in ("dp", "sharding") if shape.get(a, 1) > 1]
    if not axes or not mesh_mod.mesh_defined():
        return None
    shape = mesh_mod.global_mesh_shape()
    degree = int(np.prod([shape.get(a, 1) for a in axes]))
    if degree <= 1 or num_expert % degree != 0:
        return None
    return tuple(axes)


class ExpertLayer(Layer):
    """Default FFN expert (reference ExpertLayer): d_model -> d_hidden ->
    d_model with GELU. Used standalone only for the custom-experts path;
    the stacked fast path owns its weights directly on MoELayer."""

    def __init__(self, d_model, d_hidden, activation="gelu"):
        super().__init__()
        from .....nn.layer.common import Linear

        self.htoh4 = Linear(d_model, d_hidden)
        self.h4toh = Linear(d_hidden, d_model)
        self._act = getattr(F, activation)

    def forward(self, x):
        return self.h4toh(self._act(self.htoh4(x)))


class MoELayer(Layer):
    def __init__(self, d_model, experts=None, gate=None, moe_group=None,
                 mp_group=None, recompute_interval=0, num_expert=None,
                 d_hidden=None, capacity_factor=(1.25, 2.0),
                 activation="gelu", name=None):
        super().__init__()
        if experts is not None:
            num_expert = len(experts)
        if num_expert is None:
            raise ValueError("pass `experts` (a list) or `num_expert`")
        self.d_model = d_model
        self.num_expert = num_expert
        self.recompute_interval = recompute_interval

        # ----------------------------------------------------------- gate
        if gate is None:
            gate = {"type": "gshard", "top_k": 2}
        if isinstance(gate, str):
            gate = {"type": gate}
        if isinstance(gate, dict):
            cfg = dict(gate)
            kind = cfg.pop("type", "gshard")
            top_k = cfg.pop("top_k", None)
            cls = GATE_TYPES[kind]
            if kind == "naive":
                gate = cls(d_model, num_expert,
                           top_k=top_k or 2, **cfg)
            else:
                if top_k is not None and top_k != cls.top_k:
                    raise ValueError(
                        f"gate type {kind!r} routes top-{cls.top_k}; "
                        f"got top_k={top_k} (use 'switch' for top-1, "
                        "'gshard' for top-2, 'naive' for uncapped top-k)"
                    )
                gate = cls(d_model, num_expert,
                           capacity_factor=cfg.pop(
                               "capacity_factor", capacity_factor),
                           **cfg)
        if not isinstance(gate, BaseGate):
            raise TypeError(f"gate must be a BaseGate/config, got {gate!r}")
        self.gate = gate

        # -------------------------------------------------------- experts
        self._ep = _ep_axes(moe_group, num_expert)
        if experts is None:
            if d_hidden is None:
                d_hidden = 4 * d_model
            self.d_hidden = d_hidden
            self._act = getattr(F, activation)
            self._stacked = True
            self.w1 = self._placed(self.create_parameter(
                [num_expert, d_model, d_hidden],
                default_initializer=I.XavierUniform(
                    fan_in=d_model, fan_out=d_hidden),
            ))
            self.b1 = self._placed(self.create_parameter(
                [num_expert, d_hidden], is_bias=True,
                default_initializer=I.Constant(0.0)))
            self.w2 = self._placed(self.create_parameter(
                [num_expert, d_hidden, d_model],
                default_initializer=I.XavierUniform(
                    fan_in=d_hidden, fan_out=d_model),
            ))
            self.b2 = self._placed(self.create_parameter(
                [num_expert, d_model], is_bias=True,
                default_initializer=I.Constant(0.0)))
        else:
            self._stacked = False
            self.experts = LayerList(experts)

        self.l_aux = None  # set each forward (same trace as the loss)

    # ------------------------------------------------------------ helpers
    def _placed(self, param):
        """Shard the leading expert dim of a stacked parameter over ep."""
        if self._ep is None:
            return param
        from .....distributed.fleet.meta_parallel.parallel_layers.mp_layers \
            import _place

        return _place(param, self._ep, *([None] * (len(param.shape) - 1)))

    def _ep_constraint(self, t):
        """Stamp P(ep, None, None) on an [E, C, d] activation so XLA
        partitions the dispatch/combine einsums into the all-to-all."""
        if self._ep is None:
            return t
        from .....distributed.fleet.meta_parallel.parallel_layers.mp_layers \
            import shard_constraint

        return shard_constraint(t, self._ep, *( [None] * (len(t.shape) - 1)))

    def _stacked_ffn(self, dispatched, w1, b1, w2, b2):
        """Pure-args form so recompute() threads the weights (a closure
        would treat them as constants and drop their gradients)."""
        h = ops_math.matmul(dispatched, w1)  # [E,C,h]
        h = self._act(h + b1.unsqueeze(1))
        return ops_math.matmul(h, w2) + b2.unsqueeze(1)

    def _expert_compute(self, dispatched, use_recompute=False):
        """dispatched [E, C, d] -> expert outputs [E, C, d]."""
        if use_recompute:
            from .....distributed.fleet.recompute import recompute

        if self._stacked:
            if use_recompute:
                return recompute(self._stacked_ffn, dispatched,
                                 self.w1, self.b1, self.w2, self.b2)
            return self._stacked_ffn(
                dispatched, self.w1, self.b1, self.w2, self.b2
            )
        outs = []
        for e in range(self.num_expert):
            # per-expert recompute: the expert IS a Layer, so its
            # parameters are threaded into the checkpointed function
            if use_recompute:
                outs.append(recompute(self.experts[e], dispatched[e]))
            else:
                outs.append(self.experts[e](dispatched[e]))
        from .....ops.manipulation import stack

        return stack(outs, axis=0)

    # ------------------------------------------------------------ forward
    def forward(self, x):
        orig_shape = list(x.shape)
        d = orig_shape[-1]
        x2 = x.reshape([-1, d])  # [N, d]
        combine, dispatch, aux = self.gate(x2)
        self.l_aux = aux

        # tokens -> expert capacity slots (the all-to-all under SPMD)
        dispatched = ops_linalg.einsum(
            "nec,nd->ecd", dispatch.cast(x2.dtype), x2)
        dispatched = self._ep_constraint(dispatched)

        out = self._expert_compute(
            dispatched,
            use_recompute=bool(self.recompute_interval) and self.training,
        )
        out = self._ep_constraint(out)

        # expert outputs -> original token order, gate-weighted
        y = ops_linalg.einsum("nec,ecd->nd", combine.cast(out.dtype), out)
        return y.reshape(orig_shape)
