"""MoE gates — naive / Switch top-1 / GShard top-2.

Reference parity: python/paddle/incubate/distributed/models/moe/gate/
{naive_gate,switch_gate,gshard_gate}.py (unverified, mount empty): a gate
scores tokens against experts, selects top-k, enforces per-expert capacity
with token dropping, and emits a load-balancing auxiliary loss.

TPU-first redesign: instead of producing integer routing tables consumed by
global_scatter/global_gather CUDA ops
(paddle/fluid/operators/collective/global_scatter_op.cu), each gate emits
dense GShard-style ``dispatch`` (0/1) and ``combine`` (gate-weighted) masks
of shape [N, E, C].  The MoE layer contracts these against the token matrix
with einsums; when the expert dim E is sharded over the ``ep`` mesh axis,
XLA's SPMD partitioner lowers the contraction to the all-to-all exchange the
reference hand-writes.  Everything here is static-shape jnp-traceable, so
the whole gate runs inside the compiled train step (no host round trips).

Capacity positions come from a cumulative-sum over the token order (tokens
earlier in the batch win slots), matching the reference's deterministic
prioritized assignment; GShard second choices queue behind first choices.
The reference's optional stochastic second-choice routing is intentionally
not reproduced (deterministic routing keeps SPMD runs bit-reproducible
across recompilation).
"""
from __future__ import annotations

import math

from .....nn import functional as F
from .....nn.layer.layers import Layer
from .....nn import initializer as I
from .....ops import creation as ops_creation
from .....ops import math as ops_math
from .....ops import search as ops_search


class BaseGate(Layer):
    """Common capacity bookkeeping. Subclasses implement ``forward``
    returning ``(combine [N,E,C], dispatch [N,E,C], aux_loss scalar)``."""

    def __init__(self, d_model, num_expert, capacity_factor=(1.25, 2.0),
                 min_capacity=4):
        super().__init__()
        self.d_model = d_model
        self.num_expert = num_expert
        if capacity_factor is not None and not isinstance(
            capacity_factor, (tuple, list)
        ):
            capacity_factor = (float(capacity_factor), float(capacity_factor))
        self.capacity_factor = capacity_factor
        self.min_capacity = min_capacity
        self.weight = self.create_parameter(
            [d_model, num_expert],
            default_initializer=I.XavierUniform(
                fan_in=d_model, fan_out=num_expert
            ),
        )

    def capacity(self, n_tokens: int) -> int:
        if self.capacity_factor is None:
            return int(n_tokens)
        f = self.capacity_factor[0 if self.training else 1]
        cap = int(math.ceil(f * n_tokens / self.num_expert))
        return max(self.min_capacity, min(cap, int(n_tokens)))

    # shared helpers -----------------------------------------------------
    def _slot_dispatch(self, keep, pos, cap):
        """keep [N,E] 0/1 for surviving (token, expert) pairs; pos [N,E]
        position within the expert; -> dispatch mask [N, E, C]."""
        slot = (pos * keep).sum(-1).cast("int64")  # [N]
        loc = ops_creation.one_hot(slot, cap)  # [N, C]
        return keep.unsqueeze(-1) * loc.unsqueeze(1)  # [N, E, C]

    def _aux_loss(self, probs, mask1):
        """GShard/Switch load-balance loss: E * sum_e(frac_tokens_e *
        mean_prob_e) — 1.0 at perfect balance."""
        me = probs.mean(0)
        ce = mask1.mean(0)
        return (me * ce).sum() * float(self.num_expert)


class SwitchGate(BaseGate):
    """Top-1 routing (Switch Transformer): gate value is the un-normalized
    top-1 softmax prob; dropped tokens (over capacity) pass through with a
    zero expert contribution."""

    top_k = 1

    def forward(self, x):
        n = int(x.shape[0])
        e = self.num_expert
        cap = self.capacity(n)
        logits = F.linear(x, self.weight)
        probs = F.softmax(logits, axis=-1)  # [N, E]
        idx = ops_search.argmax(probs, axis=-1)  # [N]
        mask = ops_creation.one_hot(idx, e)  # [N, E]
        aux = self._aux_loss(probs, mask)
        pos = ops_math.cumsum(mask, axis=0) - 1.0  # [N, E]
        keep = mask * (pos < float(cap)).cast(mask.dtype)
        dispatch = self._slot_dispatch(keep, pos, cap)
        gate_w = (probs * keep).sum(-1)  # [N]; 0 for dropped
        combine = dispatch * gate_w.unsqueeze(-1).unsqueeze(-1)
        return combine, dispatch, aux


class GShardGate(BaseGate):
    """Top-2 routing (GShard): the two expert choices share the token's
    probability mass (normalized over the chosen pair); second choices
    queue for capacity behind all first choices of the same expert."""

    top_k = 2

    def forward(self, x):
        n = int(x.shape[0])
        e = self.num_expert
        cap = self.capacity(n)
        logits = F.linear(x, self.weight)
        probs = F.softmax(logits, axis=-1)  # [N, E]
        _, topi = ops_search.topk(probs, min(2, e), axis=-1)
        mask1 = ops_creation.one_hot(topi[:, 0], e)
        if e > 1:
            mask2 = ops_creation.one_hot(topi[:, 1], e)
        else:
            mask2 = mask1 * 0.0
        aux = self._aux_loss(probs, mask1)

        pos1 = ops_math.cumsum(mask1, axis=0) - 1.0
        count1 = mask1.sum(0).unsqueeze(0)  # [1, E]
        pos2 = ops_math.cumsum(mask2, axis=0) - 1.0 + count1
        keep1 = mask1 * (pos1 < float(cap)).cast(mask1.dtype)
        keep2 = mask2 * (pos2 < float(cap)).cast(mask2.dtype)

        p1 = (probs * mask1).sum(-1)
        p2 = (probs * mask2).sum(-1)
        denom = p1 + p2 + 1e-9
        g1 = (p1 / denom) * keep1.sum(-1)
        g2 = (p2 / denom) * keep2.sum(-1)

        d1 = self._slot_dispatch(keep1, pos1, cap)
        d2 = self._slot_dispatch(keep2, pos2, cap)
        dispatch = d1 + d2
        combine = (
            d1 * g1.unsqueeze(-1).unsqueeze(-1)
            + d2 * g2.unsqueeze(-1).unsqueeze(-1)
        )
        return combine, dispatch, aux


class NaiveGate(GShardGate):
    """Top-k routing with no capacity limit and no aux loss (reference
    NaiveGate): every token reaches its chosen experts.

    NOTE: no capacity means C = n_tokens, so the dense dispatch/combine
    masks are [N, E, N] — O(E·N²) memory. This gate exists for
    small-scale parity testing against the reference semantics; use the
    capacity-bounded Switch/GShard gates for production-size batches.
    """

    def __init__(self, d_model, num_expert, top_k=2, **kw):
        kw.pop("capacity_factor", None)
        super().__init__(d_model, num_expert, capacity_factor=None, **kw)
        if top_k not in (1, 2):
            raise NotImplementedError("NaiveGate supports top_k in (1, 2)")
        self.top_k = top_k

    def forward(self, x):
        if self.top_k == 1:
            combine, dispatch, _ = SwitchGate.forward(self, x)
        else:
            combine, dispatch, _ = GShardGate.forward(self, x)
        aux = (combine.sum() * 0.0)
        return combine, dispatch, aux


GATE_TYPES = {
    "naive": NaiveGate,
    "switch": SwitchGate,
    "gshard": GShardGate,
}
