"""Reverse-topological backward executor.

Reference parity: egr::RunBackward's ready-queue walk over GradNodes
(reference: paddle/fluid/eager/backward.cc — unverified, mount empty).
Differences by design: grad "kernels" are jax VJP closures (XLA-compiled on
use), so this walker is pure scheduling — cotangent bookkeeping, hook firing,
leaf accumulation, and graph release. It runs identically on concrete arrays
(eager) and on tracers (when a whole step containing .backward() is jitted).
"""
from __future__ import annotations

from collections import defaultdict, deque

import jax.numpy as jnp

from ..core import dispatch
from ..core.tensor import Tensor


def _as_value(g):
    return g.value if isinstance(g, Tensor) else g


def _collect_graph(root_nodes):
    """DFS the producer graph; return (reachable nodes, edge counts).

    pending[n] = number of input-edges from reachable consumer nodes into n.
    """
    pending = defaultdict(int)
    seen, stack, nodes = set(), list(root_nodes), []
    while stack:
        n = stack.pop()
        if id(n) in seen:
            continue
        seen.add(id(n))
        nodes.append(n)
        for inp in n.inputs:
            p = inp._node
            if p is not None:
                pending[id(p)] += 1
                if id(p) not in seen:
                    stack.append(p)
    return nodes, pending


def _fire_hooks(tensor, ct):
    if tensor._hooks:
        g = Tensor(ct)
        for hook in list(tensor._hooks):
            r = hook(g)
            if r is not None:
                g = r if isinstance(r, Tensor) else Tensor(r)
        ct = g.value
    return ct


def _engine(root_pairs, retain_graph, accumulate_fn):
    """Shared walker. root_pairs: [(tensor, cotangent_value)].

    accumulate_fn(tensor, ct_value) is called for every tensor that receives
    a final cotangent (leaves, retain_grad tensors, and requested targets).
    """
    ct_map = {}  # id(tensor) -> cotangent value
    alive = {}  # id(tensor) -> tensor (keep targets alive)

    root_nodes = []
    for t, ct in root_pairs:
        if id(t) in ct_map:
            ct_map[id(t)] = ct_map[id(t)] + ct
        else:
            ct_map[id(t)] = ct
        alive[id(t)] = t
        # leaf roots are finalized below with everything else; roots with a
        # producer get their ct consumed (and hooks fired) when it runs
        if t._node is not None:
            root_nodes.append(t._node)

    nodes, pending = _collect_graph(root_nodes)
    queue = deque(n for n in nodes if pending[id(n)] == 0)
    processed = set()

    while queue:
        node = queue.popleft()
        if id(node) in processed:
            continue
        processed.add(id(node))
        if node.vjp_fn is None:
            raise RuntimeError(
                f"GradNode<{node.name}> was already released; call "
                "backward(retain_graph=True) to backprop twice through the "
                "same graph."
            )
        # gather output cotangents (zeros where no contribution arrived)
        cts = []
        for i, (shape, dtype) in enumerate(node.out_meta):
            ref = node.out_refs[i]
            t = ref() if ref is not None else None
            ct = None if t is None else ct_map.pop(id(t), None)
            if ct is not None and t is not None:
                # the tensor's gradient is now fully accumulated: hooks fire
                # exactly once, on the final value (paddle semantics)
                ct = _fire_hooks(t, ct)
                if t._retain_grad:
                    accumulate_fn(t, ct)
            if ct is None:
                ct = dispatch.zero_cotangent(shape, dtype)
            cts.append(ct)
        out_ct = tuple(cts) if node.multi else cts[0]
        in_cts = node.vjp_fn(out_ct)
        if not isinstance(in_cts, (tuple, list)):
            in_cts = (in_cts,)
        from ..core.dispatch import check_nan_inf

        check_nan_inf(f"{node.name}_grad", in_cts)
        if len(in_cts) != len(node.inputs):
            raise RuntimeError(
                f"GradNode<{node.name}> returned {len(in_cts)} grads for "
                f"{len(node.inputs)} inputs"
            )
        for inp, ct in zip(node.inputs, in_cts):
            # a None cotangent (custom vjp "no grad") still consumes the
            # graph edge — the pending decrement must happen regardless, or
            # the producer stalls and upstream grads silently vanish
            if ct is not None:
                key = id(inp)
                if key in ct_map:
                    ct_map[key] = ct_map[key] + ct
                else:
                    ct_map[key] = ct
                alive[key] = inp
            p = inp._node
            if p is not None:
                pending[id(p)] -= 1
                if pending[id(p)] == 0:
                    queue.append(p)
        if not retain_graph:
            node.release()

    # finalize: every tensor still holding a cotangent is a leaf (or a
    # retain_grad intermediate whose ct was never popped — popped cts were
    # consumed by their producer node above).
    for key, ct in ct_map.items():
        t = alive[key]
        accumulate_fn(t, _fire_hooks(t, ct))


def run_backward(tensor, grad_tensor=None, retain_graph=False):
    """Tensor.backward(): accumulate .grad on leaves (paddle semantics)."""
    if tensor.stop_gradient and tensor._node is None:
        raise RuntimeError(
            "backward() on a tensor with stop_gradient=True and no grad graph"
        )
    if grad_tensor is None:
        ct = jnp.ones(tensor.value.shape, tensor.value.dtype)
    else:
        ct = _as_value(grad_tensor)
        ct = jnp.broadcast_to(jnp.asarray(ct, tensor.value.dtype),
                              tensor.value.shape)

    def accumulate(t, ct_val):
        if t.stop_gradient and not t._retain_grad:
            return
        if t._node is not None and not t._retain_grad:
            return  # non-leaf grads not retained by default (paddle parity)
        g = Tensor(ct_val)
        if t.grad is None:
            t.grad = g
        else:
            t.grad = Tensor(t.grad.value + ct_val)

    _engine([(tensor, ct)], retain_graph, accumulate)


def backward(tensors, grad_tensors=None, retain_graph=False):
    """paddle.autograd.backward parity (multiple roots)."""
    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]
    pairs = []
    for t, g in zip(tensors, grad_tensors):
        ct = (
            jnp.ones(t.value.shape, t.value.dtype)
            if g is None
            else jnp.asarray(_as_value(g), t.value.dtype)
        )
        pairs.append((t, ct))

    def accumulate(t, ct_val):
        if t.stop_gradient:
            return
        if t._node is not None and not t._retain_grad:
            return
        t.grad = Tensor(ct_val) if t.grad is None else Tensor(t.grad.value + ct_val)

    _engine(pairs, retain_graph, accumulate)


def grad(
    outputs,
    inputs,
    grad_outputs=None,
    retain_graph=None,
    create_graph=False,
    only_inputs=True,
    allow_unused=False,
    no_grad_vars=None,
):
    """paddle.grad parity: return grads of outputs w.r.t. inputs."""
    if create_graph:
        raise NotImplementedError(
            "create_graph=True (double grad) is not supported on the eager "
            "tape yet; use paddle_tpu.incubate.autograd functional transforms "
            "(jax.grad composition) for higher-order derivatives."
        )
    single_out = isinstance(outputs, Tensor)
    outputs = [outputs] if single_out else list(outputs)
    single_in = isinstance(inputs, Tensor)
    inputs = [inputs] if single_in else list(inputs)
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif isinstance(grad_outputs, Tensor):
        grad_outputs = [grad_outputs]

    retain = bool(retain_graph) if retain_graph is not None else False
    target_ids = {id(t): i for i, t in enumerate(inputs)}
    results = [None] * len(inputs)

    # temporarily mark targets so intermediate targets also receive cts
    saved_flags = [(t, t._retain_grad) for t in inputs]
    for t in inputs:
        t._retain_grad = True

    pairs = []
    for t, g in zip(outputs, grad_outputs):
        ct = (
            jnp.ones(t.value.shape, t.value.dtype)
            if g is None
            else jnp.asarray(_as_value(g), t.value.dtype)
        )
        pairs.append((t, ct))

    def accumulate(t, ct_val):
        i = target_ids.get(id(t))
        if i is None:
            return
        results[i] = (
            Tensor(ct_val)
            if results[i] is None
            else Tensor(results[i].value + ct_val)
        )

    try:
        _engine(pairs, retain, accumulate)
    finally:
        for t, f in saved_flags:
            t._retain_grad = f

    if not allow_unused:
        for i, r in enumerate(results):
            if r is None:
                raise RuntimeError(
                    f"input {i} is unreachable from outputs; pass "
                    "allow_unused=True to get None instead"
                )
    return results[0] if single_in else results
