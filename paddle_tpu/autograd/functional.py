"""Functional higher-order autograd: jacobian / hessian / jvp / vjp.

Reference parity: python/paddle/autograd/{functional,autograd}.py
(paddle.autograd.jacobian/hessian and incubate jvp/vjp — unverified,
mount empty). TPU redesign: these are direct surfacings of jax's
transforms — the reference needs double-grad graph machinery; here
``jax.jacrev``/``jax.jacfwd``/``jax.jvp``/``jax.vjp`` compose with the
op set natively. ``func`` is a Python callable over Tensors (a Layer
works too); differentiation is with respect to the explicit ``xs``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import tape
from ..core.tensor import Tensor


def _unwrap(x):
    return x.value if isinstance(x, Tensor) else jnp.asarray(x)


def _pure(func, allow_multi=False, caller="jacobian/hessian"):
    def fn(*vals):
        with tape.trace_scope(), tape.no_grad():
            out = func(*(Tensor(v) for v in vals))
        if isinstance(out, (list, tuple)):
            if not allow_multi:
                raise ValueError(
                    f"func must return a single Tensor for {caller}"
                )
            return tuple(o.value for o in out)
        return out.value

    return fn


def _check_unsupported(create_graph, batch_axis, caller):
    if create_graph:
        raise NotImplementedError(
            f"{caller}(create_graph=True): the result is a leaf (no "
            "tape); compose jax transforms directly for higher-order "
            "graphs"
        )
    if batch_axis is not None:
        raise NotImplementedError(
            f"{caller}(batch_axis=...): vmap the function yourself for "
            "per-sample derivatives"
        )


def _maybe_tuple(xs):
    if isinstance(xs, (list, tuple)):
        return tuple(xs), True
    return (xs,), False


def jacobian(func, xs, create_graph=False, allow_unused=False,
             batch_axis=None):
    """J[i][j] = d func(xs)[i] / d xs[j]. Returns a Tensor when ``xs`` is
    a single tensor, else a tuple per input (reference layout: output
    dims first, then input dims)."""
    _check_unsupported(create_graph, batch_axis, "jacobian")
    inputs, was_tuple = _maybe_tuple(xs)
    vals = tuple(_unwrap(x) for x in inputs)
    fn = _pure(func)
    jac = jax.jacrev(fn, argnums=tuple(range(len(vals))))(*vals)
    outs = tuple(Tensor(j) for j in jac)
    return outs if was_tuple else outs[0]


def hessian(func, xs, create_graph=False, allow_unused=False,
            batch_axis=None):
    """H[i][j] = d^2 func(xs) / d xs[i] d xs[j] for a SCALAR-output
    func. Single input -> Tensor; tuple input -> tuple-of-tuples."""
    _check_unsupported(create_graph, batch_axis, "hessian")
    inputs, was_tuple = _maybe_tuple(xs)
    vals = tuple(_unwrap(x) for x in inputs)
    fn = _pure(func)

    def scalar_fn(*vs):
        out = fn(*vs)
        if out.ndim != 0 and out.size != 1:
            raise ValueError("hessian requires a scalar-output func")
        return out.reshape(())

    hes = jax.hessian(scalar_fn, argnums=tuple(range(len(vals))))(*vals)
    if was_tuple:
        return tuple(tuple(Tensor(h) for h in row) for row in hes)
    return Tensor(hes[0][0])


def _wrap_out(out):
    if isinstance(out, tuple):
        return tuple(Tensor(o) for o in out)
    return Tensor(out)


def jvp(func, xs, v=None):
    """(outputs, J @ v): forward-mode directional derivative. Multi-
    output funcs return tuples in both slots."""
    inputs, _ = _maybe_tuple(xs)
    vals = tuple(_unwrap(x) for x in inputs)
    if v is None:
        tangents = tuple(jnp.ones_like(x) for x in vals)
    else:
        vt, _ = _maybe_tuple(v)
        tangents = tuple(_unwrap(t) for t in vt)
    fn = _pure(func, allow_multi=True, caller="jvp")
    out, tang = jax.jvp(fn, vals, tangents)
    return _wrap_out(out), _wrap_out(tang)


def vjp(func, xs, v=None):
    """(outputs, v^T @ J): reverse-mode; v defaults to ones (matching
    each output for multi-output funcs)."""
    inputs, was_tuple = _maybe_tuple(xs)
    vals = tuple(_unwrap(x) for x in inputs)
    fn = _pure(func, allow_multi=True, caller="vjp")
    out, vjp_fn = jax.vjp(fn, *vals)
    if v is None:
        ct = jax.tree_util.tree_map(jnp.ones_like, out)
    elif isinstance(out, tuple):
        vt, _ = _maybe_tuple(v)
        ct = tuple(_unwrap(t) for t in vt)
    else:
        ct = _unwrap(v[0] if isinstance(v, (list, tuple)) else v)
    grads = vjp_fn(ct)
    gout = tuple(Tensor(g) for g in grads)
    return _wrap_out(out), (gout if was_tuple else gout[0])
