"""paddle_tpu.autograd — imperative autograd API over jax VJPs.

Reference parity: python/paddle/autograd/ (unverified, mount empty).
"""
from ..core.tape import no_grad, enable_grad, set_grad_enabled, is_grad_enabled
from .backward import backward, grad, run_backward
from .functional import hessian, jacobian, jvp, vjp
from .py_layer import PyLayer, PyLayerContext

__all__ = [
    "no_grad",
    "enable_grad",
    "set_grad_enabled",
    "is_grad_enabled",
    "backward",
    "grad",
    "jacobian",
    "hessian",
    "jvp",
    "vjp",
    "PyLayer",
    "PyLayerContext",
]
