"""Custom autograd functions.

Reference parity: paddle.autograd.PyLayer
(reference: python/paddle/autograd/py_layer.py — unverified, mount empty).
User-defined forward/backward pairs become GradNodes whose vjp calls the
user's backward under no_grad.
"""
from __future__ import annotations

from ..core import dispatch, tape
from ..core.tensor import Tensor


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.not_inplace = True

    def save_for_backward(self, *tensors):
        self._saved = tuple(tensors)

    def saved_tensor(self):
        return self._saved

    # paddle alias
    saved_tensors = property(lambda self: self._saved)


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        with tape.no_grad():
            out = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(out, (tuple, list))
        outs = tuple(out) if multi else (out,)
        out_vals = tuple(o.value if isinstance(o, Tensor) else o for o in outs)

        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        diff_mask = [dispatch._is_diff_tensor(a) for a in tensor_inputs]

        def vjp_fn(out_cts):
            with tape.no_grad():
                ct_tensors = tuple(Tensor(c) for c in out_cts)
                grads = cls.backward(ctx, *ct_tensors)
            if not isinstance(grads, (tuple, list)):
                grads = (grads,)
            if len(grads) != len(tensor_inputs):
                raise RuntimeError(
                    f"{cls.__name__}.backward returned {len(grads)} grads for "
                    f"{len(tensor_inputs)} tensor inputs"
                )
            return tuple(
                (g.value if isinstance(g, Tensor) else g)
                for g, m in zip(grads, diff_mask)
                if m
            )

        wrapped = dispatch.custom_vjp_apply(
            cls.__name__, tensor_inputs, out_vals, vjp_fn
        )
        return wrapped if multi else wrapped[0]
