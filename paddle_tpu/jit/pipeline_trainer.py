"""CompiledPipelineTrainStep — PipelineLayer on the compiled pp schedule.

Reference parity: the integration the reference gets from
fleet.distributed_model(PipelineLayer) + PipelineParallel.train_batch
(python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py,
pp_layers.py — unverified, mount empty), here fused into ONE jitted train
step (SURVEY.md §7 hard part #2).

Bridge design: a PipelineLayer is [prefix..., block*L, suffix...] where
the blocks are the repeated transformer body. This trainer auto-detects
the longest run of same-architecture blocks, and at trace time:

  1. runs the prefix items (embedding etc.) on the whole batch — these
     live OUTSIDE the pp ring (replicated or TP-sharded via GSPMD, like
     the reference's non-uniform first stage),
  2. reshapes activations to [M, B/M, ...] microbatches and runs the
     blocks through parallel.pipeline.pipeline_apply inside a shard_map
     that is MANUAL over pp only — dp/mp stay in GSPMD auto mode, so
     Megatron TP layers and dp batch sharding compose inside the ring,
  3. re-flattens and runs the suffix (head) + loss on the whole batch
     (exact for mean losses: equals averaging per-microbatch losses).

Block parameters are stacked in-trace from the per-block Parameters and
constrained to P('pp') — XLA keeps per-step re-stacking cheap relative to
the schedule, the imperative Layer objects remain the source of truth
(state_dict/checkpoint unchanged), and grads flow back through the stack
to each block's own Parameter. ``num_virtual>1`` enables the interleaved
schedule; PipelineLayer.recompute_interval>0 turns on per-block remat
inside the ring.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from ..parallel import mesh as mesh_mod
from ..parallel import pipeline as pipe_mod
from .trainer import CompiledTrainStep


def _block_signature(layer):
    if not isinstance(layer, Layer):
        return None
    names = tuple(
        (k, tuple(p.shape), str(p.dtype))
        for k, p in layer.named_parameters()
    )
    return (type(layer), names) if names else None


class CompiledPipelineTrainStep(CompiledTrainStep):
    def __init__(self, layers, loss_fn, optimizer, micro_batches=1,
                 num_virtual=1, amp_level=None, amp_dtype="bfloat16",
                 pp_axis=None, scaler=None, layout_policy=None):
        from ..distributed.fleet.meta_parallel.parallel_layers.pp_layers \
            import PipelineLayer
        from ..parallel import layout as layout_mod

        if not isinstance(layers, PipelineLayer):
            raise TypeError(
                "CompiledPipelineTrainStep expects a PipelineLayer"
            )
        if pp_axis is None:
            # the ring axis comes from the layout policy (one object
            # names every axis), not a per-call-site string
            pol = (
                layout_mod.resolve(layout_policy)
                if layout_policy is not None
                else layout_mod.get_policy()
            )
            pp_axis = pol.pp_axis
        # fp16 dynamic loss scaling rides the base class's in-trace
        # mechanism unchanged: the whole-batch loss after the ppermute
        # schedule is scaled, grads unscaled + finite-checked across ALL
        # stages at once (SPMD: every rank sees the global grads), and the
        # update conditionally skipped with scaler state carried through
        # the jitted step (reference: PipelineParallel + GradScaler).
        super().__init__(
            layers, loss_fn, optimizer, amp_level, amp_dtype,
            scaler=scaler, layout_policy=layout_policy,
        )
        self.micro_batches = int(micro_batches)
        self.num_virtual = int(num_virtual)
        self.pp_axis = pp_axis
        self.pp_degree = mesh_mod.axis_size(pp_axis)
        self._remat = layers._recompute_interval > 0
        self._analyze(layers)

    # ------------------------------------------------------- structure
    def _analyze(self, pl):
        items = pl._items  # [(desc, layer)]
        tile = self.pp_degree * self.num_virtual
        # layers appearing more than once (SharedLayerDesc) cannot stack
        counts = {}
        for _, l in items:
            counts[id(l)] = counts.get(id(l), 0) + 1
        sigs = [
            _block_signature(l) if counts[id(l)] == 1 else None
            for _, l in items
        ]
        best_len, best_start = 0, 0
        i = 0
        while i < len(items):
            if sigs[i] is None:
                i += 1
                continue
            j = i
            while j < len(items) and sigs[j] == sigs[i]:
                j += 1
            if j - i > best_len:
                best_len, best_start = j - i, i
            i = j
        usable = (best_len // tile) * tile
        if usable == 0:
            raise ValueError(
                f"PipelineLayer has no run of >= {tile} identical blocks "
                f"(pp_degree {self.pp_degree} x virtual {self.num_virtual});"
                " longest repeated-architecture run is "
                f"{best_len} — adjust the model depth or degrees"
            )
        self._blk_lo = best_start
        self._blk_hi = best_start + usable  # tail of the run joins suffix
        # stable index->registered-name mapping for the block params
        self._blk_indices = list(range(self._blk_lo, self._blk_hi))
        for idx in self._blk_indices:
            _, l = items[idx]
            if list(l.named_buffers()):
                raise NotImplementedError(
                    "pipeline blocks with buffers (e.g. BatchNorm running "
                    "stats) are not supported in the compiled pp schedule; "
                    "use LayerNorm/RMSNorm blocks or the eager engine"
                )
        self._template = items[self._blk_lo][1]

    # ------------------------------------------------------- traced fwd
    def _forward_traced(self, inputs):
        pl = self.network
        items = pl._items
        x = Tensor(inputs[0]) if len(inputs) == 1 else tuple(
            Tensor(v) for v in inputs
        )
        for it in items[: self._blk_lo]:
            x = pl._run_item(it, x)

        if not isinstance(x, Tensor):
            raise NotImplementedError(
                "the compiled pipeline schedule requires a single-tensor "
                "activation entering the block run (got a tuple); fold "
                "extra inputs into the blocks or use the eager engine "
                "(pipeline_configs={'compiled': False})"
            )
        M = self.micro_batches
        hv = x.value
        B = hv.shape[0]
        if B % M != 0:
            raise ValueError(
                f"batch {B} not divisible by micro_batches {M}"
            )
        h_mb = hv.reshape((M, B // M) + hv.shape[1:])

        # per-block param trees (current traced values), stacked [S,(v,)k]
        template = self._template
        rel_names = [k for k, _ in template.named_parameters()]
        per_block = []
        for idx in self._blk_indices:
            _, l = items[idx]
            tree = {k: p.value for k, p in l.named_parameters()}
            per_block.append([tree[k] for k in rel_names])
        stacked = pipe_mod.stack_block_params(
            per_block, self.pp_degree, self.num_virtual
        )
        mesh = mesh_mod.get_mesh()
        stacked = [
            jax.lax.with_sharding_constraint(
                a, NamedSharding(mesh, P(self.pp_axis))
            )
            for a in stacked
        ]

        def block_fn(blk, xv):
            template.load_functional_state(
                dict(zip(rel_names, blk))
            )
            return pl._run_item(
                (None, template), Tensor(xv)
            ).value

        if self.pp_degree > 1:
            pipe_fn = pipe_mod.make_pipeline_fn(
                block_fn, self.pp_degree, mesh, self.pp_axis,
                num_virtual=self.num_virtual, remat=self._remat,
                manual_axes={self.pp_axis},
            )
            out_mb = pipe_fn(stacked, h_mb)
        else:
            # pp degree 1: plain scan over all blocks (still microbatched
            # so the schedule semantics — loss averaging — match)
            flat = [
                a.reshape((-1,) + a.shape[2 + (self.num_virtual > 1):])
                for a in stacked
            ]

            def body(hh, blk):
                return block_fn(blk, hh), None

            outs = []
            for m in range(M):
                hm, _ = jax.lax.scan(body, h_mb[m], flat)
                outs.append(hm)
            out_mb = jnp.stack(outs)

        out = out_mb.reshape((B,) + out_mb.shape[2:])
        y = Tensor(out)
        for it in items[self._blk_hi :]:
            y = pl._run_item(it, y)
        return y
