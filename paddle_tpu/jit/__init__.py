"""paddle.jit — the step compiler.

Reference parity: python/paddle/jit/ (dy2static AST translator + SOT
bytecode capture + CINN offload — unverified, mount empty). TPU-first
redesign per SURVEY.md §3.5: there is no source translation at all — JAX
tracing IS the dynamic-to-static bridge, and XLA is the compiler CINN was
retargeting. ``to_static`` wraps a Layer/function into a traced, cached,
whole-program-compiled callable; ``save``/``load`` export/import StableHLO
via jax.export (the deployment format replacing ProgramDesc+params).
"""
from .api import TranslatedLayer, ignore_module, load, not_to_static, save, to_static  # noqa: F401
from .trainer import CompiledTrainStep  # noqa: F401
from . import dy2static  # noqa: F401
