"""to_static / jit.save / jit.load.

See package docstring. A StaticFunction jits the wrapped Layer's forward as
a pure function of (params, buffers, inputs); recompilation is keyed by
input shapes/dtypes exactly like the reference's program cache.
"""
from __future__ import annotations

import json
import os

import numpy as np

import jax
import jax.export  # explicit: a submodule, not auto-imported on jax<0.5
import jax.numpy as jnp

from ..core import random as random_mod
from ..core import tape
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from ..static import InputSpec


def _unwrap(x):
    return x.value if isinstance(x, Tensor) else x


def _to_values(out):
    """Structurally convert Tensors -> raw arrays (Tensor IS a pytree node,
    so tree_map would rebuild Tensors instead of unwrapping them)."""
    if isinstance(out, Tensor):
        return out.value
    if isinstance(out, (list, tuple)):
        return type(out)(_to_values(v) for v in out)
    if isinstance(out, dict):
        return {k: _to_values(v) for k, v in out.items()}
    return out


def _to_tensors(out):
    if hasattr(out, "dtype") and hasattr(out, "shape"):
        return Tensor(out)
    if isinstance(out, (list, tuple)):
        return type(out)(_to_tensors(v) for v in out)
    if isinstance(out, dict):
        return {k: _to_tensors(v) for k, v in out.items()}
    return out


class StaticFunction:
    """Callable wrapping a Layer or function with whole-program jax.jit."""

    def __init__(self, fn, layer=None, input_spec=None, full_graph=True):
        from .dy2static import convert_to_static

        # AST pass first: Python if/while on traced predicates become
        # lax.cond/lax.while_loop so data-dependent control flow compiles
        self._fn = convert_to_static(fn)
        self._layer = layer
        self._input_spec = input_spec
        # one compiled program per train/eval mode: dropout/batch-norm
        # behavior is baked at trace time, so the cache is keyed on it
        self._jitted = {}
        owner = type(layer).__name__ if layer is not None else getattr(
            fn, "__qualname__", getattr(fn, "__name__", "fn")
        )
        self._guard_key = f"to_static::{owner}"
        # per-instance recompile guard (the serving-engine pattern): a
        # process-global registry would pin the jitted closure — and the
        # whole Layer it closes over — for process lifetime, and two
        # instances of one class would collide on the key
        from ..analysis.trace_guard import TraceGuard

        self.trace_guard = TraceGuard()

    def _build(self, mode):
        layer = self._layer
        fn = self._fn
        # NO buffer donation here, deliberately: Layer buffer arrays are
        # aliased by external snapshots (ServingEngine._buffers,
        # functional_state() holders), so donating them would delete
        # arrays a snapshot still references — 'Array has been deleted'
        # at a distance on accelerators. The linter's donation-miss
        # finding on this graph is accepted in the lint baseline with
        # this reason; the un-aliased optimizer-state donations landed
        # instead.

        if layer is not None:
            def pure(params, buffers, rng, *input_vals):
                layer.load_functional_state(params, buffers)
                if mode:
                    layer.train()
                else:
                    layer.eval()
                with tape.trace_scope(), tape.no_grad(), random_mod.key_scope(rng):
                    out = fn(*(Tensor(v) for v in input_vals))
                out_vals = _to_values(out)
                new_buffers = {k: b.value for k, b in layer.named_buffers()}
                return out_vals, new_buffers

            self._jitted[mode] = jax.jit(pure)
        else:
            def pure(rng, *input_vals):
                with tape.trace_scope(), tape.no_grad(), random_mod.key_scope(rng):
                    out = fn(*(Tensor(v) for v in input_vals))
                return _to_values(out)

            self._jitted[mode] = jax.jit(pure)
        # recompile guard: jax.jit re-traces on every new input
        # shape/dtype signature invisibly to this wrapper — register the
        # compiled callable so the trace guard can poll its cache
        # growth and flag storms (drifting shapes)
        self.trace_guard.watch(
            f"{self._guard_key}[mode={int(mode)}]", self._jitted[mode]
        )

    def __call__(self, *inputs):
        mode = bool(self._layer.training) if self._layer is not None else False
        if mode not in self._jitted:
            self._build(mode)
        jitted = self._jitted[mode]
        vals = [_unwrap(x) for x in inputs]
        rng = random_mod.next_key()
        if self._layer is not None:
            params = {k: p.value for k, p in self._layer.named_parameters()}
            buffers = {k: b.value for k, b in self._layer.named_buffers()}
            out_vals, new_buffers = jitted(params, buffers, rng, *vals)
            # restore concrete values (tracing left tracers inside the layer)
            self._layer.load_functional_state(params, new_buffers)
            if mode:
                self._layer.train()
            else:
                self._layer.eval()
        else:
            out_vals = jitted(rng, *vals)
        self.trace_guard.check()  # ≤2 entries: a cheap per-call poll
        return _to_tensors(out_vals)

    # paddle API parity
    @property
    def code(self):
        return "<jax-traced; no translated source on TPU>"

    def concrete_program_specify_input_spec(self, *a, **k):
        return None


def to_static(function=None, input_spec=None, full_graph=True, backend=None,
              **kwargs):
    """Decorator/wrapper: compile a Layer's forward or a function with XLA."""

    def wrap(obj):
        if isinstance(obj, Layer):
            static = StaticFunction(
                obj.forward, layer=obj, input_spec=input_spec
            )
            obj.forward = static
            obj._static_forward = static
            return obj
        return StaticFunction(obj, layer=None, input_spec=input_spec)

    if function is not None:
        return wrap(function)
    return wrap


def not_to_static(fn=None):
    return fn


def ignore_module(modules):
    return None


def save(layer, path, input_spec=None, **configs):
    """Export layer inference graph as StableHLO + params (jit.save parity).

    Produces: path.json (meta), path.stablehlo (serialized jax.export
    artifact), path.pdparams (state dict) — the TPU-native analog of the
    reference's __model__ + params deployment bundle.
    """
    from ..framework.io import save as fsave

    if isinstance(layer, StaticFunction):
        fn, owner = layer._fn, layer._layer
    elif isinstance(layer, Layer):
        fn, owner = layer.forward, layer
        if isinstance(fn, StaticFunction):
            fn, owner = fn._fn, fn._layer
    else:
        fn, owner = layer, None

    if input_spec is None and owner is not None:
        raise ValueError("jit.save requires input_spec (shape contract)")
    specs = [
        s if isinstance(s, InputSpec) else InputSpec.from_tensor(s)
        for s in (input_spec or [])
    ]
    # None/-1 dims become symbolic so the exported StableHLO is
    # batch-polymorphic (replaces the reference's -1 dims in ProgramDesc)
    scope = jax.export.SymbolicScope()
    examples = []
    for si, s in enumerate(specs):
        dim_strs = [
            f"b{si}_{di}" if (d is None or d < 0) else str(d)
            for di, d in enumerate(s.shape or [])
        ]
        shape = jax.export.symbolic_shape(
            ",".join(dim_strs) if dim_strs else "", scope=scope
        )
        examples.append(jax.ShapeDtypeStruct(shape, s.dtype))

    params = {k: p.value for k, p in owner.named_parameters()} if owner else {}
    buffers = {k: b.value for k, b in owner.named_buffers()} if owner else {}

    def pure(params, buffers, *input_vals):
        if owner is not None:
            owner.load_functional_state(params, buffers)
        was_training = owner.training if owner is not None else False
        if owner is not None:
            owner.eval()
        try:
            with tape.trace_scope(), tape.no_grad():
                out = fn(*(Tensor(v) for v in input_vals))
        finally:
            if owner is not None and was_training:
                owner.train()
        return _to_values(out)

    exported = jax.export.export(jax.jit(pure))(params, buffers, *examples)
    if owner is not None:
        owner.load_functional_state(params, buffers)  # clear leaked tracers
    blob = exported.serialize()

    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path + ".stablehlo", "wb") as f:
        f.write(blob)
    fsave({"params": params, "buffers": buffers}, path + ".pdiparams")
    import inspect

    try:
        sig_names = [
            p.name for p in inspect.signature(fn).parameters.values()
            if p.name != "self"
            and p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
        ][: len(specs)]
    except (TypeError, ValueError):
        sig_names = []
    if len(sig_names) != len(specs):
        sig_names = [f"input_{i}" for i in range(len(specs))]
    # an explicit InputSpec.name is the feed name (reference contract);
    # the forward signature is only the fallback
    sig_names = [
        (s.name if getattr(s, "name", None) else fallback)
        for s, fallback in zip(specs, sig_names)
    ]
    if len(set(sig_names)) != len(sig_names):
        raise ValueError(
            f"input_spec feed names must be unique, got {sig_names} "
            "(named handles would collide in the predictor)"
        )
    meta = {
        "input_specs": [
            {"shape": s.shape, "dtype": np.dtype(s.dtype).name} for s in specs
        ],
        "input_names": sig_names,
        "format": "paddle_tpu.stablehlo.v1",
    }
    with open(path + ".json", "w") as f:
        json.dump(meta, f)


class TranslatedLayer(Layer):
    """A loaded inference program (jit.load result)."""

    def __init__(self, exported, state):
        super().__init__()
        self._exported = exported
        self._state = state

    def forward(self, *inputs):
        vals = [_unwrap(x) for x in inputs]
        out = self._exported.call(
            self._state["params"], self._state["buffers"], *vals
        )
        return _to_tensors(out)


def load(path, params_path=None, **configs):
    """Load a saved inference artifact. ``params_path`` overrides the
    default co-located weights file (deployment layouts may keep
    finetuned params elsewhere — the reference Config's params_file)."""
    from ..framework.io import load as fload

    with open(path + ".stablehlo", "rb") as f:
        exported = jax.export.deserialize(f.read())
    state = fload(params_path or (path + ".pdiparams"), return_numpy=False)

    def _val(v):
        import jax.numpy as jnp

        return jnp.asarray(v.value if isinstance(v, Tensor) else v)

    state = {
        "params": {k: _val(v) for k, v in state["params"].items()},
        "buffers": {k: _val(v) for k, v in state["buffers"].items()},
    }
    return TranslatedLayer(exported, state)
