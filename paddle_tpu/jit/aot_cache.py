"""Persistent AOT compile cache — serialized XLA executables on disk.

The serving engines compile a small, fully-enumerable set of
fixed-shape programs (one decode step, one prefill + one adopt per
power-of-two prompt bucket — ``analysis.TraceGuard`` inventories
exactly these entries at runtime). Cold start therefore pays one XLA
compile per program at first traffic: seconds of wall clock per bucket
while the chip idles, multiplied by every relaunch and every newly
spawned replica. This module makes those compiles a one-time cost per
(program, geometry, device-kind):

- ``engine.warmup(aot_cache=dir)`` lowers + compiles every program
  BEFORE first traffic and serializes each finished executable here
  (``jax.experimental.serialize_executable`` — the PjRt executable
  blob plus its arg/result trees, pickled and written atomically);
- a relaunched or newly spawned replica with the same cache dir
  deserializes the executables instead of tracing or compiling
  anything: it reaches READY with zero new trace-guard compile
  entries, and its first request runs the exact same binary the
  previous process ran.

Keys hash the full program identity: engine geometry + model dims +
sampling config, the aval signature (shape/dtype of every leaf plus
the pytree structure), jax version, backend platform and device kind —
any drift is a clean MISS, never a wrong executable. A corrupt or
unreadable entry degrades to a cold compile (counted, one warning),
mirroring the kernel tune cache's discipline. The conventional
location is ``aot_cache/`` next to ``jit.save`` artifacts or inside a
checkpoint root (:func:`cache_dir_for`).

Cache hits/misses/saves publish as
``paddle_jit_aot_cache_total{event=...}``.
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import tempfile
import threading

import jax

logger = logging.getLogger("paddle_tpu.jit.aot_cache")

MANIFEST_FILE = "manifest.json"


def cache_dir_for(artifact_or_ckpt_dir):
    """The conventional AOT cache location next to saved artifacts or
    inside a checkpoint root."""
    return os.path.join(str(artifact_or_ckpt_dir), "aot_cache")


def _aval_signature(args):
    """(pytree structure repr, per-leaf shape/dtype) — the part of a
    program's identity its example arguments carry."""
    leaves, treedef = jax.tree_util.tree_flatten(args)
    avals = []
    for leaf in leaves:
        shape = list(getattr(leaf, "shape", ()))
        dtype = str(getattr(leaf, "dtype", type(leaf).__name__))
        avals.append([shape, dtype])
    return {"tree": str(treedef), "avals": avals}


def _count(event):
    try:
        from ..observability import get_registry

        get_registry().counter(
            "paddle_jit_aot_cache_total",
            help="AOT compile-cache events (hit|miss|save|error)",
        ).inc(event=event)
    except Exception:
        pass


class AOTProgramCache:
    """Directory of serialized executables + a JSON manifest.

    The manifest (``manifest.json``) is the human/tooling inventory:
    one record per entry with the program name, aval signature and
    provenance. It is advisory — entry files are self-contained, and a
    concurrent writer losing a manifest read-modify-write race costs
    only an inventory line, never a wrong load."""

    def __init__(self, path):
        self.path = str(path)
        os.makedirs(self.path, exist_ok=True)
        self._lock = threading.Lock()
        self._warned_save = False

    # ----------------------------------------------------------- keying
    def key_for(self, signature, example_args):
        """``(key, meta)`` for a program: ``signature`` is the caller's
        identity dict (engine geometry, model dims, ...), the rest is
        derived — aval signature, jax version, platform, device kind."""
        dev = jax.devices()[0]
        meta = {
            "signature": signature,
            "args": _aval_signature(example_args),
            "jax": jax.__version__,
            "platform": dev.platform,
            "device_kind": getattr(dev, "device_kind", "unknown"),
        }
        key = hashlib.sha256(
            json.dumps(meta, sort_keys=True).encode("utf-8")
        ).hexdigest()[:32]
        return key, meta

    def _entry_path(self, key):
        return os.path.join(self.path, f"{key}.aotx")

    def has(self, key):
        return os.path.isfile(self._entry_path(key))

    # ------------------------------------------------------------ load
    def load(self, key):
        """Deserialize + load the executable for ``key``, or None on
        miss/corruption (a bad entry is removed and counted — the
        caller falls back to a cold compile)."""
        p = self._entry_path(key)
        if not os.path.isfile(p):
            _count("miss")
            return None
        try:
            from jax.experimental import serialize_executable as se

            with open(p, "rb") as f:
                parts = pickle.load(f)
            compiled = se.deserialize_and_load(*parts)
        except Exception as e:
            _count("error")
            logger.warning(
                "aot cache: entry %s unusable (%r); recompiling", p, e
            )
            try:
                os.remove(p)
            except OSError:
                pass
            return None
        _count("hit")
        return compiled

    # ------------------------------------------------------------ save
    def save(self, key, compiled, meta):
        """Serialize ``compiled`` under ``key`` (atomic write) and add
        its manifest record. Returns True on success; failures degrade
        to not-cached (counted, warned once)."""
        try:
            from jax.experimental import serialize_executable as se

            blob = pickle.dumps(se.serialize(compiled))
            fd, tmp = tempfile.mkstemp(
                dir=self.path, suffix=".aotx.tmp"
            )
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(blob)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self._entry_path(key))
            except BaseException:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                raise
            self._note_entry(key, meta, len(blob))
        except Exception as e:
            _count("error")
            if not self._warned_save:
                self._warned_save = True
                logger.warning(
                    "aot cache: cannot serialize executables on this "
                    "backend (%r); warmup still compiles, nothing is "
                    "persisted", e
                )
            return False
        _count("save")
        return True

    # -------------------------------------------------------- manifest
    def _manifest_path(self):
        return os.path.join(self.path, MANIFEST_FILE)

    def entries(self):
        """The manifest inventory ``{key: record}`` ({} when absent)."""
        try:
            with open(self._manifest_path()) as f:
                doc = json.load(f)
            return doc.get("entries", {}) if isinstance(doc, dict) else {}
        except (OSError, ValueError):
            return {}

    def _note_entry(self, key, meta, nbytes):
        with self._lock:
            entries = self.entries()
            entries[key] = {
                "program": (meta.get("signature") or {}).get("program"),
                "bytes": int(nbytes),
                "meta": meta,
            }
            doc = json.dumps({"version": 1, "entries": entries},
                             indent=1, sort_keys=True)
            fd, tmp = tempfile.mkstemp(dir=self.path,
                                       suffix=".manifest.tmp")
            try:
                with os.fdopen(fd, "w") as f:
                    f.write(doc)
                os.replace(tmp, self._manifest_path())
            except BaseException:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                raise


def resolve(cache):
    """Accept an :class:`AOTProgramCache` or a directory path (or
    None); the engine warmup seam calls this so callers can pass
    either."""
    if cache is None or isinstance(cache, AOTProgramCache):
        return cache
    return AOTProgramCache(cache)
