"""CompiledTrainStep — the whole-step jitted trainer (the TPU perf path).

Reference parity: this replaces the reference's executor+CINN "static graph
training" mode (SURVEY.md §7 stage 4). One jax.jit covers forward, backward,
gradient clipping, weight decay, and the optimizer update, with parameter
and optimizer-state buffers donated — XLA fuses the lot and the host only
dispatches one executable per step. Loss scaling / AMP run inside the trace.

Works with the imperative Layer/Optimizer objects: parameters and optimizer
accumulators are pulled into pytrees, the pure step runs, and the results
are written back — so .state_dict(), checkpoints, and eager inspection all
keep working between steps.
"""
from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp

from .. import chaos as _chaos
from ..core import random as random_mod
from ..core import tape
from ..core.tensor import Tensor
from ..optimizer import optimizer as opt_mod


def _unwrap(x):
    return x.value if isinstance(x, Tensor) else x


class CompiledTrainStep:
    """Build once per (network, loss, optimizer); call with batches."""

    SUPPORTED = (
        opt_mod.AdamW,  # check subclasses before parents
        opt_mod.Adam,
        opt_mod.Lamb,
        opt_mod.Momentum,
        opt_mod.SGD,
    )

    def __init__(self, network, loss_fn, optimizer, amp_level=None,
                 amp_dtype="bfloat16", scaler=None, layout_policy=None):
        from .dy2static import convert_to_static

        # dy2static pass on the top-level forward so Python if/while on
        # tensor values compile (lax.cond/while_loop) inside the step.
        # The converted forward is swapped in ONLY while tracing the step
        # (_forward_traced) — plain eager calls keep the original method.
        self._converted_forward = None
        fw = network.forward
        if callable(fw) and not hasattr(fw, "_jitted"):
            conv = convert_to_static(fw)
            if getattr(conv, "__func__", conv) is not getattr(
                fw, "__func__", fw
            ):
                self._converted_forward = conv
        self.network = network
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.amp_level = amp_level
        self.amp_dtype = amp_dtype
        # fp16 dynamic loss scaling fused INTO the compiled step: scale
        # the loss, unscale grads, skip the update on inf/nan, and grow/
        # shrink the scale — all in-trace (reference GradScaler + fp16)
        self.scaler = self._normalize_scaler(scaler)
        self._kind = None
        for cls in self.SUPPORTED:
            if type(optimizer) is cls or isinstance(optimizer, cls):
                self._kind = cls
                break
        if self._kind is None:
            raise NotImplementedError(
                f"CompiledTrainStep does not support {type(optimizer).__name__};"
                " use the eager path"
            )
        self._step_fn = None
        self._param_names = [k for k, _ in network.named_parameters()]
        self._checkpoint = None
        self._sentinel = None
        self._watchdog = None
        # sharding layout: an explicit LayoutPolicy (or registry name)
        # pins this trainer; None captures the ACTIVE parallel.layout
        # policy NOW, at construction — so the documented pattern
        # (`with layout.use_policy(...): trainer = ...`, step later)
        # keeps the chosen layout even after the context exits. The
        # policy's optimizer-state / master-param rules are stamped on
        # the step's outputs, so e.g. the pp-sharded-state layout keeps
        # Adam moments sharded over the pp axis across steps (the
        # 29.4 -> 18.4 GiB/chip 7B lever).
        from ..parallel import layout as layout_mod

        self._layout_policy = (
            layout_mod.resolve(layout_policy)
            if layout_policy is not None
            else layout_mod.get_policy()
        )
        # AMP O3 (fp8 matmuls): per-tensor delayed-scaling amax
        # histories, carried through the compiled step next to the
        # optimizer state (structure discovered on the first call)
        self._fp8_state = None
        self._fp8_bytes_saved = 0
        # step-argument avals captured at first invoke — the
        # memory_report() trace input (HBM footprint next to the
        # StepMeter gauges)
        self._step_args_sds = None

    def attach_checkpoint(self, manager):
        """Wire a ``checkpoint.CheckpointManager`` into the step loop:
        after each optimizer step the manager's policy decides whether
        to kick off an async save. The manager is bound to this
        trainer's network/optimizer if it was constructed bare.

        AMP O3: the fp8 delayed-scaling amax histories live outside the
        network/optimizer state dicts, so attaching also registers them
        as manager extra-state — each save persists
        :meth:`fp8_state_dict` in the commit manifest and a restore
        feeds it back through :meth:`load_fp8_state`, making O3
        crash-resumes bit-identical instead of cold-starting scales at
        1. Works in either order with ``restore_or_init()`` (a restore
        that already happened applies at registration)."""
        manager.bind(self.network, self.optimizer)
        self._checkpoint = manager
        if hasattr(manager, "register_extra_state"):
            manager.register_extra_state(
                "fp8", self.fp8_state_dict, self.load_fp8_state
            )
        return manager

    def attach_sentinel(self, sentinel):
        """Wire a ``training.AnomalySentinel`` into the step loop: the
        sentinel sees every step's loss as a lazy device ref and walks
        its skip/rollback/abort policy ladder on NaN/inf or loss
        spikes. The sentinel's checkpoint manager (when it has one) is
        how rollback restores; attach_checkpoint wires saving
        separately."""
        sentinel.bind(self)
        self._sentinel = sentinel
        return sentinel

    def attach_watchdog(self, watchdog):
        """Wire a ``training.TrainWatchdog``: each step's dispatch is
        timestamped (one host clock read) so a wedged step or a
        straggling peer fires before the job dies silently."""
        self._watchdog = watchdog
        return watchdog

    # -------------------------------------------------- sentinel snapshots
    def _memory_snapshot(self):
        """One pre-step on-device snapshot for the sentinel's
        skip-step rung: ``jnp.copy`` per leaf (donation-immune, the
        checkpoint snapshot discipline — no host sync), plus the small
        host-side counters the restore must rewind. The RNG stream is
        deliberately NOT captured: a skipped batch keeps the key
        sequence advancing."""
        snap = {
            "params": {
                k: jnp.copy(p.value)
                for k, p in self.network.named_parameters()
            },
            "buffers": {
                k: jnp.copy(b.value)
                for k, b in self.network.named_buffers()
            },
            "opt_state": {
                k: tuple(jnp.copy(a) for a in accs)
                for k, accs in self._gather_opt_state({}).items()
            },
            "fp8": (
                {k: jnp.copy(v) for k, v in self._fp8_state.items()}
                if self._fp8_state is not None else None
            ),
            "step_count": self.optimizer._step_count,
        }
        if self.scaler is not None:
            sc = self.scaler
            snap["scaler"] = (sc._scale, sc._good_steps, sc._bad_steps)
        return snap

    def _restore_memory_snapshot(self, snap):
        """Undo the step(s) since ``snap`` was taken (skip-step)."""
        lookup = dict(self.network.named_parameters())
        for k, v in snap["params"].items():
            lookup[k].value = v
        self.network.load_functional_state(buffers=snap["buffers"])
        self._scatter_opt_state(snap["opt_state"])
        if snap["fp8"] is not None:
            self._fp8_state = dict(snap["fp8"])
        self.optimizer._step_count = snap["step_count"]
        if self.scaler is not None and "scaler" in snap:
            (self.scaler._scale, self.scaler._good_steps,
             self.scaler._bad_steps) = snap["scaler"]

    def fp8_state_dict(self):
        """The AMP O3 delayed-scaling state as host numpy arrays
        ({site/operand: amax history}), for persisting next to a
        checkpoint. Empty dict when O3 is off or not yet discovered."""
        import numpy as _np

        if self._fp8_state is None:
            return {}
        return {k: _np.asarray(v) for k, v in self._fp8_state.items()}

    def load_fp8_state(self, state):
        """Restore delayed-scaling histories saved by
        :meth:`fp8_state_dict` (keys must match the model's matmul
        sites — same architecture, same call order)."""
        if not state:
            return
        self._fp8_state = {
            k: jnp.asarray(v, jnp.float32) for k, v in state.items()
        }

    @staticmethod
    def _normalize_scaler(scaler):
        """A disabled GradScaler is the same as no scaler (shared with
        callers that need to compare against self.scaler)."""
        if scaler is not None and getattr(scaler, "_enable", True):
            return scaler
        return None

    # ------------------------------------------------------------ opt state
    def _gather_opt_state(self, params):
        opt = self.optimizer
        state = {}
        if self._kind in (opt_mod.Adam, opt_mod.AdamW, opt_mod.Lamb):
            for k, p in self.network.named_parameters():
                state[k] = (
                    opt._acc(p, "moment1"),
                    opt._acc(p, "moment2"),
                )
        elif self._kind is opt_mod.Momentum:
            for k, p in self.network.named_parameters():
                state[k] = (opt._acc(p, "velocity"),)
        else:  # SGD
            for k in self._param_names:
                state[k] = ()
        return state

    def _scatter_opt_state(self, state):
        opt = self.optimizer
        names = {k: p for k, p in self.network.named_parameters()}
        for k, accs in state.items():
            p = names[k]
            if self._kind in (opt_mod.Adam, opt_mod.AdamW, opt_mod.Lamb):
                opt._set_acc(p, "moment1", accs[0])
                opt._set_acc(p, "moment2", accs[1])
            elif self._kind is opt_mod.Momentum:
                opt._set_acc(p, "velocity", accs[0])

    def _forward_traced(self, inputs):
        """Network invocation inside the traced step (hook: the pipeline
        trainer overrides this to run the stacked-stage shard_map
        schedule instead of the sequential forward)."""
        if self._converted_forward is None:
            return self.network(*(Tensor(v) for v in inputs))
        # temporary swap so Layer.__call__ hooks still run around the
        # dy2static-converted body; restored even if tracing throws
        d = self.network.__dict__
        had_own = "forward" in d
        prev = d.get("forward")
        d["forward"] = self._converted_forward
        try:
            return self.network(*(Tensor(v) for v in inputs))
        finally:
            if had_own:
                d["forward"] = prev
            else:
                d.pop("forward", None)

    # ----------------------------------------------------------- pure step
    def _build(self):
        network = self.network
        loss_fn = self.loss_fn
        opt = self.optimizer
        kind = self._kind
        amp_level = self.amp_level
        amp_dtype = self.amp_dtype

        clip = opt._grad_clip
        from ..optimizer.clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue

        # id -> structured name, built once (7B-scale param trees: O(n))
        name_of = {id(p): k for k, p in network.named_parameters()}
        wd_coeffs, lr_mults = {}, {}
        decay_fun = getattr(opt, "_apply_decay_fun", None)
        for group, p in opt._all_params():
            name = name_of[id(p)]
            if decay_fun is not None and not decay_fun(p.name or ""):
                # eager AdamW parity: the exclusion only suppresses the
                # optimizer-level weight_decay; a per-param regularizer or
                # group-level weight_decay still applies
                wd_backup = opt._weight_decay
                opt._weight_decay = 0.0
                try:
                    coeff, l1 = opt._decay_value(group, p)
                finally:
                    opt._weight_decay = wd_backup
            else:
                coeff, l1 = opt._decay_value(group, p)
            if l1 == "l1":
                raise NotImplementedError(
                    "CompiledTrainStep does not support L1Decay "
                    f"(parameter {name!r}); use the eager optimizer path"
                )
            wd_coeffs[name] = float(coeff)
            lr_mults[name] = float(
                group.get("learning_rate", 1.0)
            ) * float(p.optimize_attr.get("learning_rate", 1.0))

        hyper = {}
        if kind in (opt_mod.Adam, opt_mod.AdamW, opt_mod.Lamb):
            hyper = dict(beta1=opt._beta1, beta2=opt._beta2, eps=opt._eps)
        elif kind is opt_mod.Momentum:
            hyper = dict(mu=opt._momentum, nesterov=opt._nesterov)

        def loss_of(params, buffers, rng, inputs, labels,
                    fp8_state=None):
            network.load_functional_state(params, buffers)
            if amp_level in ("O1", "O2", "O3"):
                from ..amp import auto_cast

                # O3 keeps O1's bf16/fp32 op split for everything that
                # is NOT a matmul; the matmuls themselves are routed to
                # fp8 by the context below
                cm = auto_cast(
                    True, level="O1" if amp_level == "O3" else amp_level,
                    dtype=amp_dtype,
                )
            else:
                import contextlib

                cm = contextlib.nullcontext()
            if amp_level == "O3":
                from ..amp import fp8 as fp8_mod

                fp8_cm = fp8_mod.fp8_autocast(fp8_state)
            else:
                import contextlib

                fp8_cm = contextlib.nullcontext()
            with tape.trace_scope(), tape.no_grad(), \
                    random_mod.key_scope(rng), cm, fp8_cm as fp8_ctx:
                network.train()
                out = self._forward_traced(inputs)
                outs = out if isinstance(out, (list, tuple)) else [out]
                loss = loss_fn(*(list(outs) + [Tensor(v) for v in labels]))
            new_buffers = {k: b.value for k, b in network.named_buffers()}
            out_vals = tuple(o.value for o in outs)
            if fp8_ctx is not None:
                # delayed-scaling histories ride the step like buffers:
                # in as carried state, out updated with this step's
                # amaxes (device arrays end to end — no host sync)
                self._fp8_bytes_saved = fp8_ctx.weight_bytes_saved
                new_fp8 = fp8_ctx.new_state
            else:
                new_fp8 = None
            return loss.value.astype(jnp.float32), (
                new_buffers, out_vals, new_fp8,
            )

        self._loss_of = loss_of

        # ZeRO stage-2/3 (group_sharded): constrain grads to the sharded
        # layout; XLA realizes the reduce-scatter + sharded-update pattern
        grad_placements = getattr(opt, "_grad_placements", None) or {}

        # layout-policy memory levers: stamp the policy's optimizer-state
        # (and master-param) shardings on the step outputs so the lowered
        # module carries them and the write-back keeps them steady-state.
        # The default tp-pp-dp policy produces NO pins — the step stays
        # byte-identical to the pre-policy trainer.
        from ..parallel import mesh as mesh_mod

        pol = self._layout_policy
        policy_state_pins, policy_param_pins = {}, {}
        if mesh_mod.mesh_defined() and (
            pol.pp_shard_optimizer_state or pol.pp_shard_master_params
        ):
            for k, p in network.named_parameters():
                sh = pol.optimizer_state_sharding(p.value)
                if sh is not None:
                    policy_state_pins[k] = sh
                sh = pol.master_param_sharding(p.value)
                if sh is not None:
                    policy_param_pins[k] = sh

        scaler = self.scaler

        def step(params, opt_state, buffers, lr, t, rng, inputs, labels,
                 scale=None, good=None, bad=None, fp8_state=None):
            if scaler is not None:
                def scaled_loss_of(params, buffers, rng, inputs, labels):
                    loss, aux = loss_of(params, buffers, rng, inputs,
                                        labels, fp8_state=fp8_state)
                    return loss * scale, (aux, loss)

                (
                    (_, ((new_buffers, out_vals, new_fp8), loss)),
                    grads,
                ) = jax.value_and_grad(scaled_loss_of, has_aux=True)(
                    params, buffers, rng, inputs, labels
                )
                inv = (1.0 / scale).astype(jnp.float32)
                grads = jax.tree_util.tree_map(
                    lambda g: (g.astype(jnp.float32) * inv).astype(g.dtype),
                    grads,
                )
                finite = jnp.all(jnp.asarray([
                    jnp.all(jnp.isfinite(g.astype(jnp.float32)))
                    for g in jax.tree_util.tree_leaves(grads)
                ]))
            else:
                (loss, (new_buffers, out_vals, new_fp8)), grads = \
                    jax.value_and_grad(loss_of, has_aux=True)(
                        params, buffers, rng, inputs, labels,
                        fp8_state,
                    )
                finite = None

            if grad_placements:
                grads = {
                    k: (
                        jax.lax.with_sharding_constraint(
                            g, grad_placements[k]
                        )
                        if k in grad_placements
                        else g
                    )
                    for k, g in grads.items()
                }

            # gradient clipping (global-norm path fused into the step)
            if isinstance(clip, ClipGradByGlobalNorm):
                sq = sum(
                    jnp.sum(jnp.square(g.astype(jnp.float32)))
                    for g in jax.tree_util.tree_leaves(grads)
                )
                gnorm = jnp.sqrt(sq)
                # NOT named `scale`: that closure variable is the fp16
                # loss scale, which the scaler update below reads
                clip_coef = jnp.minimum(
                    1.0, clip.clip_norm / jnp.maximum(gnorm, 1e-12)
                )
                grads = jax.tree_util.tree_map(
                    lambda g: (g.astype(jnp.float32) * clip_coef).astype(
                        g.dtype
                    ),
                    grads,
                )
            elif isinstance(clip, ClipGradByNorm):
                def _pn(g):
                    n = jnp.sqrt(jnp.sum(jnp.square(g)))
                    s = jnp.where(n > clip.clip_norm, clip.clip_norm / jnp.maximum(n, 1e-12), 1.0)
                    return g * s

                grads = jax.tree_util.tree_map(_pn, grads)
            elif isinstance(clip, ClipGradByValue):
                grads = jax.tree_util.tree_map(
                    lambda g: jnp.clip(g, clip.min, clip.max), grads
                )

            new_params, new_state = {}, {}
            for k in params:
                p, g = params[k], grads[k]
                wd = wd_coeffs.get(k, 0.0)
                plr = lr * lr_mults.get(k, 1.0)
                if kind is opt_mod.SGD:
                    if wd:
                        g = g + wd * p
                    new_params[k] = opt_mod._sgd_update.__wrapped__(p, g, plr)
                    new_state[k] = ()
                elif kind is opt_mod.Momentum:
                    if wd:
                        g = g + wd * p
                    (vel,) = opt_state[k]
                    np_, v2 = opt_mod._momentum_update.__wrapped__(
                        p, vel, g, plr, hyper["mu"], hyper["nesterov"]
                    )
                    new_params[k] = np_
                    new_state[k] = (v2,)
                elif kind in (opt_mod.Adam, opt_mod.AdamW):
                    m, v = opt_state[k]
                    decoupled = kind is opt_mod.AdamW
                    np_, m2, v2 = opt_mod._adam_update.__wrapped__(
                        p, m, v, g, plr, hyper["beta1"], hyper["beta2"],
                        hyper["eps"], t, wd, decoupled,
                    )
                    new_params[k] = np_
                    new_state[k] = (m2, v2)
                else:  # Lamb
                    m, v = opt_state[k]
                    np_, m2, v2 = opt_mod._lamb_update.__wrapped__(
                        p, m, v, g, plr, hyper["beta1"], hyper["beta2"],
                        hyper["eps"], t, opt._lamb_wd,
                    )
                    new_params[k] = np_
                    new_state[k] = (m2, v2)

            if policy_state_pins or policy_param_pins:
                new_state = {
                    k: tuple(
                        (
                            jax.lax.with_sharding_constraint(
                                a, policy_state_pins[k]
                            )
                            if k in policy_state_pins and a.ndim
                            else a
                        )
                        for a in accs
                    )
                    for k, accs in new_state.items()
                }
                new_params = {
                    k: (
                        jax.lax.with_sharding_constraint(
                            v, policy_param_pins[k]
                        )
                        if k in policy_param_pins
                        else v
                    )
                    for k, v in new_params.items()
                }

            if scaler is not None:
                # non-finite grads: keep params/state, adjust the scale
                keep = lambda new, old: jax.tree_util.tree_map(
                    lambda a, b: jnp.where(finite, a, b), new, old
                )
                new_params = keep(new_params, params)
                new_state = keep(new_state, opt_state)
                good2 = jnp.where(finite, good + 1, 0)
                bad2 = jnp.where(finite, 0, bad + 1)
                if scaler._dynamic:
                    scale2 = jnp.where(
                        good2 >= scaler._incr_every,
                        scale * scaler._incr_ratio, scale,
                    )
                    good2 = jnp.where(
                        good2 >= scaler._incr_every, 0, good2
                    )
                    # decrease floors at 1.0 (eager update() parity):
                    # an unfloored scale decays to 0 and 1/scale poisons
                    # every later step
                    scale2 = jnp.where(
                        bad2 >= scaler._decr_every,
                        jnp.maximum(scale * scaler._decr_ratio, 1.0),
                        scale2,
                    )
                    bad2 = jnp.where(bad2 >= scaler._decr_every, 0, bad2)
                else:
                    scale2 = scale  # static-scale mode: never adjusted
                return (new_params, new_state, new_buffers, loss, out_vals,
                        new_fp8, scale2, good2, bad2, finite)
            return (new_params, new_state, new_buffers, loss, out_vals,
                    new_fp8)

        self._step = step

    @staticmethod
    def _explicit_sharding(x):
        """A sharding worth pinning: an explicit NamedSharding on a
        multi-device mesh (ZeRO/FSDP placement invariants). Plain
        single-device placements must NOT be pinned — pinning them
        disables XLA's layout freedom and donation fast path (measured
        70x single-chip slowdown in round 2) and breaks runs whose
        inputs later live on a mesh."""
        s = getattr(x, "sharding", None)
        if isinstance(s, jax.sharding.NamedSharding) and s.mesh.size > 1:
            return s
        return None

    def _finalize_jit(self, params, opt_state, buffers):
        """Keep sharded optimizer state / FSDP params sharded across
        steps (ZeRO stages are placement invariants, not one-shot
        placements) by constraining ONLY the leaves that arrived with an
        explicit multi-device NamedSharding. Everything else is left to
        XLA's sharding propagation + donation, which preserves
        placements on the common path without the cost of output
        pinning."""
        param_pins = {
            k: self._explicit_sharding(v) for k, v in params.items()
        }
        state_pins = {
            k: tuple(self._explicit_sharding(a) for a in accs)
            for k, accs in opt_state.items()
        }
        buffer_pins = {
            k: self._explicit_sharding(v) for k, v in buffers.items()
        }
        base = self._step
        any_pin = (
            any(param_pins.values())
            or any(buffer_pins.values())
            or any(s for pins in state_pins.values() for s in pins)
        )
        if any_pin:
            def step(params, opt_state, buffers, lr, t, rng, inputs, labels,
                     *extra):
                new_params, new_state, new_buffers, loss, out_vals, *rest = \
                    base(params, opt_state, buffers, lr, t, rng, inputs,
                         labels, *extra)
                new_params = {
                    k: (
                        jax.lax.with_sharding_constraint(v, param_pins[k])
                        if param_pins.get(k) is not None
                        else v
                    )
                    for k, v in new_params.items()
                }
                new_state = {
                    k: tuple(
                        (
                            jax.lax.with_sharding_constraint(a, pin)
                            if pin is not None
                            else a
                        )
                        for a, pin in zip(accs, state_pins[k])
                    )
                    for k, accs in new_state.items()
                }
                new_buffers = {
                    k: (
                        jax.lax.with_sharding_constraint(v, buffer_pins[k])
                        if buffer_pins.get(k) is not None
                        else v
                    )
                    for k, v in new_buffers.items()
                }
                return (new_params, new_state, new_buffers, loss, out_vals,
                        *rest)
        else:
            step = base
        self._step_fn = jax.jit(step, donate_argnums=(0, 1, 2))

    def _invoke(self, *step_args):
        """Run the jitted step, translating XLA's unbounded-while reverse-AD
        limitation into an actionable paddle-level error."""
        if self._step_args_sds is None:
            # avals only — donation below frees the buffers, the
            # shapes/dtypes stay valid for memory_report()'s re-trace
            self._step_args_sds = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(
                    jnp.shape(a), jnp.result_type(a)
                ),
                step_args,
            )
        try:
            return self._step_fn(*step_args)
        except ValueError as e:
            msg = str(e)
            if "Reverse-mode differentiation" in msg and "while_loop" in msg:
                from .dy2static import Dy2StaticError

                raise Dy2StaticError(
                    "a value-dependent `while` loop inside the training "
                    "step is not reverse-differentiable on XLA. If the "
                    "loop result needs gradients, bound the loop: "
                    "paddle.static.nn.while_loop(..., maximum_trip_count="
                    "N) lowers to a fixed-length masked scan that trains; "
                    "or rewrite with a concrete Python trip count "
                    "(unrolled). Unbounded tensor-condition loops are "
                    "inference-only."
                ) from e
            raise

    def memory_report(self):
        """Donation-aware live-range HBM estimate of the compiled step
        (``analysis.memory_lint``): peak resident bytes with params/
        opt-state/buffers donated, next to the StepMeter's timing
        gauges. Re-traces the step body at the captured argument avals
        (no FLOPs, no compile); None before the first step. The trace
        swaps tracers through the imperative layers, so the network's
        concrete state is restored before returning."""
        if self._step_fn is None or self._step_args_sds is None:
            return None
        from .. import analysis
        from ..parallel import layout as layout_mod

        params = {k: p.value for k, p in self.network.named_parameters()}
        buffers = {k: b.value for k, b in self.network.named_buffers()}
        try:
            with layout_mod.use_policy(self._layout_policy):
                est = analysis.estimate_fn(
                    self._step_fn, *self._step_args_sds,
                    graph="train_step", donate_argnums=(0, 1, 2),
                )
        finally:
            self.network.load_functional_state(params, buffers)
        return est.to_dict()

    def _publish_memory_gauge(self):
        """Opt-in (``PADDLE_TPU_TRAIN_MEMORY_GAUGE=1``): publish the
        train step's estimated peak as a gauge on the first real step.
        Off by default — the re-trace costs one extra trace of the
        step body at warmup."""
        import os

        if not os.environ.get("PADDLE_TPU_TRAIN_MEMORY_GAUGE"):
            return
        rep = self.memory_report()
        if rep is None:
            return
        from .. import observability as obs

        g = obs.get_registry().gauge(
            "paddle_train_step_peak_bytes",
            help="estimated peak resident bytes of the compiled train "
                 "step (memory_lint live-range model, donation-aware)",
            unit="bytes",
        )
        g.set(float(rep["peak_bytes"]))

    def _record_telemetry(self, dt, in_vals, loss, warmup):
        """Publish one step into the process StepMeter (observability).

        Host-side only: batch geometry comes from input SHAPES and the
        loss is handed over as a device ref the meter's lazy gauge
        fetches on scrape — no sync is added to the step. The first
        call per program is reported as ``warmup`` (its wall time is
        dominated by trace+XLA compile and goes to the compile_time
        histogram, not step_time). Telemetry can never fail a train
        step."""
        try:
            from .. import observability as obs

            meter = obs.get_step_meter()
            meter.auto_configure(self.network)  # MFU from model config
            examples, tokens = obs.batch_geometry(in_vals)
            meter.observe_step(
                dt, examples=examples, tokens=tokens, loss=loss,
                warmup=warmup,
            )
            if self.amp_level == "O3" and self._fp8_bytes_saved:
                # analytic per-step HBM delta of routing the matmul
                # weights through fp8 (counted at trace time)
                meter.note_fp8_bytes_saved(self._fp8_bytes_saved)
            if warmup:
                self._publish_memory_gauge()
        except Exception:
            pass

    # ---------------------------------------------------------------- call
    def __call__(self, inputs, labels):
        """One optimizer step. The trainer's captured layout policy is
        ACTIVE for the whole call: policy-routed code that resolves the
        policy at trace time (ParallelCrossEntropy / causal_lm_loss,
        sep-ring attention, Optimizer._acc accumulator births) sees the
        trainer's layout even when the step runs outside the
        use_policy context the trainer was constructed in — otherwise
        the layout would apply half-way (pinned state, default loss)."""
        from ..parallel import layout as layout_mod

        with layout_mod.use_policy(self._layout_policy):
            return self._step_once(inputs, labels)

    def _step_once(self, inputs, labels):
        _t0 = time.perf_counter()
        _warmup = self._step_fn is None  # first call traces + compiles
        if self._step_fn is None:
            self._build()
        params = {k: p.value for k, p in self.network.named_parameters()}
        for k, v in params.items():
            if isinstance(v, jax.ShapeDtypeStruct):
                raise RuntimeError(
                    f"parameter {k!r} is still abstract (built under "
                    "paddle.LazyGuard): call network.materialize() or "
                    "load a checkpoint before training. Abstract "
                    "networks can only be lowered (jit(...).lower), "
                    "not executed."
                )
        step_next = self.optimizer._step_count + 1
        if self._sentinel is not None:
            # pre-step snapshot for the skip rung — BEFORE the gather
            # below hands these arrays to the donating jit
            self._sentinel.before_step(step_next)
        if self._watchdog is not None:
            self._watchdog.note_dispatch(step_next)
        # chaos seams: a blocking callback here is the deterministic
        # wedged step, an os._exit callback the deterministic dead rank
        _chaos.poke("train.step_begin", step=step_next)
        buffers = {k: b.value for k, b in self.network.named_buffers()}
        opt_state = self._gather_opt_state(params)
        if self._step_fn is None:  # (compile happens on first _invoke)
            self._finalize_jit(params, opt_state, buffers)
        self.optimizer._step_count += 1
        lr = jnp.float32(self.optimizer.get_lr())
        t = jnp.float32(self.optimizer._step_count)
        rng = random_mod.next_key()
        in_vals = tuple(_unwrap(x) for x in inputs)
        lbl_vals = tuple(_unwrap(y) for y in labels)
        if self.amp_level == "O3" and self._fp8_state is None:
            # discover the fp8 delayed-scaling state STRUCTURE with an
            # abstract pass (jax.eval_shape — no compile, no FLOPs), so
            # the compiled step's signature includes the carried
            # histories from its one and only trace
            shapes = jax.eval_shape(
                lambda p, b, r, i, l: self._loss_of(
                    p, b, r, i, l, None
                )[1][2],
                params, buffers, rng, in_vals, lbl_vals,
            )
            self._fp8_state = jax.tree_util.tree_map(
                lambda s: jnp.zeros(s.shape, s.dtype), shapes
            )
            # eval_shape left abstract tracers in the Layer objects
            self.network.load_functional_state(params, buffers)
        if self.scaler is not None:
            sc = self.scaler
            (new_params, new_state, new_buffers, loss, out_vals,
             new_fp8, scale2, good2, bad2, finite) = self._invoke(
                params, opt_state, buffers, lr, t, rng, in_vals, lbl_vals,
                jnp.float32(sc._scale), jnp.int32(sc._good_steps),
                jnp.int32(sc._bad_steps), self._fp8_state,
            )
            sc._scale = float(scale2)
            sc._good_steps = int(good2)
            sc._bad_steps = int(bad2)
            sc._found_inf = not bool(finite)
            if sc._found_inf:
                # the update was skipped: bias-correction time must not
                # advance (reference optimizers see no step either)
                self.optimizer._step_count -= 1
        else:
            (new_params, new_state, new_buffers, loss, out_vals,
             new_fp8) = self._invoke(
                params, opt_state, buffers, lr, t, rng, in_vals,
                lbl_vals, None, None, None, self._fp8_state,
            )
        if new_fp8 is not None:
            # device arrays in, device arrays out — the histories never
            # touch the host (the step stays sync-free)
            self._fp8_state = new_fp8
        # chaos value seam: a callback returning float("nan") is the
        # deterministic anomaly the sentinel ladder must recover from
        injected = _chaos.poke_value(
            "train.loss", loss, step=self.optimizer._step_count
        )
        if injected is not loss:
            loss = jnp.asarray(injected, jnp.float32)
        # write back: imperative objects stay the source of truth
        lookup = dict(self.network.named_parameters())
        for k, v in new_params.items():
            lookup[k].value = v
        self.network.load_functional_state(buffers=new_buffers)
        self._scatter_opt_state(new_state)
        self._record_telemetry(time.perf_counter() - _t0, in_vals, loss,
                               _warmup)
        action = None
        if self._sentinel is not None:
            # may raise RollbackAndReplay (state already restored to
            # the last commit) or TrainingAborted (bundle dumped);
            # returns the Action when the ladder chose skip-step
            action = self._sentinel.after_step(
                self.optimizer._step_count, loss
            )
        if self._checkpoint is not None and action is None:
            # after write-back AND the sentinel verdict: a step the
            # sentinel just undid must not be checkpointed. Policy
            # check + on-device snapshot only — the write happens on
            # the manager's background thread
            self._checkpoint.on_step(self.optimizer._step_count)
        return Tensor(loss), [Tensor(o) for o in out_vals]
