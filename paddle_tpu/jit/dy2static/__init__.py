"""dy2static: dynamic-to-static control-flow conversion.

Reference parity: python/paddle/jit/dy2static/* + python/paddle/jit/sot/*
(~80k LoC upstream — unverified, mount empty). TPU-first redesign: the
reference translates Python control flow into static-graph cond/while ops
executed by its interpreter; here the targets are XLA's native structured
control flow (``lax.cond`` / ``lax.while_loop`` / ``lax.switch``), which
compile into HLO conditionals the TPU executes without host round trips.

Two cooperating layers:

1. **Runtime converters** (this module): ``convert_ifelse`` /
   ``convert_while`` / ``convert_and`` etc. Each inspects its predicate at
   call time — a concrete value keeps plain Python semantics (the eager
   path and non-tensor conditions are untouched); a traced value routes to
   the corresponding ``lax`` primitive with Tensor un/re-wrapping.
2. **AST pass** (``transformer.py``): rewrites Python ``if``/``while`` on
   potentially-traced predicates into calls to the runtime converters.
   ``to_static`` applies it automatically; statements it cannot convert
   (early ``return``, ``break``/``continue``) are left as-is and produce
   an actionable error from ``Tensor.__bool__`` if their predicate turns
   out to be traced.

The public ``paddle.static.nn.cond/while_loop/switch_case`` ops are thin
wrappers over the same converters (static/nn/__init__.py).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor

__all__ = [
    "Dy2StaticError", "UndefinedVar", "convert_to_static",
    "convert_ifelse", "convert_while", "convert_and", "convert_or",
    "convert_not", "cond_impl", "while_impl", "switch_impl",
]


class Dy2StaticError(Exception):
    """Raised when dynamic Python control flow cannot be staticized."""


class UndefinedVar:
    """Placeholder for a name not yet bound when captured (reference:
    jit/dy2static/utils.py UndefinedVar). Any use raises a NameError with
    the original variable name."""

    __slots__ = ("name",)

    def __init__(self, name):
        object.__setattr__(self, "name", name)

    def _raise(self):
        raise NameError(
            f"local variable '{self.name}' referenced before assignment "
            "(inside to_static-converted control flow)"
        )

    def __getattr__(self, item):
        object.__getattribute__(self, "name")  # keep pickling sane
        self._raise()

    def __bool__(self):
        self._raise()

    def __call__(self, *a, **k):
        self._raise()

    def __iter__(self):
        self._raise()

    def __repr__(self):
        return f"UndefinedVar({object.__getattribute__(self, 'name')!r})"


def ld(thunk, name):
    """Capture the current value of a possibly-unbound local."""
    try:
        return thunk()
    except (NameError, UnboundLocalError):
        return UndefinedVar(name)


def false_():
    """Early-exit flag initializer (the AST rewriter's `__es_*` flags).

    A jnp bool scalar, NOT Python ``False``: converted branches/loops
    assign a traced bool into the flag, and an XLA loop carry / cond
    output must keep one structure — a Python-bool static would flip to
    a tensor leaf mid-trace and fail the template check."""
    return jnp.asarray(False)


def true_():
    """Traced-compatible ``True`` for early-exit flag assignment."""
    return jnp.asarray(True)


def int0_():
    """Pre-loop init for a for-index snapshot slot: int32 to match the
    traced range counter (convert_for_range's start_t)."""
    return jnp.asarray(0, jnp.int32)


def index_snap(i):
    """Snapshot a loop index into a carried slot at a deferred-return
    site. Always an int32 jnp scalar, so unrolled (python-int index) and
    scanned (traced index) loops produce one carry structure. (int32:
    matches convert_for_range's counter; ranges past 2**31 would
    truncate — far beyond any unrollable/scannable loop.)"""
    return jnp.asarray(_raw(i)).astype(jnp.int32)


def index_unsnap(v):
    """Inverse of index_snap for the concrete path: a non-traced scalar
    goes back to a Python int so deferred `return i` keeps plain-Python
    types; tracers pass through untouched."""
    raw = _raw(v)
    if isinstance(raw, jax.core.Tracer):
        return v
    try:
        return int(raw)
    except TypeError:  # pragma: no cover - non-scalar snapshots
        return v


def _raw(x):
    return x.value if isinstance(x, Tensor) else x


def _is_traced(x):
    return isinstance(_raw(x), jax.core.Tracer)


def _is_arraylike(x):
    if isinstance(x, Tensor):
        return True
    return isinstance(x, (jax.Array, np.ndarray)) or isinstance(
        x, jax.core.Tracer
    )


def _pred_bool(pred):
    v = _raw(pred)
    return bool(np.asarray(v))


def _is_structure_error(e):
    """Does this TypeError come from jax's cond/while structure checks
    (as opposed to a genuine user bug raised inside a branch)?"""
    msg = str(e)
    return any(
        key in msg
        for key in (
            "pytree", "type structure", "carry input and carry output",
            "must have equal types", "output and input",
        )
    )


# --------------------------------------------------------------- if / cond
def _split_outputs(out, where):
    """Flatten a branch output into (array_leaves, rebuild_template).

    Tensors/arrays become lax-carried leaves; everything else (ints, None,
    UndefinedVar, strings, ...) is recorded as a static in the template.
    The template is a nested structure mirroring ``out`` where array
    positions hold the marker ``_ARR`` and statics hold themselves.
    """
    leaves = []

    def walk(o):
        if isinstance(o, Tensor):
            leaves.append(o.value)
            return _ARR_T
        if _is_arraylike(o):
            leaves.append(jnp.asarray(o))
            return _ARR
        if isinstance(o, (list, tuple)):
            return type(o)(walk(v) for v in o)
        if isinstance(o, dict):
            return {k: walk(v) for k, v in sorted(o.items())}
        return o

    template = walk(out)
    return leaves, template


_ARR = object()    # raw-array position
_ARR_T = object()  # Tensor position


def _rebuild_outputs(template, leaves):
    it = iter(leaves)

    def walk(t):
        if t is _ARR_T:
            return Tensor(next(it))
        if t is _ARR:
            return next(it)
        if isinstance(t, (list, tuple)):
            return type(t)(walk(v) for v in t)
        if isinstance(t, dict):
            return {k: walk(v) for k, v in t.items()}
        return t

    return walk(template)


def _templates_equal(a, b):
    if a is _ARR_T or a is _ARR:
        return b is _ARR_T or b is _ARR
    if isinstance(a, (list, tuple)):
        return (
            type(a) is type(b) and len(a) == len(b)
            and all(_templates_equal(x, y) for x, y in zip(a, b))
        )
    if isinstance(a, dict):
        return (
            isinstance(b, dict) and a.keys() == b.keys()
            and all(_templates_equal(a[k], b[k]) for k in a)
        )
    if isinstance(a, UndefinedVar) or isinstance(b, UndefinedVar):
        return isinstance(a, UndefinedVar) and isinstance(b, UndefinedVar)
    try:
        return bool(a == b)
    except Exception:
        return a is b


def _describe_template(t):
    if t is _ARR_T or t is _ARR:
        return "Tensor"
    if isinstance(t, UndefinedVar):
        return f"<undefined '{object.__getattribute__(t, 'name')}'>"
    if isinstance(t, (list, tuple)):
        return type(t)(_describe_template(v) for v in t)
    if isinstance(t, dict):
        return {k: _describe_template(v) for k, v in t.items()}
    return repr(t)


def _branch_mismatch_error(where, names, recorded):
    hint = ""
    if names:
        hint = f" (captured variables, in order: {names})"
    return Dy2StaticError(
        f"{where}: branches of a Tensor-dependent `if` must produce "
        "matching outputs — every assigned variable must be a Tensor "
        f"(or an equal static) in BOTH branches{hint}. "
        f"true branch: {_describe_template(recorded['t'])}; "
        f"false branch: {_describe_template(recorded['f'])}. "
        "Assign the variable in both branches, or compute it with "
        "paddle.where instead."
    )


def cond_impl(pred, true_thunk, false_thunk, names=None, where="cond"):
    """Core of paddle.static.nn.cond and the AST if-conversion.

    ``true_thunk``/``false_thunk``: nullary callables returning an
    arbitrary Tensor pytree. Concrete predicate -> run the taken branch
    only (plain Python semantics, tape-autograd intact). Traced predicate
    -> ``lax.cond``: XLA compiles both branches, executes one; jax
    reverse-mode differentiates it natively inside whole-step jit.
    """
    if not _is_traced(pred):
        return (true_thunk if _pred_bool(pred) else false_thunk)()

    recorded = {}

    def make(fn, tag):
        def inner(_):
            leaves, template = _split_outputs(fn(), where)
            recorded[tag] = template
            return tuple(jnp.asarray(v) for v in leaves)

        return inner

    try:
        leaves = jax.lax.cond(
            jnp.asarray(_raw(pred)).astype(bool).reshape(()),
            make(true_thunk, "t"), make(false_thunk, "f"), (),
        )
    except TypeError as e:
        if not _is_structure_error(e):
            raise  # a genuine user bug inside a branch: keep its traceback
        if (
            "t" in recorded and "f" in recorded
            and not _templates_equal(recorded["t"], recorded["f"])
        ):
            # leaf-count mismatches (a var Tensor in one branch,
            # unassigned/static in the other) fail inside lax.cond before
            # our own template check runs — surface the paddle-level
            # explanation, not jax's pytree dump
            raise _branch_mismatch_error(where, names, recorded) from e
        raise Dy2StaticError(
            f"{where}: the two branches of a Tensor-condition must "
            "return matching shapes/dtypes; jax reported: " + str(e)
        ) from e
    if not _templates_equal(recorded["t"], recorded["f"]):
        raise _branch_mismatch_error(where, names, recorded)
    return _rebuild_outputs(recorded["t"], leaves)


def convert_ifelse(pred, true_fn, false_fn, args, names):
    """AST-generated `if` conversion: branch fns take the captured args
    (current values of every name either branch assigns) and return the
    tuple of their final values."""
    out = cond_impl(
        pred, lambda: true_fn(*args), lambda: false_fn(*args),
        names=names, where="to_static if",
    )
    return tuple(out)


# ------------------------------------------------------------------- while
def while_impl(cond_fn, body_fn, loop_vars, names=None, where="while_loop",
               maximum_trip_count=None):
    """Core of paddle.static.nn.while_loop and the AST while-conversion.

    ``loop_vars`` is a flat tuple; ``cond_fn(*vars) -> scalar`` and
    ``body_fn(*vars) -> tuple(vars)``. Tensor loop state rides the
    ``lax.while_loop`` carry; non-tensor loop vars must stay invariant
    (XLA loops have a fixed carry signature).

    ``maximum_trip_count``: when given, the traced loop lowers to a
    masked ``lax.scan`` of that fixed length (iterations after the
    condition goes false are no-ops) — reverse-mode differentiable, which
    ``lax.while_loop`` is not. This is how a value-dependent loop trains
    on TPU; the unbounded form is inference-only under reverse AD.
    """
    loop_vars = tuple(loop_vars)
    first = cond_fn(*loop_vars)
    if not _is_traced(first):
        # concrete condition: plain Python loop — eager semantics (tape
        # autograd intact), and under an outer trace the body simply
        # unrolls (traced loop STATE is fine; only a traced CONDITION
        # needs lax.while_loop)
        out = loop_vars
        step = 0
        while True:
            if maximum_trip_count is not None and step >= int(
                maximum_trip_count
            ):
                break  # same bound as the traced masked-scan lowering
            pred = cond_fn(*out) if step else first
            if _is_traced(pred):
                raise Dy2StaticError(
                    f"{where}: the loop condition became value-dependent "
                    f"after {step} iteration(s) (it started concrete). "
                    "Initialize the state the condition reads as a "
                    "Tensor so the whole loop compiles via "
                    "lax.while_loop, or keep the condition on concrete "
                    "Python values."
                )
            if not _pred_bool(pred):
                break
            out = tuple(body_fn(*out))
            if len(out) != len(loop_vars):
                raise Dy2StaticError(
                    f"{where}: body must return as many values as "
                    f"loop_vars ({len(loop_vars)}), got {len(out)}"
                )
            step += 1
        return out

    init_leaves, template = _split_outputs(loop_vars, where)

    def rebuild(leaves):
        return _rebuild_outputs(template, leaves)

    def cond_wrapped(leaves):
        res = cond_fn(*rebuild(leaves))
        return jnp.asarray(_raw(res)).astype(bool).reshape(())

    def body_wrapped(leaves):
        out = tuple(body_fn(*rebuild(leaves)))
        new_leaves, new_template = _split_outputs(out, where)
        if not _templates_equal(new_template, template):
            hint = f" (loop variables, in order: {names})" if names else ""
            raise Dy2StaticError(
                f"{where}: a Tensor-dependent `while` must keep its loop "
                f"variables' structure fixed{hint}: every loop variable "
                "must stay a Tensor (same shape/dtype) across iterations. "
                f"before: {_describe_template(template)}; after one step: "
                f"{_describe_template(new_template)}."
            )
        return tuple(jnp.asarray(v) for v in new_leaves)

    init = tuple(jnp.asarray(v) for v in init_leaves)
    try:
        if maximum_trip_count is not None:
            # masked scan: fixed length, iterations past the condition
            # are identity — reverse-differentiable on TPU. The identity
            # arm is a real lax.cond branch, NOT a jnp.where over an
            # unconditionally-executed body: with where, a body op that
            # is NaN on the frozen carry (sqrt/log/division one step past
            # the exit) poisons reverse-mode through 0*NaN; with cond the
            # stale body does not run. Batching note: under jax.vmap,
            # cond lowers to a select over both arms, but the
            # transpose routes zero cotangents to the unselected arm
            # WITHOUT reintroducing 0*NaN — vmapped grads of a bounded
            # loop stay finite (pinned by test_dy2static::
            # test_while_loop_masked_scan_vmap_grads_stay_finite; if a
            # jax upgrade ever breaks that test, this guarantee is the
            # thing that regressed).
            def scan_body(carry, _):
                leaves, done = carry
                cont = jnp.logical_and(cond_wrapped(leaves), ~done)
                kept = jax.lax.cond(
                    cont,
                    lambda ls: tuple(body_wrapped(ls)),
                    lambda ls: ls,
                    leaves,
                )
                return (kept, ~cont), None

            (final, _), _ = jax.lax.scan(
                scan_body, (init, jnp.asarray(False)), None,
                length=int(maximum_trip_count),
            )
        else:
            final = jax.lax.while_loop(cond_wrapped, body_wrapped, init)
    except TypeError as e:
        if not _is_structure_error(e):
            raise  # a genuine user bug inside cond/body: keep its traceback
        raise Dy2StaticError(
            f"{where}: loop body changed the shape/dtype of a loop "
            "variable (XLA loop carries are fixed); jax reported: "
            + str(e)
        ) from e
    return tuple(_rebuild_outputs(template, final))


def convert_while(cond_fn, body_fn, loop_vars, names):
    return while_impl(
        cond_fn, body_fn, loop_vars, names=names, where="to_static while"
    )


def convert_for_range(range_args, body_fn, prior_i, loop_vars, names):
    """AST-generated ``for i in range(...)`` conversion. ``body_fn`` takes
    (i, *loop_vars) and returns the updated loop_vars tuple; ``prior_i``
    is the loop variable's binding before the statement (or UndefinedVar)
    — Python keeps it when the range is empty. Concrete bounds keep the
    plain Python loop (unrolled under trace); a traced bound lowers to
    lax.while_loop via while_impl with the counter as an extra carried
    variable."""
    if len(range_args) == 1:
        start, stop, step = 0, range_args[0], 1
    elif len(range_args) == 2:
        (start, stop), step = range_args, 1
    else:
        start, stop, step = range_args

    if not any(_is_traced(v) for v in (start, stop, step)):
        out = tuple(loop_vars)
        i = prior_i  # empty range: the prior binding survives (Python)
        for i in range(_as_index(start), _as_index(stop),
                       _as_index(step)):
            out = tuple(body_fn(i, *out))
        return (i,) + out

    if _is_traced(step):
        raise Dy2StaticError(
            "to_static for-range: a Tensor step is not supported (XLA "
            "loops need a sign-static step to know the loop direction); "
            "make the step a Python int, or rewrite with "
            "paddle.static.nn.while_loop"
        )
    for bname, b in (("start", start), ("stop", stop)):
        if _is_traced(b) and not jnp.issubdtype(
            jnp.asarray(_raw(b)).dtype, jnp.integer
        ):
            raise Dy2StaticError(
                f"to_static for-range: the {bname} bound is a "
                f"{jnp.asarray(_raw(b)).dtype} Tensor; range() bounds "
                "must be integers (cast with .astype('int32'))"
            )
    step_i = _as_index(step)
    if step_i == 0:
        raise ValueError("range() arg 3 must not be zero")
    if not _is_traced(start):
        start = _as_index(start)  # float start: TypeError (range parity)

    def cond_fn(i, *vars_):
        iv = jnp.asarray(_raw(i))
        sv = jnp.asarray(_raw(stop))
        return (iv < sv) if step_i > 0 else (iv > sv)

    def body_wrap(i, *vars_):
        new_vars = tuple(body_fn(i, *vars_))
        return (Tensor(jnp.asarray(_raw(i)) + step_i),) + new_vars

    start_t = (
        start if isinstance(start, Tensor)
        else Tensor(jnp.asarray(start, jnp.int32))
    )
    out = while_impl(
        cond_fn, body_wrap, (start_t,) + tuple(loop_vars),
        names=tuple(names or ()),
        where="to_static for-range",
    )
    # out[0] is the counter AFTER the last increment; Python's post-loop
    # binding is one step back. A zero-iteration traced loop cannot keep
    # "unbound" semantics inside a trace — clamp to start (documented
    # divergence; avoids e.g. a silent -1 index downstream).
    final = jnp.asarray(_raw(out[0])) - step_i
    start_v = jnp.asarray(_raw(start_t))
    i_last = Tensor(
        jnp.maximum(final, start_v) if step_i > 0
        else jnp.minimum(final, start_v)
    )
    return (i_last,) + tuple(out[1:])


def _as_py(v):
    if isinstance(v, Tensor):
        return np.asarray(v.value).item()
    return v


def _as_index(v):
    """range()-parity bound conversion: floats raise like Python."""
    p = _as_py(v)
    if isinstance(p, float) or (
        hasattr(p, "dtype") and not np.issubdtype(p.dtype, np.integer)
    ):
        raise TypeError(
            f"'{type(p).__name__}' object cannot be interpreted as an "
            "integer (range() bound in to_static-converted loop)"
        )
    return int(p)


# ------------------------------------------------------------------ switch
def switch_impl(branch_index, branch_fns, default=None, where="switch_case"):
    """paddle.static.nn.switch_case semantics over ``lax.switch``.

    ``branch_fns``: list of callables, or list of (int_index, callable)
    pairs. Out-of-range / unmatched index runs ``default`` (required when
    indices are sparse and the predicate is traced).
    """
    pairs = []
    if isinstance(branch_fns, dict):
        branch_fns = list(branch_fns.items())
    for i, item in enumerate(branch_fns):
        if isinstance(item, (tuple, list)) and len(item) == 2 and callable(
            item[1]
        ):
            pairs.append((int(item[0]), item[1]))
        elif callable(item):
            pairs.append((i, item))
        else:
            raise TypeError(
                f"{where}: branch_fns entries must be callables or "
                f"(index, callable) pairs, got {type(item).__name__}"
            )
    indices = [p[0] for p in pairs]
    if len(set(indices)) != len(indices):
        raise ValueError(f"{where}: duplicate branch indices {indices}")

    if not _is_traced(branch_index):
        idx = int(np.asarray(_raw(branch_index)))
        for k, fn in pairs:
            if k == idx:
                return fn()
        if default is None:
            # paddle: the largest-index branch doubles as the default
            return max(pairs, key=lambda p: p[0])[1]()
        return default()

    if default is None:
        # paddle: the largest-index branch doubles as the default
        default = max(pairs, key=lambda p: p[0])[1]

    idx_val = jnp.asarray(_raw(branch_index)).astype(jnp.int32).reshape(())
    # map the user index to a dense position; unmatched -> default slot
    positions = jnp.full((), len(pairs), jnp.int32)
    for pos, (k, _) in enumerate(pairs):
        positions = jnp.where(idx_val == k, jnp.int32(pos), positions)

    recorded = {}

    def make(fn, tag):
        def inner(_):
            leaves, template = _split_outputs(fn(), where)
            recorded[tag] = template
            return tuple(jnp.asarray(v) for v in leaves)

        return inner

    fns = [make(fn, i) for i, (_, fn) in enumerate(pairs)]
    fns.append(make(default, "default"))
    leaves = jax.lax.switch(positions, fns, ())
    templates = list(recorded.values())
    for t in templates[1:]:
        if not _templates_equal(templates[0], t):
            raise Dy2StaticError(
                f"{where}: all branches (and the default) must return "
                "matching Tensor structures under a traced index; got "
                + "; ".join(
                    str(_describe_template(t)) for t in templates
                )
            )
    return _rebuild_outputs(templates[0], leaves)


# --------------------------------------------------- short-circuit bool ops
def convert_and(lhs, rhs_thunk):
    if not _is_traced(lhs):
        if isinstance(lhs, Tensor):
            lhs = _pred_bool(lhs)
        return rhs_thunk() if lhs else lhs
    from ...ops.logic import logical_and

    lhs_t = lhs if isinstance(lhs, Tensor) else Tensor(jnp.asarray(lhs))
    rhs = rhs_thunk()
    rhs_t = rhs if isinstance(rhs, Tensor) else Tensor(jnp.asarray(rhs))
    return logical_and(lhs_t.astype("bool"), rhs_t.astype("bool"))


def convert_or(lhs, rhs_thunk):
    if not _is_traced(lhs):
        if isinstance(lhs, Tensor):
            lhs = _pred_bool(lhs)
        return lhs if lhs else rhs_thunk()
    from ...ops.logic import logical_or

    lhs_t = lhs if isinstance(lhs, Tensor) else Tensor(jnp.asarray(lhs))
    rhs = rhs_thunk()
    rhs_t = rhs if isinstance(rhs, Tensor) else Tensor(jnp.asarray(rhs))
    return logical_or(lhs_t.astype("bool"), rhs_t.astype("bool"))


def convert_not(x):
    if not _is_traced(x):
        return not (_pred_bool(x) if isinstance(x, Tensor) else x)
    from ...ops.logic import logical_not

    x_t = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    return logical_not(x_t.astype("bool"))


from .transformer import convert_to_static  # noqa: E402  (cycle-free)
