"""AST pass: rewrite Python `if`/`while` into runtime-converter calls.

Reference parity: python/paddle/jit/dy2static/transformers/* (IfElse,
Loop, LogicalOp, Return, BreakContinue transformers — unverified, mount
empty). Scope is the subset that maps onto XLA structured control flow:

* ``if``/``elif``/``else`` -> ``_jst.convert_ifelse``.
* ``while`` (no ``else`` clause) -> ``_jst.convert_while``.
* ``for i in range(...)`` -> ``_jst.convert_for_range``.
* ``return`` / ``break`` / ``continue`` inside the above: the
  ``_EarlyExitRewriter`` pre-pass else-merges guard returns and
  flag-gates the rest (see its docstring), after which the statements
  above convert normally. Early returns along traced paths must produce
  matching structures (a ``lax.cond`` requirement); mismatches raise
  the converters' structure errors.
* ``and`` / ``or`` / ``not`` inside converted predicates
  -> ``_jst.convert_and/or/not`` (Python short-circuit semantics are
  preserved for concrete operands; traced operands become logical ops).

Still outside the subset: ``yield``, exits escaping ``try``, loop
``else`` clauses, non-range ``for`` iterables. These are left untouched:
with a concrete predicate they run as plain Python; with a traced
predicate, ``Tensor.__bool__`` raises an actionable error naming the
rewrite options (this module's skip-list is mirrored in that message).

The conversion is value-semantics-preserving for names: every name a
branch/body assigns is captured before the statement (``_jst.ld``: value
or ``UndefinedVar``), threaded through the generated branch functions as
parameters, and rebound afterwards from the returned tuple — names the
taken path does not assign keep their prior value. Assignments to
attributes/subscripts inside branches execute as ordinary side effects
(valid on the concrete path; on the traced path they are outside the
convertible subset, like the reference's dy2static).
"""
from __future__ import annotations

import ast
import functools
import textwrap
import types
import warnings


# ------------------------------------------------------------ name analysis
class _AssignedNames(ast.NodeVisitor):
    """Names bound by statements in a block (not descending into nested
    function/class scopes, where bindings are local to that scope)."""

    def __init__(self):
        self.names = set()

    def _target(self, t):
        if isinstance(t, ast.Name):
            self.names.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._target(e)
        elif isinstance(t, ast.Starred):
            self._target(t.value)
        # Attribute/Subscript targets are side effects, not name bindings

    def visit_Assign(self, node):
        for t in node.targets:
            self._target(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._target(node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        self._target(node.target)
        self.generic_visit(node)

    def visit_For(self, node):
        self._target(node.target)
        self.generic_visit(node)

    def visit_withitem(self, node):
        if node.optional_vars is not None:
            self._target(node.optional_vars)
        self.generic_visit(node)

    def visit_NamedExpr(self, node):  # walrus
        self._target(node.target)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        self.names.add(node.name)  # the def itself binds its name

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        self.names.add(node.name)

    def visit_Lambda(self, node):
        pass

    def visit_Import(self, node):
        for a in node.names:
            self.names.add((a.asname or a.name).split(".")[0])

    visit_ImportFrom = visit_Import


def _assigned_names(stmts):
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    return v.names


class _CtrlFlow(ast.NodeVisitor):
    """Detect return/break/continue/yield that would escape the block."""

    def __init__(self):
        self.found = False
        self._loop_depth = 0

    def visit_Return(self, node):
        self.found = True

    def visit_Yield(self, node):
        self.found = True

    visit_YieldFrom = visit_Yield

    def visit_Break(self, node):
        if self._loop_depth == 0:
            self.found = True

    visit_Continue = visit_Break

    def _loop(self, node):
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_For = _loop
    visit_While = _loop

    def visit_FunctionDef(self, node):
        pass  # its own scope

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef


def _has_escaping_ctrl(stmts):
    v = _CtrlFlow()
    for s in stmts:
        v.visit(s)
    return v.found


# ------------------------------------------------------- early-exit rewrite
def _find_in_block(stmts, types, stop_loops=False):
    """Nodes of ``types`` within a statement list, not descending into
    nested function/class scopes; ``stop_loops`` additionally stops at
    nested loops (for finding THIS loop's break/continue)."""
    found = []

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, n):
            if isinstance(n, types):
                found.append(n)  # the def itself counts; its body is
            # a separate scope — never descended

        visit_AsyncFunctionDef = visit_FunctionDef
        visit_Lambda = visit_FunctionDef
        visit_ClassDef = visit_FunctionDef

        def visit_While(self, n):
            if isinstance(n, types):
                found.append(n)
            if not stop_loops:
                self.generic_visit(n)

        visit_For = visit_While

        def generic_visit(self, n):
            if isinstance(n, types):
                found.append(n)
            super().generic_visit(n)

    v = V()
    for s in stmts:
        v.visit(s)
    return found


def _terminates(stmts):
    """Every path through the list ends in return/break/continue/raise
    (so code after it is unreachable)."""
    if not stmts:
        return False
    last = stmts[-1]
    if isinstance(last, (ast.Return, ast.Break, ast.Continue, ast.Raise)):
        return True
    if isinstance(last, ast.If):
        return _terminates(last.body) and _terminates(last.orelse)
    return False


class _ExitCtx:
    """Per-block rewrite context. ``defer`` is set inside loop bodies
    whose returns are DEFERRED: a ``return expr`` there only raises a
    site flag (the gating freezes every carried name afterwards), and
    ``expr`` is evaluated by post-loop dispatch ifs — the only way a
    return value of unknown structure can cross an XLA loop carry.
    ``index_name``/``index_snap`` snapshot a for-loop's index into a
    carried slot, since the post-loop index holds the range end, not the
    fire-time value."""

    __slots__ = ("ret_active", "brk", "cont", "defer", "index_name",
                 "index_snap")

    def __init__(self, ret_active, brk=None, cont=None, defer=None,
                 index_name=None, index_snap=None):
        self.ret_active = ret_active
        self.brk = brk
        self.cont = cont
        self.defer = defer
        self.index_name = index_name
        self.index_snap = index_snap


class _RenameLoad(ast.NodeTransformer):
    """Rename loads of ``old`` to ``new`` — but NOT inside scopes that
    rebind ``old`` (lambda params, comprehension targets, nested defs),
    where the inner binding shadows the loop index."""

    def __init__(self, old, new):
        self.old, self.new = old, new

    def visit_Name(self, node):
        if node.id == self.old and isinstance(node.ctx, ast.Load):
            return _name(self.new)
        return node

    def visit_Lambda(self, node):
        a = node.args
        params = (
            [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
            + ([a.vararg.arg] if a.vararg else [])
            + ([a.kwarg.arg] if a.kwarg else [])
        )
        if self.old in params:
            return node  # shadowed: leave the lambda body alone
        self.generic_visit(node)
        return node

    def visit_FunctionDef(self, node):
        return node  # own scope; loads there resolve at call time

    visit_AsyncFunctionDef = visit_FunctionDef

    def _comp(self, node):
        bound = set()
        for gen in node.generators:
            for n in ast.walk(gen.target):
                if isinstance(n, ast.Name):
                    bound.add(n.id)
        if self.old in bound:
            return node  # comprehension rebinds the index: shadowed
        self.generic_visit(node)
        return node

    visit_ListComp = _comp
    visit_SetComp = _comp
    visit_DictComp = _comp
    visit_GeneratorExp = _comp


class _EarlyExitRewriter:
    """Rewrite ``return`` / ``break`` / ``continue`` inside control flow
    into bool-flag assignments + gating, the reference's
    return/break-continue transformer strategy
    (python/paddle/jit/dy2static/transformers/return_transformer.py,
    break_continue_transformer.py — unverified, mount empty), adapted to
    the XLA lowering:

    * guard-pattern returns (``if c: return a`` followed by more code)
      are ELSE-MERGED — the remainder moves into the if's else — so the
      dominant early-return shape lowers to a clean ``lax.cond`` with
      matching branch structures and no flags at all;
    * where merging can't apply (a branch only MAY return, loop bodies),
      flags gate the remainder: ``__es_ret``/``__es_retval`` for
      returns, per-loop ``__es_brk``/``__es_cont``. Flags initialize via
      ``_jst.false_()`` (a jnp bool, not Python False) so an XLA loop
      carry / cond output keeps one structure when a traced branch
      assigns into them;
    * while-conditions gain ``not (ret or brk) and ...``; a converted
      for-range keeps scanning its full range with the body gated to
      identity (correct, mildly wasteful — documented).

    The rewrite output is ordinary Python with identical semantics, so
    functions with concrete predicates behave exactly as before; the
    main transformer then converts the generated ifs/loops like any
    user-written ones. Functions with try/except around an exit, or
    generators, are left untouched (unconvertible, as before).
    """

    RET, RETVAL = "__es_ret", "__es_retval"

    def __init__(self):
        self.uid = 0
        self.changed = False

    # ----------------------------------------------------- AST snippets
    @staticmethod
    def _assign(name, value):
        return ast.Assign(targets=[_name(name, ast.Store())], value=value)

    def _set_false(self, name):
        return self._assign(
            name, ast.Call(func=_jst_attr("false_"), args=[], keywords=[])
        )

    def _set_true(self, name):
        return self._assign(
            name, ast.Call(func=_jst_attr("true_"), args=[], keywords=[])
        )

    @staticmethod
    def _not_flags(flags):
        test = (
            _name(flags[0]) if len(flags) == 1
            else ast.BoolOp(op=ast.Or(), values=[_name(f) for f in flags])
        )
        return ast.UnaryOp(op=ast.Not(), operand=test)

    def _gate(self, flags, body):
        return ast.If(test=self._not_flags(flags), body=body, orelse=[])

    # ------------------------------------------------------- detection
    def _exit_kinds(self, stmts, ctx):
        """(has_ret, has_brk, has_cont) for the ORIGINAL (pre-rewrite)
        statements, relative to the active context."""
        has_ret = (
            (ctx.ret_active or ctx.defer is not None)
            and bool(_find_in_block(stmts, ast.Return))
        )
        has_brk = bool(
            ctx.brk and _find_in_block(stmts, ast.Break, stop_loops=True)
        )
        has_cont = bool(
            ctx.cont
            and _find_in_block(stmts, ast.Continue, stop_loops=True)
        )
        return has_ret, has_brk, has_cont

    def _ret_flags(self, ctx, d0):
        """Names that signal 'a return fired' in this context: the
        deferred site flags created since ``d0``, or the function-level
        RET flag."""
        if ctx.defer is not None:
            return [f for f, _ in ctx.defer[d0:]]
        return [self.RET] if ctx.ret_active else []

    # ------------------------------------------------------ processing
    def process_block(self, stmts, ctx):
        out = []
        for i, s in enumerate(stmts):
            rest = stmts[i + 1:]
            if isinstance(s, ast.Return) and ctx.defer is not None:
                # deferred: raise a site flag (plus the for-index
                # snapshot); the post-loop dispatch evaluates the value
                self.changed = True
                self.uid += 1
                flag = f"__es_lret{self.uid}"
                expr = s.value
                if expr is not None and ctx.index_name:
                    expr = _RenameLoad(
                        ctx.index_name, ctx.index_snap
                    ).visit(expr)
                    out.append(self._assign(
                        ctx.index_snap,
                        ast.Call(func=_jst_attr("index_snap"),
                                 args=[_name(ctx.index_name)],
                                 keywords=[]),
                    ))
                ctx.defer.append((flag, expr))
                out.append(self._set_true(flag))
                return out
            if isinstance(s, ast.Return) and ctx.ret_active:
                self.changed = True
                out.append(self._assign(
                    self.RETVAL, s.value or ast.Constant(None)
                ))
                out.append(self._set_true(self.RET))
                return out  # anything after a return is dead
            if isinstance(s, ast.Break) and ctx.brk:
                self.changed = True
                out.append(self._set_true(ctx.brk))
                return out
            if isinstance(s, ast.Continue) and ctx.cont:
                self.changed = True
                out.append(self._set_true(ctx.cont))
                return out
            if isinstance(s, ast.If):
                has_ret, has_brk, has_cont = self._exit_kinds([s], ctx)
                any_exit = has_ret or has_brk or has_cont
                d0 = len(ctx.defer) if ctx.defer is not None else 0
                body_t, else_t = _terminates(s.body), _terminates(s.orelse)
                if any_exit and body_t and else_t:
                    s.body = self.process_block(s.body, ctx)
                    s.orelse = self.process_block(s.orelse, ctx)
                    out.append(s)
                    return out  # rest dead
                if any_exit and body_t and rest:
                    # else-merge: remainder becomes the else branch
                    self.changed = True
                    s.body = self.process_block(s.body, ctx)
                    s.orelse = self.process_block(
                        list(s.orelse) + rest, ctx
                    )
                    out.append(s)
                    return out
                if any_exit and else_t and s.orelse and rest:
                    self.changed = True
                    s.orelse = self.process_block(s.orelse, ctx)
                    s.body = self.process_block(list(s.body) + rest, ctx)
                    out.append(s)
                    return out
                # general: recurse, then gate the remainder on the flags
                s.body = self.process_block(s.body, ctx)
                s.orelse = self.process_block(s.orelse, ctx)
                out.append(s)
                flags = (
                    (self._ret_flags(ctx, d0) if has_ret else [])
                    + ([ctx.brk] if has_brk else [])
                    + ([ctx.cont] if has_cont else [])
                )
                if flags and rest:
                    self.changed = True
                    out.append(self._gate(
                        flags, self.process_block(rest, ctx)
                    ))
                    return out
                continue
            if isinstance(s, (ast.While, ast.For)):
                processed, post = self._process_loop(s, ctx)
                out.extend(processed)
                if post:
                    # post-loop dispatch returns: hand them + the rest
                    # back to CPS (they else-merge like user returns)
                    out.extend(self.process_block(list(post) + rest, ctx))
                    return out
                continue
            if isinstance(s, ast.Match):
                has_ret, has_brk, has_cont = self._exit_kinds([s], ctx)
                d0 = len(ctx.defer) if ctx.defer is not None else 0
                for c in s.cases:
                    c.body = self.process_block(c.body, ctx)
                out.append(s)
                flags = (
                    (self._ret_flags(ctx, d0) if has_ret else [])
                    + ([ctx.brk] if has_brk else [])
                    + ([ctx.cont] if has_cont else [])
                )
                if flags and rest:
                    self.changed = True
                    out.append(self._gate(
                        flags, self.process_block(rest, ctx)
                    ))
                    return out
                continue
            if isinstance(s, ast.Try):
                # the rewrite() guard guarantees no exit escapes a try;
                # loops wholly inside still get their own treatment
                neutral = _ExitCtx(False)
                s.body = self.process_block(s.body, neutral)
                for h in s.handlers:
                    h.body = self.process_block(h.body, neutral)
                s.orelse = self.process_block(s.orelse, neutral)
                s.finalbody = self.process_block(s.finalbody, neutral)
                out.append(s)
                continue
            if isinstance(s, ast.With):
                has_ret, has_brk, has_cont = self._exit_kinds(
                    s.body, ctx
                )
                d0 = len(ctx.defer) if ctx.defer is not None else 0
                s.body = self.process_block(s.body, ctx)
                out.append(s)
                flags = (
                    (self._ret_flags(ctx, d0) if has_ret else [])
                    + ([ctx.brk] if has_brk else [])
                    + ([ctx.cont] if has_cont else [])
                )
                if flags and rest:
                    self.changed = True
                    out.append(self._gate(
                        flags, self.process_block(rest, ctx)
                    ))
                    return out
                continue
            out.append(s)
        return out

    @staticmethod
    def _is_range_for(loop):
        return (
            isinstance(loop, ast.For)
            and isinstance(loop.target, ast.Name)
            and isinstance(loop.iter, ast.Call)
            and isinstance(loop.iter.func, ast.Name)
            and loop.iter.func.id == "range"
            and not loop.iter.keywords
            and 1 <= len(loop.iter.args) <= 3
            and not any(
                isinstance(a, ast.Starred) for a in loop.iter.args
            )
        )

    def _process_loop(self, loop, ctx):
        """Returns (statements-to-emit, post-dispatch-stmts). The post
        list holds UNPROCESSED ``if <site-flag>: return <expr>`` nodes
        for the caller's CPS to fold into the remainder."""
        if (
            not (isinstance(loop, ast.While) or self._is_range_for(loop))
            or loop.orelse
        ):
            # non-range iterable or loop-else clause: the flag rewrite
            # would change semantics (a gated-to-identity `for` still
            # drains its iterator; a flag-exited while always runs its
            # else) — leave this loop's own exits as real Python
            # statements and only recurse for nested structures
            neutral = _ExitCtx(False)
            loop.body = self.process_block(loop.body, neutral)
            loop.orelse = self.process_block(loop.orelse, neutral)
            return [loop], []
        defer_ret = (
            (ctx.ret_active or ctx.defer is not None)
            and bool(_find_in_block(loop.body, ast.Return))
        )
        has_brk = bool(
            _find_in_block(loop.body, ast.Break, stop_loops=True)
        )
        has_cont = bool(
            _find_in_block(loop.body, ast.Continue, stop_loops=True)
        )
        pre = []
        brk = cont = snap = None
        sites = []
        if has_brk:
            self.uid += 1
            brk = f"__es_brk{self.uid}"
            pre.append(self._set_false(brk))
            self.changed = True
        if has_cont:
            self.uid += 1
            cont = f"__es_cont{self.uid}"
            # pre-loop init as well as the per-iteration reset below: an
            # XLA loop carry needs the flag bound (same structure) BEFORE
            # the first iteration
            pre.append(self._set_false(cont))
            self.changed = True
        index_name = None
        if defer_ret and isinstance(loop, ast.For) and isinstance(
            loop.target, ast.Name
        ):
            self.uid += 1
            snap = f"__es_i{self.uid}"
            index_name = loop.target.id
            pre.append(self._assign(
                snap,
                ast.Call(func=_jst_attr("int0_"), args=[], keywords=[]),
            ))
        inner = _ExitCtx(
            ctx.ret_active, brk=brk, cont=cont,
            defer=sites if defer_ret else None,
            index_name=index_name, index_snap=snap,
        )
        new_body = self.process_block(loop.body, inner)
        for flag, _ in sites:
            pre.append(self._set_false(flag))
        if has_cont:
            # continue-flag resets at the top of every iteration
            new_body = [self._set_false(cont)] + new_body
        exit_flags = [f for f, _ in sites] + ([brk] if brk else [])
        if isinstance(loop, ast.While):
            if exit_flags:
                loop.test = ast.BoolOp(
                    op=ast.And(),
                    values=[self._not_flags(exit_flags), loop.test],
                )
            loop.body = new_body
        else:  # For: the converted range-scan runs all iterations; the
            #   body is gated to identity once an exit flag fires
            loop.body = (
                [self._gate(exit_flags, new_body)] if exit_flags
                else new_body
            )
        post = []
        if snap is not None and sites:
            # restore a concrete snapshot to a Python int before the
            # dispatch evaluates the deferred expression (the carried
            # slot is a jnp scalar; plain-Python semantics promise an
            # int return on the concrete path). Tracers pass through.
            post.append(self._assign(
                snap,
                ast.Call(func=_jst_attr("index_unsnap"),
                         args=[_name(snap)], keywords=[]),
            ))
        post += [
            ast.If(
                test=_name(flag),
                body=[ast.Return(value=expr or ast.Constant(None))],
                orelse=[],
            )
            for flag, expr in sites
        ]
        return pre + [loop], post

    # ----------------------------------------------------------- entry
    def rewrite(self, fdef):
        """Rewrite fdef.body in place. Returns True if anything changed."""
        body = fdef.body
        if _find_in_block(body, (ast.Yield, ast.YieldFrom)):
            return False  # generators stay unconvertible
        for t in _find_in_block(body, ast.Try):
            inner = (
                t.body
                + [s for h in t.handlers for s in h.body]
                + t.orelse
                + t.finalbody
            )
            # only exits that ESCAPE the try disable the rewrite: any
            # return, or a break/continue not consumed by a loop inside
            # the try (stop_loops skips loop-internal ones)
            if _find_in_block(inner, ast.Return) or _find_in_block(
                inner, (ast.Break, ast.Continue), stop_loops=True
            ):
                return False  # exit through try/except: leave untouched
        all_rets = _find_in_block(body, ast.Return)
        top_rets = [s for s in body if isinstance(s, ast.Return)]
        nested_ret = len(all_rets) > len(top_rets)
        loops_active = any(
            _find_in_block(l.body, (ast.Break, ast.Continue),
                           stop_loops=True)
            for l in _find_in_block(body, (ast.While, ast.For))
        )
        if not nested_ret and not loops_active:
            return False
        ctx = _ExitCtx(ret_active=nested_ret)
        new_body = self.process_block(body, ctx)
        if nested_ret:
            prologue = [
                self._set_false(self.RET),
                self._assign(self.RETVAL, ast.Constant(None)),
            ]
            if not _terminates(new_body):
                new_body = new_body + [
                    ast.Return(value=_name(self.RETVAL))
                ]
            new_body = prologue + new_body
        fdef.body = new_body
        return self.changed


# ------------------------------------------------------------- AST building
def _name(id_, ctx=None):
    return ast.Name(id=id_, ctx=ctx or ast.Load())


def _jst_attr(fn_name):
    return ast.Attribute(value=_name("_jst"), attr=fn_name, ctx=ast.Load())


def _capture_call(var):
    """_jst.ld(lambda: var, 'var')"""
    lam = ast.Lambda(
        args=ast.arguments(
            posonlyargs=[], args=[], vararg=None, kwonlyargs=[],
            kw_defaults=[], kwarg=None, defaults=[],
        ),
        body=_name(var),
    )
    return ast.Call(
        func=_jst_attr("ld"), args=[lam, ast.Constant(var)], keywords=[]
    )


def _make_branch_fn(fname, params, body, result_names):
    """def fname(p1, p2, ...): <body>; return (r1, r2, ...)"""
    ret = ast.Return(
        value=ast.Tuple(
            elts=[_name(n) for n in result_names], ctx=ast.Load()
        )
    )
    return ast.FunctionDef(
        name=fname,
        args=ast.arguments(
            posonlyargs=[],
            args=[ast.arg(arg=p, annotation=None) for p in params],
            vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
            defaults=[],
        ),
        body=list(body) + [ret],
        decorator_list=[],
        returns=None,
    )


class _PredicateBoolOps(ast.NodeTransformer):
    """Inside converted predicates: and/or/not -> runtime converters."""

    def visit_BoolOp(self, node):
        self.generic_visit(node)
        op = "convert_and" if isinstance(node.op, ast.And) else "convert_or"
        expr = node.values[0]
        for rhs in node.values[1:]:
            thunk = ast.Lambda(
                args=ast.arguments(
                    posonlyargs=[], args=[], vararg=None, kwonlyargs=[],
                    kw_defaults=[], kwarg=None, defaults=[],
                ),
                body=rhs,
            )
            expr = ast.Call(
                func=_jst_attr(op), args=[expr, thunk], keywords=[]
            )
        return expr

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.Call(
                func=_jst_attr("convert_not"), args=[node.operand],
                keywords=[],
            )
        return node

    def visit_Lambda(self, node):
        return node  # don't rewrite inside nested lambdas


def _convert_predicate(test):
    return _PredicateBoolOps().visit(test)


class _SuperRewriter(ast.NodeTransformer):
    """Zero-arg ``super()`` relies on the ``__class__`` compiler cell,
    which only exists for defs inside a class body; the regenerated def is
    module-level, so rewrite to the explicit two-arg form. ``__class__``
    itself is provided via the snapshotted closure (the original method's
    implicit cell)."""

    def __init__(self, self_name):
        self.self_name = self_name

    def visit_Call(self, node):
        self.generic_visit(node)
        if (
            isinstance(node.func, ast.Name) and node.func.id == "super"
            and not node.args and not node.keywords and self.self_name
        ):
            node.args = [_name("__class__"), _name(self.self_name)]
        return node


def _loads(stmts):
    """Conservative liveness: every name that COULD be read by these
    statements (plain loads, aug-assign reads, global/nonlocal, loads
    inside nested scopes — closures count)."""
    names = set()
    for s in stmts:
        for n in ast.walk(s):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                names.add(n.id)
            elif isinstance(n, ast.AugAssign) and isinstance(
                n.target, ast.Name
            ):
                names.add(n.target.id)
            elif isinstance(n, (ast.Global, ast.Nonlocal)):
                names.update(n.names)
    return names


class _ControlFlowTransformer:
    """Statement-list walker (NOT an ast.NodeTransformer): conversion of
    an ``if`` needs to know which of its assigned names are still live
    AFTER it — dead names are not threaded out of the generated branch
    functions, so a name bound on only one path (the early-exit
    rewriter's else-merge produces these constantly) doesn't force a
    cond structure mismatch when nothing ever reads it again."""

    def __init__(self):
        self.counter = 0
        self.changed = False

    def _uid(self):
        self.counter += 1
        return self.counter

    # ------------------------------------------------------ block walk
    def process_stmts(self, stmts, live):
        """Transform a statement list; ``live`` is the set of names that
        may be read after this list ends (enclosing-scope liveness).
        Suffix-load sets are accumulated in one reverse pass (O(nodes),
        not O(n^2) re-walks of the tail per statement)."""
        n = len(stmts)
        sufs = [None] * n
        acc = set(live)
        for i in range(n - 1, -1, -1):
            sufs[i] = acc
            acc = acc | _loads([stmts[i]])
        out = []
        for i, s in enumerate(stmts):
            out.extend(self._process_stmt(s, sufs[i]))
        return out

    def _process_stmt(self, s, live):
        # Nested def/lambda/class keep their own (untransformed) scope:
        # the conversion targets the decorated function's body only,
        # like the reference's per-function transform entry.
        if isinstance(s, ast.If):
            return self._convert_if(s, live)
        if isinstance(s, ast.While):
            return self._convert_while(s, live)
        if isinstance(s, ast.For):
            return self._convert_for(s, live)
        if isinstance(s, (ast.With, ast.AsyncWith)):
            s.body = self.process_stmts(s.body, live)
            return [s]
        if isinstance(s, ast.Match):
            for c in s.cases:
                c.body = self.process_stmts(c.body, live)
            return [s]
        if isinstance(s, ast.AsyncFor):
            s.body = self.process_stmts(
                s.body, live | _loads([s]) | _assigned_names(s.body)
            )
            s.orelse = self.process_stmts(s.orelse, live)
            return [s]
        if isinstance(s, ast.Try):
            ctx = live | _loads(
                [x for h in s.handlers for x in h.body]
                + s.orelse + s.finalbody
            )
            s.body = self.process_stmts(s.body, ctx)
            for h in s.handlers:
                h.body = self.process_stmts(h.body, live)
            s.orelse = self.process_stmts(s.orelse, live)
            s.finalbody = self.process_stmts(s.finalbody, live)
            return [s]
        return [s]

    # ------------------------------------------------------ conversions
    def _convert_if(self, node, live):
        node.body = self.process_stmts(node.body, live)
        node.orelse = self.process_stmts(node.orelse, live)
        if _has_escaping_ctrl(node.body) or _has_escaping_ctrl(node.orelse):
            return [node]
        assigned = sorted(
            n
            for n in _assigned_names(node.body) | _assigned_names(node.orelse)
            if not n.startswith("__dy2st_")  # inner conversions' machinery
        )
        # thread OUT only names still live after the if: a name bound on
        # one path and never read again must not constrain the cond's
        # output structure (else-merged early returns rely on this)
        result = [n for n in assigned if n in live]
        if not result:
            return [node]  # side-effect-only / dead-out if: leave as Python
        uid = self._uid()
        self.changed = True
        true_name, false_name = f"__dy2st_true_{uid}", f"__dy2st_false_{uid}"
        out_name = f"__dy2st_out_{uid}"
        true_fn = _make_branch_fn(true_name, assigned, node.body, result)
        false_fn = _make_branch_fn(
            false_name, assigned, node.orelse or [ast.Pass()], result
        )
        call = ast.Assign(
            targets=[_name(out_name, ast.Store())],
            value=ast.Call(
                func=_jst_attr("convert_ifelse"),
                args=[
                    _convert_predicate(node.test),
                    _name(true_name), _name(false_name),
                    ast.Tuple(
                        elts=[_capture_call(n) for n in assigned],
                        ctx=ast.Load(),
                    ),
                    ast.Constant(tuple(result)),
                ],
                keywords=[],
            ),
        )
        unpack = ast.Assign(
            targets=[ast.Tuple(
                elts=[_name(n, ast.Store()) for n in result],
                ctx=ast.Store(),
            )],
            value=_name(out_name),
        )
        return [true_fn, false_fn, call, unpack]

    def _convert_for(self, node, live):
        # loop-carried names are live at the end of the body (the next
        # iteration reads them), as are the loop's own test/iter loads
        body_live = live | _loads([node]) | _assigned_names(node.body)
        node.body = self.process_stmts(node.body, body_live)
        node.orelse = self.process_stmts(node.orelse, live)
        # only `for <name> in range(...)` without else/ctrl-flow converts;
        # other iterables stay Python (eager semantics / unrolled in trace)
        if (
            node.orelse
            or _has_escaping_ctrl(node.body)
            or not isinstance(node.target, ast.Name)
            or not isinstance(node.iter, ast.Call)
            or not isinstance(node.iter.func, ast.Name)
            or node.iter.func.id != "range"
            or node.iter.keywords
            or not (1 <= len(node.iter.args) <= 3)
            or any(isinstance(a, ast.Starred) for a in node.iter.args)
        ):
            return [node]
        loop_name = node.target.id
        body_assigned = _assigned_names(node.body)
        if loop_name in body_assigned:
            # the body rebinds the loop variable: Python's post-loop
            # binding would be the body's value, which the conversion
            # cannot reproduce — leave as plain Python
            return [node]
        assigned = sorted(
            n for n in body_assigned if not n.startswith("__dy2st_")
        )
        if not assigned:
            return [node]
        uid = self._uid()
        self.changed = True
        body_name = f"__dy2st_forbody_{uid}"
        out_name = f"__dy2st_out_{uid}"
        body_fn = _make_branch_fn(
            body_name, [loop_name] + assigned, node.body, assigned
        )
        call = ast.Assign(
            targets=[_name(out_name, ast.Store())],
            value=ast.Call(
                func=_jst_attr("convert_for_range"),
                args=[
                    ast.Tuple(elts=list(node.iter.args), ctx=ast.Load()),
                    _name(body_name),
                    _capture_call(loop_name),  # prior binding (empty range)
                    ast.Tuple(
                        elts=[_capture_call(n) for n in assigned],
                        ctx=ast.Load(),
                    ),
                    ast.Constant((loop_name,) + tuple(assigned)),
                ],
                keywords=[],
            ),
        )
        # the loop variable stays bound after the loop (Python semantics)
        unpack = ast.Assign(
            targets=[ast.Tuple(
                elts=[_name(n, ast.Store())
                      for n in [loop_name] + assigned],
                ctx=ast.Store(),
            )],
            value=_name(out_name),
        )
        return [body_fn, call, unpack]

    def _convert_while(self, node, live):
        body_live = live | _loads([node]) | _assigned_names(node.body)
        node.body = self.process_stmts(node.body, body_live)
        node.orelse = self.process_stmts(node.orelse, live)
        if node.orelse or _has_escaping_ctrl(node.body):
            return [node]
        assigned = sorted(
            n for n in _assigned_names(node.body)
            if not n.startswith("__dy2st_")
        )
        if not assigned:
            return [node]
        uid = self._uid()
        self.changed = True
        cond_name, body_name = f"__dy2st_cond_{uid}", f"__dy2st_body_{uid}"
        out_name = f"__dy2st_out_{uid}"
        cond_fn = _make_branch_fn(
            cond_name, assigned, [], []
        )
        # cond returns the predicate, not a tuple
        cond_fn.body = [ast.Return(value=_convert_predicate(node.test))]
        body_fn = _make_branch_fn(body_name, assigned, node.body, assigned)
        call = ast.Assign(
            targets=[_name(out_name, ast.Store())],
            value=ast.Call(
                func=_jst_attr("convert_while"),
                args=[
                    _name(cond_name), _name(body_name),
                    ast.Tuple(
                        elts=[_capture_call(n) for n in assigned],
                        ctx=ast.Load(),
                    ),
                    ast.Constant(tuple(assigned)),
                ],
                keywords=[],
            ),
        )
        unpack = ast.Assign(
            targets=[ast.Tuple(
                elts=[_name(n, ast.Store()) for n in assigned],
                ctx=ast.Store(),
            )],
            value=_name(out_name),
        )
        return [cond_fn, body_fn, call, unpack]


# ------------------------------------------------------------ entry point
def convert_to_static(fn):
    """Apply the control-flow AST pass to ``fn``; returns the transformed
    function, or ``fn`` unchanged when there is nothing to convert or the
    source is unavailable (built-ins, lambdas, exec'd code)."""
    import inspect

    bound_self = None
    if isinstance(fn, types.MethodType):
        bound_self = fn.__self__
        fn = fn.__func__
    if not isinstance(fn, types.FunctionType):
        return fn if bound_self is None else types.MethodType(fn, bound_self)
    if hasattr(fn, "__wrapped__"):
        # a decorator wrapper: getsource would unwrap to the inner def and
        # recompiling would silently drop the decorator — leave untouched
        return fn if bound_self is None else types.MethodType(fn, bound_self)

    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, IndentationError, SyntaxError):
        return fn if bound_self is None else types.MethodType(fn, bound_self)

    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn if bound_self is None else types.MethodType(fn, bound_self)
    fdef.decorator_list = []  # avoid re-running to_static/wrappers

    # record pre-transform facts for the conversion-time warnings below
    # (after transformation the tree contains generated __dy2st_* defs)
    user_nested_defs = [
        n.name if hasattr(n, "name") else "<lambda>"
        for n in _find_in_block(
            fdef.body, (ast.FunctionDef, ast.AsyncFunctionDef)
        )
    ] + (["<lambda>"] if _find_in_block(fdef.body, ast.Lambda) else [])

    # early-exit pre-pass: return/break/continue -> else-merging +
    # flag-gating, so the control-flow conversion below sees none of them
    _EarlyExitRewriter().rewrite(fdef)

    tr = _ControlFlowTransformer()
    # block-walk the body (nested FunctionDefs stay opaque; liveness at
    # function end is empty — only the return statement's loads matter,
    # and those are inside the body list itself)
    fdef.body = tr.process_stmts(fdef.body, set())
    if not tr.changed:
        return fn if bound_self is None else types.MethodType(fn, bound_self)

    # zero-arg super() would need the class-body __class__ cell; rewrite
    # it to super(__class__, self) — __class__ arrives via the closure
    # snapshot below (the original method's implicit cell)
    self_name = None
    if fdef.args.args:
        self_name = fdef.args.args[0].arg
    elif fdef.args.posonlyargs:
        self_name = fdef.args.posonlyargs[0].arg
    _SuperRewriter(self_name).visit(fdef)
    ast.fix_missing_locations(tree)

    from . import convert_ifelse  # noqa: F401  (module import below)
    from .. import dy2static as _jst_module

    globs = dict(fn.__globals__)
    globs["_jst"] = _jst_module
    # snapshot closure cells: the regenerated code has no free variables.
    # NOTE: a snapshot — names rebound in the enclosing scope after
    # conversion keep their conversion-time values (documented limit).
    # Both limits warn at conversion time: silent wrong-capture is worse
    # than a noisy-but-actionable message.
    if user_nested_defs:
        warnings.warn(
            f"to_static: {fn.__qualname__} contains nested function(s) "
            f"{sorted(set(user_nested_defs))}; their bodies are NOT "
            "transformed — tensor-dependent if/while/for inside them "
            "will not convert (move such control flow into the "
            "decorated function, or decorate the nested function too)"
        )
    if fn.__closure__:
        snap_names = [
            n for n in fn.__code__.co_freevars if n != "__class__"
        ]
        if snap_names:
            warnings.warn(
                f"to_static: {fn.__qualname__} closes over "
                f"{snap_names}; these are SNAPSHOTTED at conversion "
                "time — rebinding them in the enclosing scope later "
                "will not be seen by the converted function (pass them "
                "as arguments for live values)"
            )
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                globs[name] = cell.cell_contents
            except ValueError:
                # empty cell (recursive / forward-referenced def): a
                # silent skip would NameError at call time — don't convert
                warnings.warn(
                    f"to_static: cannot convert {fn.__qualname__}: free "
                    f"variable '{name}' is not yet bound; falling back to "
                    "trace-only compilation"
                )
                return (
                    fn if bound_self is None
                    else types.MethodType(fn, bound_self)
                )

    try:
        code = compile(tree, f"<dy2static {fn.__qualname__}>", "exec")
        ns = {}
        exec(code, globs, ns)
        new_fn = ns[fdef.name]
    except Exception as e:  # pragma: no cover - transform must never break
        warnings.warn(
            f"to_static: control-flow conversion of {fn.__qualname__} "
            f"failed ({type(e).__name__}: {e}); falling back to "
            "trace-only compilation"
        )
        return fn if bound_self is None else types.MethodType(fn, bound_self)

    new_fn.__defaults__ = fn.__defaults__
    new_fn.__kwdefaults__ = fn.__kwdefaults__
    functools.update_wrapper(new_fn, fn)
    new_fn.__dy2static_source__ = ast.unparse(tree)
    if bound_self is not None:
        return types.MethodType(new_fn, bound_self)
    return new_fn
