"""AST pass: rewrite Python `if`/`while` into runtime-converter calls.

Reference parity: python/paddle/jit/dy2static/transformers/* (IfElse,
Loop, LogicalOp transformers — unverified, mount empty). Scope here is
deliberately the common subset that maps onto XLA structured control flow:

* ``if``/``elif``/``else`` whose branches contain no ``return`` /
  ``break`` / ``continue`` / ``yield`` -> ``_jst.convert_ifelse``.
* ``while`` (no ``else`` clause, body free of the same statements)
  -> ``_jst.convert_while``.
* ``and`` / ``or`` / ``not`` inside converted predicates
  -> ``_jst.convert_and/or/not`` (Python short-circuit semantics are
  preserved for concrete operands; traced operands become logical ops).

Anything outside this subset is left untouched: with a concrete predicate
it runs as plain Python; with a traced predicate, ``Tensor.__bool__``
raises an actionable error naming the rewrite options (this module's
skip-list is intentionally mirrored in that message).

The conversion is value-semantics-preserving for names: every name a
branch/body assigns is captured before the statement (``_jst.ld``: value
or ``UndefinedVar``), threaded through the generated branch functions as
parameters, and rebound afterwards from the returned tuple — names the
taken path does not assign keep their prior value. Assignments to
attributes/subscripts inside branches execute as ordinary side effects
(valid on the concrete path; on the traced path they are outside the
convertible subset, like the reference's dy2static).
"""
from __future__ import annotations

import ast
import functools
import textwrap
import types
import warnings


# ------------------------------------------------------------ name analysis
class _AssignedNames(ast.NodeVisitor):
    """Names bound by statements in a block (not descending into nested
    function/class scopes, where bindings are local to that scope)."""

    def __init__(self):
        self.names = set()

    def _target(self, t):
        if isinstance(t, ast.Name):
            self.names.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._target(e)
        elif isinstance(t, ast.Starred):
            self._target(t.value)
        # Attribute/Subscript targets are side effects, not name bindings

    def visit_Assign(self, node):
        for t in node.targets:
            self._target(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._target(node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        self._target(node.target)
        self.generic_visit(node)

    def visit_For(self, node):
        self._target(node.target)
        self.generic_visit(node)

    def visit_withitem(self, node):
        if node.optional_vars is not None:
            self._target(node.optional_vars)
        self.generic_visit(node)

    def visit_NamedExpr(self, node):  # walrus
        self._target(node.target)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        self.names.add(node.name)  # the def itself binds its name

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        self.names.add(node.name)

    def visit_Lambda(self, node):
        pass

    def visit_Import(self, node):
        for a in node.names:
            self.names.add((a.asname or a.name).split(".")[0])

    visit_ImportFrom = visit_Import


def _assigned_names(stmts):
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    return v.names


class _CtrlFlow(ast.NodeVisitor):
    """Detect return/break/continue/yield that would escape the block."""

    def __init__(self):
        self.found = False
        self._loop_depth = 0

    def visit_Return(self, node):
        self.found = True

    def visit_Yield(self, node):
        self.found = True

    visit_YieldFrom = visit_Yield

    def visit_Break(self, node):
        if self._loop_depth == 0:
            self.found = True

    visit_Continue = visit_Break

    def _loop(self, node):
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_For = _loop
    visit_While = _loop

    def visit_FunctionDef(self, node):
        pass  # its own scope

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef


def _has_escaping_ctrl(stmts):
    v = _CtrlFlow()
    for s in stmts:
        v.visit(s)
    return v.found


# ------------------------------------------------------------- AST building
def _name(id_, ctx=None):
    return ast.Name(id=id_, ctx=ctx or ast.Load())


def _jst_attr(fn_name):
    return ast.Attribute(value=_name("_jst"), attr=fn_name, ctx=ast.Load())


def _capture_call(var):
    """_jst.ld(lambda: var, 'var')"""
    lam = ast.Lambda(
        args=ast.arguments(
            posonlyargs=[], args=[], vararg=None, kwonlyargs=[],
            kw_defaults=[], kwarg=None, defaults=[],
        ),
        body=_name(var),
    )
    return ast.Call(
        func=_jst_attr("ld"), args=[lam, ast.Constant(var)], keywords=[]
    )


def _make_branch_fn(fname, params, body, result_names):
    """def fname(p1, p2, ...): <body>; return (r1, r2, ...)"""
    ret = ast.Return(
        value=ast.Tuple(
            elts=[_name(n) for n in result_names], ctx=ast.Load()
        )
    )
    return ast.FunctionDef(
        name=fname,
        args=ast.arguments(
            posonlyargs=[],
            args=[ast.arg(arg=p, annotation=None) for p in params],
            vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
            defaults=[],
        ),
        body=list(body) + [ret],
        decorator_list=[],
        returns=None,
    )


class _PredicateBoolOps(ast.NodeTransformer):
    """Inside converted predicates: and/or/not -> runtime converters."""

    def visit_BoolOp(self, node):
        self.generic_visit(node)
        op = "convert_and" if isinstance(node.op, ast.And) else "convert_or"
        expr = node.values[0]
        for rhs in node.values[1:]:
            thunk = ast.Lambda(
                args=ast.arguments(
                    posonlyargs=[], args=[], vararg=None, kwonlyargs=[],
                    kw_defaults=[], kwarg=None, defaults=[],
                ),
                body=rhs,
            )
            expr = ast.Call(
                func=_jst_attr(op), args=[expr, thunk], keywords=[]
            )
        return expr

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.Call(
                func=_jst_attr("convert_not"), args=[node.operand],
                keywords=[],
            )
        return node

    def visit_Lambda(self, node):
        return node  # don't rewrite inside nested lambdas


def _convert_predicate(test):
    return _PredicateBoolOps().visit(test)


class _SuperRewriter(ast.NodeTransformer):
    """Zero-arg ``super()`` relies on the ``__class__`` compiler cell,
    which only exists for defs inside a class body; the regenerated def is
    module-level, so rewrite to the explicit two-arg form. ``__class__``
    itself is provided via the snapshotted closure (the original method's
    implicit cell)."""

    def __init__(self, self_name):
        self.self_name = self_name

    def visit_Call(self, node):
        self.generic_visit(node)
        if (
            isinstance(node.func, ast.Name) and node.func.id == "super"
            and not node.args and not node.keywords and self.self_name
        ):
            node.args = [_name("__class__"), _name(self.self_name)]
        return node


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self.counter = 0
        self.changed = False

    def _uid(self):
        self.counter += 1
        return self.counter

    # Nested def/lambda/class keep their own (untransformed) scope: the
    # conversion targets the decorated function's body only, like the
    # reference's per-function transform entry.
    def visit_FunctionDef(self, node):
        return node

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef

    def visit_If(self, node):
        self.generic_visit(node)
        if _has_escaping_ctrl(node.body) or _has_escaping_ctrl(node.orelse):
            return node
        assigned = sorted(
            n
            for n in _assigned_names(node.body) | _assigned_names(node.orelse)
            if not n.startswith("__dy2st_")  # inner conversions' machinery
        )
        if not assigned:
            return node  # side-effect-only if: leave as Python
        uid = self._uid()
        self.changed = True
        true_name, false_name = f"__dy2st_true_{uid}", f"__dy2st_false_{uid}"
        out_name = f"__dy2st_out_{uid}"
        true_fn = _make_branch_fn(true_name, assigned, node.body, assigned)
        false_fn = _make_branch_fn(
            false_name, assigned, node.orelse or [ast.Pass()], assigned
        )
        call = ast.Assign(
            targets=[_name(out_name, ast.Store())],
            value=ast.Call(
                func=_jst_attr("convert_ifelse"),
                args=[
                    _convert_predicate(node.test),
                    _name(true_name), _name(false_name),
                    ast.Tuple(
                        elts=[_capture_call(n) for n in assigned],
                        ctx=ast.Load(),
                    ),
                    ast.Constant(tuple(assigned)),
                ],
                keywords=[],
            ),
        )
        unpack = ast.Assign(
            targets=[ast.Tuple(
                elts=[_name(n, ast.Store()) for n in assigned],
                ctx=ast.Store(),
            )],
            value=_name(out_name),
        )
        return [true_fn, false_fn, call, unpack]

    def visit_For(self, node):
        self.generic_visit(node)
        # only `for <name> in range(...)` without else/ctrl-flow converts;
        # other iterables stay Python (eager semantics / unrolled in trace)
        if (
            node.orelse
            or _has_escaping_ctrl(node.body)
            or not isinstance(node.target, ast.Name)
            or not isinstance(node.iter, ast.Call)
            or not isinstance(node.iter.func, ast.Name)
            or node.iter.func.id != "range"
            or node.iter.keywords
            or not (1 <= len(node.iter.args) <= 3)
            or any(isinstance(a, ast.Starred) for a in node.iter.args)
        ):
            return node
        loop_name = node.target.id
        body_assigned = _assigned_names(node.body)
        if loop_name in body_assigned:
            # the body rebinds the loop variable: Python's post-loop
            # binding would be the body's value, which the conversion
            # cannot reproduce — leave as plain Python
            return node
        assigned = sorted(
            n for n in body_assigned if not n.startswith("__dy2st_")
        )
        if not assigned:
            return node
        uid = self._uid()
        self.changed = True
        body_name = f"__dy2st_forbody_{uid}"
        out_name = f"__dy2st_out_{uid}"
        body_fn = _make_branch_fn(
            body_name, [loop_name] + assigned, node.body, assigned
        )
        call = ast.Assign(
            targets=[_name(out_name, ast.Store())],
            value=ast.Call(
                func=_jst_attr("convert_for_range"),
                args=[
                    ast.Tuple(elts=list(node.iter.args), ctx=ast.Load()),
                    _name(body_name),
                    _capture_call(loop_name),  # prior binding (empty range)
                    ast.Tuple(
                        elts=[_capture_call(n) for n in assigned],
                        ctx=ast.Load(),
                    ),
                    ast.Constant((loop_name,) + tuple(assigned)),
                ],
                keywords=[],
            ),
        )
        # the loop variable stays bound after the loop (Python semantics)
        unpack = ast.Assign(
            targets=[ast.Tuple(
                elts=[_name(n, ast.Store())
                      for n in [loop_name] + assigned],
                ctx=ast.Store(),
            )],
            value=_name(out_name),
        )
        return [body_fn, call, unpack]

    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse or _has_escaping_ctrl(node.body):
            return node
        assigned = sorted(
            n for n in _assigned_names(node.body)
            if not n.startswith("__dy2st_")
        )
        if not assigned:
            return node
        uid = self._uid()
        self.changed = True
        cond_name, body_name = f"__dy2st_cond_{uid}", f"__dy2st_body_{uid}"
        out_name = f"__dy2st_out_{uid}"
        cond_fn = _make_branch_fn(
            cond_name, assigned, [], []
        )
        # cond returns the predicate, not a tuple
        cond_fn.body = [ast.Return(value=_convert_predicate(node.test))]
        body_fn = _make_branch_fn(body_name, assigned, node.body, assigned)
        call = ast.Assign(
            targets=[_name(out_name, ast.Store())],
            value=ast.Call(
                func=_jst_attr("convert_while"),
                args=[
                    _name(cond_name), _name(body_name),
                    ast.Tuple(
                        elts=[_capture_call(n) for n in assigned],
                        ctx=ast.Load(),
                    ),
                    ast.Constant(tuple(assigned)),
                ],
                keywords=[],
            ),
        )
        unpack = ast.Assign(
            targets=[ast.Tuple(
                elts=[_name(n, ast.Store()) for n in assigned],
                ctx=ast.Store(),
            )],
            value=_name(out_name),
        )
        return [cond_fn, body_fn, call, unpack]


# ------------------------------------------------------------ entry point
def convert_to_static(fn):
    """Apply the control-flow AST pass to ``fn``; returns the transformed
    function, or ``fn`` unchanged when there is nothing to convert or the
    source is unavailable (built-ins, lambdas, exec'd code)."""
    import inspect

    bound_self = None
    if isinstance(fn, types.MethodType):
        bound_self = fn.__self__
        fn = fn.__func__
    if not isinstance(fn, types.FunctionType):
        return fn if bound_self is None else types.MethodType(fn, bound_self)
    if hasattr(fn, "__wrapped__"):
        # a decorator wrapper: getsource would unwrap to the inner def and
        # recompiling would silently drop the decorator — leave untouched
        return fn if bound_self is None else types.MethodType(fn, bound_self)

    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, IndentationError, SyntaxError):
        return fn if bound_self is None else types.MethodType(fn, bound_self)

    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn if bound_self is None else types.MethodType(fn, bound_self)
    fdef.decorator_list = []  # avoid re-running to_static/wrappers

    tr = _ControlFlowTransformer()
    # visit the body statements (visit(fdef) itself would skip: nested
    # FunctionDefs are deliberately opaque to the transformer)
    new_body = []
    for stmt in fdef.body:
        res = tr.visit(stmt)
        if isinstance(res, list):
            new_body.extend(res)
        elif res is not None:
            new_body.append(res)
    fdef.body = new_body
    if not tr.changed:
        return fn if bound_self is None else types.MethodType(fn, bound_self)

    # zero-arg super() would need the class-body __class__ cell; rewrite
    # it to super(__class__, self) — __class__ arrives via the closure
    # snapshot below (the original method's implicit cell)
    self_name = None
    if fdef.args.args:
        self_name = fdef.args.args[0].arg
    elif fdef.args.posonlyargs:
        self_name = fdef.args.posonlyargs[0].arg
    _SuperRewriter(self_name).visit(fdef)
    ast.fix_missing_locations(tree)

    from . import convert_ifelse  # noqa: F401  (module import below)
    from .. import dy2static as _jst_module

    globs = dict(fn.__globals__)
    globs["_jst"] = _jst_module
    # snapshot closure cells: the regenerated code has no free variables.
    # NOTE: a snapshot — names rebound in the enclosing scope after
    # conversion keep their conversion-time values (documented limit).
    if fn.__closure__:
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                globs[name] = cell.cell_contents
            except ValueError:
                # empty cell (recursive / forward-referenced def): a
                # silent skip would NameError at call time — don't convert
                warnings.warn(
                    f"to_static: cannot convert {fn.__qualname__}: free "
                    f"variable '{name}' is not yet bound; falling back to "
                    "trace-only compilation"
                )
                return (
                    fn if bound_self is None
                    else types.MethodType(fn, bound_self)
                )

    try:
        code = compile(tree, f"<dy2static {fn.__qualname__}>", "exec")
        ns = {}
        exec(code, globs, ns)
        new_fn = ns[fdef.name]
    except Exception as e:  # pragma: no cover - transform must never break
        warnings.warn(
            f"to_static: control-flow conversion of {fn.__qualname__} "
            f"failed ({type(e).__name__}: {e}); falling back to "
            "trace-only compilation"
        )
        return fn if bound_self is None else types.MethodType(fn, bound_self)

    new_fn.__defaults__ = fn.__defaults__
    new_fn.__kwdefaults__ = fn.__kwdefaults__
    functools.update_wrapper(new_fn, fn)
    new_fn.__dy2static_source__ = ast.unparse(tree)
    if bound_self is not None:
        return types.MethodType(new_fn, bound_self)
    return new_fn
