"""Block/page KV pool for the paged serving engine.

The slab pool's concurrency problem: a decode slab row is ``S_max``
tokens of resident HBM no matter how short the request, so bucketing
wins (fewer compiles) never became resident-HBM wins (more concurrent
requests per chip). The paged pool fixes the unit of residency: K/V
live in a PAGE ARENA (``[num_pages, page_size, kvH, D]`` per layer x2)
and a request claims only ``ceil(total_tokens / page_size)`` pages —
its own length, quantized to one page. At equal KV HBM, a mixed-length
workload admits strictly more concurrent requests (the tier-1 test
pins this against the slab engine).

Layout contract:

- Page id **0 is the reserved garbage page**: unallocated page-table
  tail entries and free decode rows point at it, so scatter/gather over
  a fixed ``[B, P_max]`` table never needs a validity branch — garbage
  columns sit behind the position mask (-inf -> exact 0 through the
  fp32 softmax), the same discipline that makes recycled slab blocks
  safe without scrubbing.
- ``page_size`` must be a power of two and divide ``min_bucket`` (hence
  every power-of-two prefill bucket): adoption scatters a prefilled
  ``[1, bucket]`` block as ``bucket // page_size`` whole pages, one
  compiled scatter program per bucket.
- Pages are claimed UP FRONT at admission (``pages_for(total_tokens)``)
  so decode can never fail mid-sequence on page exhaustion; EOS early
  stop releases the whole claim early. The quantization loss is at most
  ``page_size - 1`` tokens per request.

Like the slab pool, the arena ARRAYS live on the engine (they are jit
carry state); the pool owns the freelist and the accounting — a drained
server must read ``pages_in_use == 0`` (zero-leak, tier-1-pinned).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..models.generation import normalize_cache_dtype


class PagesExhausted(RuntimeError):
    """Raised when a claim cannot be satisfied (admission backpressure;
    the engine treats it as 'leave the request queued')."""


class PagedKVPool:
    """Freelist + accounting over a fixed page arena.

    ``num_pages`` is the number of USABLE pages (the reserved garbage
    page 0 is allocated on top). ``claim(n)`` returns ``n`` page ids or
    raises :class:`PagesExhausted`; ``release(ids)`` returns them.
    Double-release and foreign ids raise — leaks are bugs, not noise.
    """

    def __init__(self, config, *, page_size=16, num_pages, dtype=None,
                 max_seq_len=4096):
        ps = int(page_size)
        if ps < 1 or (ps & (ps - 1)):
            raise ValueError(
                f"page_size must be a power of two, got {page_size}"
            )
        self.config = config
        self.page_size = ps
        self.num_pages = int(num_pages)
        if self.num_pages < 1:
            raise ValueError("need at least one usable page")
        self.max_seq_len = int(max_seq_len)
        # saved-artifact accounting pools carry no model config and
        # never allocate arrays — any dtype name is just a label there
        self.dtype = jnp.dtype(
            normalize_cache_dtype(dtype) if config is not None
            else (dtype or "bfloat16")
        )
        # ids 1..num_pages are claimable; 0 is the garbage page
        self._free = list(range(1, self.num_pages + 1))[::-1]
        # page id -> refcount. A fresh claim holds one reference; the
        # prefix cache and every request adopting a shared page hold one
        # more each (incref). release() decrements; the page returns to
        # the freelist only when the LAST reference drops — copy-on-
        # write page sharing without a separate ownership ledger.
        self._refs = {}
        # counters for metrics/introspection
        self.claims = 0
        self.releases = 0
        self.increfs = 0
        self.exhausted_events = 0
        self.peak_in_use = 0
        # incremental sum of max(0, refcount - 2) over all pages: every
        # reference past (cache + first holder) is a private page copy
        # sharing avoided — the shared-HBM-saved gauge reads this O(1)
        # instead of walking the cache per request
        self._extra_shared_refs = 0

    # --------------------------------------------------------- geometry
    def pages_for(self, total_tokens):
        """Pages a request of ``total_tokens`` (prompt + max_new) needs."""
        if total_tokens < 1:
            raise ValueError("total_tokens must be >= 1")
        return -(-int(total_tokens) // self.page_size)

    def table_width(self):
        """P_max: page-table columns covering ``max_seq_len`` logical
        slots (the compiled decode step's fixed table shape)."""
        return -(-self.max_seq_len // self.page_size)

    def alloc_arena_arrays(self):
        """The page arena in the shared cache layout:
        ``[num_pages + 1, page_size, kvH, D]`` x2 per layer (row 0 =
        garbage page), pool dtype. An int8 pool allocates quantized
        storage (int8 values + per-(slot, kvH) fp32 scales as one
        ``QuantizedKV`` pytree per array; zero scales keep the garbage
        page dequantizing to exact zeros)."""
        cfg = self.config
        shape = (self.num_pages + 1, self.page_size, cfg.kv_heads,
                 cfg.head_dim)
        if self.dtype == jnp.int8:
            from ..quantization.kv import alloc_quantized

            return [
                (alloc_quantized(shape), alloc_quantized(shape))
                for _ in range(cfg.num_hidden_layers)
            ]
        return [
            (jnp.zeros(shape, self.dtype), jnp.zeros(shape, self.dtype))
            for _ in range(cfg.num_hidden_layers)
        ]

    # ------------------------------------------------------- claim flow
    @property
    def free_pages(self):
        return len(self._free)

    @property
    def pages_in_use(self):
        return len(self._refs)

    @property
    def shared_pages(self):
        """Pages held by more than one reference (a cached prefix page
        adopted by at least one live request, or the cache plus its
        publisher)."""
        return sum(1 for v in self._refs.values() if v > 1)

    @property
    def shared_saved_pages(self):
        """Private page copies avoided by sharing RIGHT NOW: references
        past (cache + first holder) per page, maintained incrementally
        — O(1) to read from any thread."""
        return self._extra_shared_refs

    def refcount(self, page_id):
        return self._refs.get(int(page_id), 0)

    def claim(self, n):
        """``n`` fresh page ids (refcount 1 each), or raise
        :class:`PagesExhausted` (nothing is claimed on failure — no
        partial claims to unwind)."""
        n = int(n)
        if n < 1:
            raise ValueError(f"claim of {n} pages")
        if n > len(self._free):
            self.exhausted_events += 1
            raise PagesExhausted(
                f"need {n} pages, {len(self._free)} free "
                f"({len(self._refs)} in use)"
            )
        ids = [self._free.pop() for _ in range(n)]
        for i in ids:
            self._refs[i] = 1
        self.claims += n
        self.peak_in_use = max(self.peak_in_use, len(self._refs))
        return ids

    def incref(self, ids):
        """Adopt already-claimed pages by reference (prefix sharing:
        the cache's hold on a published page, a request's hold on an
        adopted one). Validated all-or-nothing like :meth:`release`."""
        ids = [int(i) for i in ids]
        bad = [i for i in ids if i not in self._refs]
        if bad:
            raise ValueError(
                f"page(s) {bad} not claimed — cannot share an "
                f"unclaimed page"
            )
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate page ids in one incref: {ids}")
        for i in ids:
            if self._refs[i] >= 2:
                self._extra_shared_refs += 1
            self._refs[i] += 1
        self.increfs += len(ids)

    def release(self, ids):
        """Drop one reference per id. The WHOLE id list is validated
        before anything is touched — a raise means nothing was
        released, so a caller may safely treat the claim as still held.
        A page returns to the freelist only when its LAST reference
        drops (``releases`` counts freelist returns, so a fully drained
        pool always reads ``claims == releases`` — the zero-leak pin)."""
        ids = [int(i) for i in ids]
        bad = [i for i in ids if i not in self._refs]
        if bad:
            raise ValueError(
                f"page(s) {bad} not claimed (double release or foreign "
                f"id?)"
            )
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate page ids in one release: {ids}")
        for i in ids:
            if self._refs[i] >= 3:
                self._extra_shared_refs -= 1
            self._refs[i] -= 1
            if self._refs[i] == 0:
                del self._refs[i]
                self._free.append(i)
                self.releases += 1

    # ------------------------------------------------------- accounting
    def page_bytes(self):
        """HBM bytes of ONE page across every layer's K and V arena.
        0 when the pool was built without a model config (the saved-
        artifact accounting path — page counts still tally, byte
        figures degrade honestly instead of guessing)."""
        cfg = self.config
        if cfg is None:
            return 0
        from ..quantization.kv import kv_token_bytes

        # int8 pages count their per-token fp32 scale overhead: the
        # equal-HBM concurrency comparison must not flatter quantization
        return (2 * cfg.num_hidden_layers * self.page_size
                * kv_token_bytes(cfg.kv_heads, cfg.head_dim, self.dtype))

    def request_resident_bytes(self, total_tokens):
        """Resident KV bytes one admitted request costs in this pool —
        the number the slab-vs-paged concurrency test compares against
        the slab's unconditional ``S_max`` row."""
        return self.pages_for(total_tokens) * self.page_bytes()

    def arena_bytes(self):
        """Total arena residency (usable pages + the garbage page)."""
        return (self.num_pages + 1) * self.page_bytes()

    def stats(self):
        return {
            "dtype": str(self.dtype),
            "page_size": self.page_size,
            "num_pages": self.num_pages,
            "table_width": self.table_width(),
            "free_pages": self.free_pages,
            "pages_in_use": self.pages_in_use,
            "shared_pages": self.shared_pages,
            "peak_pages_in_use": self.peak_in_use,
            "increfs": self.increfs,
            "page_bytes": self.page_bytes(),
            "arena_bytes": self.arena_bytes(),
            "claims": self.claims,
            "releases": self.releases,
            "exhausted_events": self.exhausted_events,
        }
