"""Serving-side alias of the shared chaos harness.

The deterministic fault-injection harness grew up here (PR 11's
serving chaos) and then generalized to the training runtime; the one
implementation now lives in :mod:`paddle_tpu.chaos` and this module
re-exports it VERBATIM — same function objects, same module-level
monkey slot — so ``serving.chaos.install(...)`` and
``paddle_tpu.chaos.poke(...)`` always see the same armed plan and
every existing serving caller/import keeps working unchanged.
"""
from __future__ import annotations

from ..chaos import (  # noqa: F401
    ChaosClock,
    ChaosError,
    ChaosMonkey,
    active,
    chaos,
    install,
    poke,
    poke_value,
    slow_serializer,
    tear_checkpoint,
    uninstall,
    wedged_serializer,
)

__all__ = [
    "ChaosClock", "ChaosError", "ChaosMonkey", "active", "chaos",
    "install", "poke", "poke_value", "slow_serializer",
    "tear_checkpoint", "uninstall", "wedged_serializer",
]
