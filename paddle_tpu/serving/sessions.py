"""Session runtime — the serving stack's unit becomes the conversation.

A chat product sends the SAME engine a growing prompt every turn:
turn N+1's prompt is turn N's prompt + turn N's answer + the new user
message. The KV for everything before the new message already exists
the moment turn N finishes — the prefix cache (with decode-publish,
see ``paged_engine``) holds it. What was missing is the bookkeeping
that makes conversations first-class:

- ``SessionStore`` maps a client-chosen ``session_id`` to its token
  chain and turn lifecycle: ``touch`` at submit (create or refresh),
  ``note_turn`` at finish (records the full conversation ids so far —
  the exact prefix the next turn will warm-hit on).
- **Retirement** is TTL + LRU: a session idle past ``ttl_s`` or past
  the ``max_sessions`` cap is dropped from the store (counted, with a
  flight-recorder event). Retirement is bookkeeping only — the KV
  pages themselves live and die by the prefix cache's own refcounts
  and the tier store's budgets; a retired session that comes back
  simply warm-hits whatever of its prefix still survives.
- ``session_id`` rides ``POST /v1/generate`` (``http_frontend``),
  ``engine.submit``, and the fleet router's affinity key, so fleet
  turns land on the replica already holding the session's pages.

Sessions never affect token streams: matching is by token content
through the prefix cache, and a request without a ``session_id`` is
served exactly as before. Clock-injectable for deterministic TTL
tests; driver-thread-only like the engine."""
from __future__ import annotations

import time
from collections import OrderedDict

from ..observability import Gauge, get_flight_recorder
from .metrics import Counter


class Session:
    """One conversation's bookkeeping: its id, the token ids of the
    full conversation so far (prompt + answer, every finished turn),
    and its lifecycle timestamps."""

    __slots__ = ("session_id", "tokens", "turns", "created",
                 "last_active")

    def __init__(self, session_id, now):
        self.session_id = str(session_id)
        self.tokens = ()      # full conversation ids after last turn
        self.turns = 0
        self.created = now
        self.last_active = now

    def __repr__(self):
        return (f"Session({self.session_id!r}, turns={self.turns}, "
                f"tokens={len(self.tokens)})")


class SessionStore:
    """Bounded TTL+LRU map of live conversations.

    ``max_sessions`` caps residency (oldest-idle retired first);
    ``ttl_s=None`` disables idle expiry. All counters/gauges register
    under the serving namespace with replace-on-register, like every
    per-engine instrument."""

    def __init__(self, *, max_sessions=1024, ttl_s=None,
                 clock=time.monotonic, registry=None,
                 namespace="paddle_serving", recorder=None):
        self.max_sessions = int(max_sessions)
        if self.max_sessions < 1:
            raise ValueError("max_sessions must be >= 1")
        self.ttl_s = None if ttl_s is None else float(ttl_s)
        self.clock = clock
        self._sessions = OrderedDict()  # session_id -> Session, LRU
        self._rec = recorder if recorder is not None \
            else get_flight_recorder()
        ns = namespace
        self.active = Gauge(
            "sessions_active", prom_name=f"{ns}_sessions_active",
            help="conversations resident in the session store")
        self.created = Counter(
            "sessions_created",
            prom_name=f"{ns}_sessions_created_total",
            help="new sessions admitted")
        self.retired = Counter(
            "sessions_retired", labelname="reason",
            prom_name=f"{ns}_sessions_retired_total",
            help="sessions retired from the store, by reason "
                 "(ttl | lru)")
        self.turns = Counter(
            "session_turns", prom_name=f"{ns}_session_turns_total",
            help="finished turns recorded against a session")
        if registry is None:
            from ..observability import get_registry

            registry = get_registry()
        registry.register_all([
            self.active, self.created, self.retired, self.turns,
        ])
        self.active.set(0.0)

    def __len__(self):
        return len(self._sessions)

    def get(self, session_id):
        return self._sessions.get(str(session_id))

    # ---------------------------------------------------------- lifecycle
    def touch(self, session_id):
        """Create-or-refresh at submit time: sweeps TTL, bumps LRU,
        retires over-cap sessions. Returns the (live) Session."""
        now = self.clock()
        self.sweep(now)
        sid = str(session_id)
        s = self._sessions.get(sid)
        if s is None:
            s = Session(sid, now)
            self._sessions[sid] = s
            self.created.inc()
            self._rec.note("session_open", session_id=sid)
            while len(self._sessions) > self.max_sessions:
                old_sid, old = self._sessions.popitem(last=False)
                self._retire(old, "lru")
        else:
            self._sessions.move_to_end(sid)
        s.last_active = now
        self.active.set(float(len(self._sessions)))
        return s

    def note_turn(self, session_id, output_ids):
        """Record one finished turn: ``output_ids`` is the FULL
        conversation so far (prompt + generated answer) — exactly the
        token chain the prefix cache published, and the prefix turn
        N+1 extends."""
        s = self._sessions.get(str(session_id))
        if s is None:
            return None
        s.tokens = tuple(int(t) for t in output_ids)
        s.turns += 1
        s.last_active = self.clock()
        self._sessions.move_to_end(s.session_id)
        self.turns.inc()
        return s

    def _retire(self, session, reason):
        self.retired.inc(label=reason)
        self._rec.note("session_retired",
                       session_id=session.session_id, reason=reason,
                       turns=session.turns)

    def sweep(self, now=None):
        """Retire every session idle past the TTL; returns how many."""
        if self.ttl_s is None:
            return 0
        if now is None:
            now = self.clock()
        dead = [sid for sid, s in self._sessions.items()
                if now - s.last_active > self.ttl_s]
        for sid in dead:
            self._retire(self._sessions.pop(sid), "ttl")
        if dead:
            self.active.set(float(len(self._sessions)))
        return len(dead)

    def close(self):
        self._sessions.clear()
        self.active.set(0.0)

    # -------------------------------------------------------- accounting
    def stats(self):
        return {
            "active": len(self._sessions),
            "max_sessions": self.max_sessions,
            "ttl_s": self.ttl_s,
            "created": int(self.created.value),
            "retired": self.retired.by_label(),
            "turns": int(self.turns.value),
        }
