"""Bucketed KV-cache pool for the serving engine.

Serving-time cache residency has two shapes of allocation:

- **Blocks** — per-request prefill caches. Prompt lengths are rounded up
  to power-of-two buckets so the number of compiled prefill programs is
  O(log S_max) instead of O(#distinct prompt lengths), and freed blocks
  are recycled *within their bucket* so steady-state serving allocates
  nothing. Recycled buffers are NOT zeroed: the decode position mask
  guarantees a slot is never read before it is written (stale finite
  values sit behind a -inf mask, contributing exactly 0 through the
  fp32 softmax), so scrubbing would be pure overhead.
- **Slabs** — the engine's resident fixed-shape decode buffer
  ([num_slots, S_max, kvH, D] per layer x2). Claim/release of slots
  flows through the pool so occupancy accounting covers the whole
  serving cache footprint in one place.

Dtype default is bf16 (``models.generation.DEFAULT_CACHE_DTYPE``) —
half the HBM of the old unconditional fp32 caches; the attention path
upcasts at the matmul. Layout is owned by
``models.generation.alloc_kv_caches`` so the pool, the whole-decode
programs, and the engine can never drift apart.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..models.generation import (
    DEFAULT_CACHE_DTYPE,
    alloc_kv_caches,
    normalize_cache_dtype,
)


def bucket_for(seq_len, min_bucket=16, max_seq_len=None):
    """Smallest power-of-two >= seq_len (floored at ``min_bucket``,
    capped at ``max_seq_len`` when given — a request that fits the cap
    but overshoots the rounded bucket still gets the cap bucket)."""
    if seq_len < 1:
        raise ValueError(f"seq_len must be >= 1, got {seq_len}")
    b = max(int(min_bucket), 1)
    while b < seq_len:
        b <<= 1
    if max_seq_len is not None:
        if seq_len > max_seq_len:
            raise ValueError(
                f"seq_len {seq_len} exceeds max_seq_len {max_seq_len}"
            )
        b = min(b, int(max_seq_len))
    return b


class KVBlock:
    """A bucketed per-request cache handle: ``caches`` is the
    ``alloc_kv_caches`` layout ([1, bucket, kvH, D] x2 per layer)."""

    __slots__ = ("bucket", "caches", "_live")

    def __init__(self, bucket, caches):
        self.bucket = bucket
        self.caches = caches
        self._live = True


class SlotSlab:
    """The engine's resident decode buffer viewed as claimable slots.

    The slab's arrays live on the engine (they are jit carry state);
    the slab tracks which rows are claimed and reports into the pool's
    occupancy. ``claim()`` returns a free row index or None."""

    def __init__(self, pool, num_slots, seq_len):
        self._pool = pool
        self.num_slots = int(num_slots)
        self.seq_len = int(seq_len)
        self._free = list(range(int(num_slots)))[::-1]  # pop -> slot 0 first
        self._claimed = set()

    def claim(self):
        if not self._free:
            return None
        slot = self._free.pop()
        self._claimed.add(slot)
        return slot

    def release(self, slot):
        if slot not in self._claimed:
            raise ValueError(f"slot {slot} is not claimed (double free?)")
        self._claimed.remove(slot)
        self._free.append(slot)

    @property
    def claimed(self):
        return len(self._claimed)

    @property
    def free_slots(self):
        return len(self._free)


class KVCachePool:
    """Bucketed KV-cache pool: power-of-two prefill blocks with
    per-bucket freelists + slot accounting for engine decode slabs.

    ``occupancy`` is the number of LIVE allocations (blocks handed out
    and not yet freed, plus claimed slab slots); a drained server must
    read 0 — the tier-1 serving test pins that (zero slot leaks)."""

    def __init__(self, config, *, dtype=None, min_bucket=16,
                 max_seq_len=4096, max_blocks=None):
        self.config = config
        self.dtype = jnp.dtype(normalize_cache_dtype(dtype))
        self.min_bucket = int(min_bucket)
        self.max_seq_len = int(max_seq_len)
        self.max_blocks = max_blocks  # live-block cap (None = unbounded)
        self._freelists = {}   # bucket -> [KVBlock]
        self._live_blocks = 0
        self._block_bytes = 0  # all blocks ever created (resident)
        self._slabs = []
        # counters for metrics/introspection
        self.allocs = 0
        self.reuse_hits = 0

    # ------------------------------------------------------------ blocks
    def bucket_for(self, seq_len):
        return bucket_for(seq_len, self.min_bucket, self.max_seq_len)

    def alloc(self, seq_len):
        """A KVBlock whose bucket covers ``seq_len``. Reuses a freed
        block of the same bucket when one exists."""
        if self.max_blocks is not None and (
            self._live_blocks >= self.max_blocks
        ):
            raise PoolExhausted(
                f"KV pool block cap reached ({self.max_blocks} live)"
            )
        bucket = self.bucket_for(seq_len)
        free = self._freelists.get(bucket)
        if free:
            blk = free.pop()
            blk._live = True
            self.reuse_hits += 1
        else:
            blk = KVBlock(
                bucket,
                alloc_kv_caches(self.config, 1, bucket, self.dtype),
            )
            self.allocs += 1
            self._block_bytes += self._bytes(bucket)
        self._live_blocks += 1
        return blk

    def free(self, block):
        if not block._live:
            raise ValueError("KVBlock double-free")
        block._live = False
        self._freelists.setdefault(block.bucket, []).append(block)
        self._live_blocks -= 1

    def discard(self, block):
        """Retire a block WITHOUT recycling its buffers — for blocks
        whose arrays may be invalid (e.g. donated into a compiled call
        that then failed: the donation consumed the buffers, and
        freelisting them would poison every later alloc in the
        bucket)."""
        if not block._live:
            raise ValueError("KVBlock double-free")
        block._live = False
        block.caches = None
        self._live_blocks -= 1
        self._block_bytes -= self._bytes(block.bucket)

    # ------------------------------------------------------------- slabs
    def alloc_slab_arrays(self, num_slots, seq_len):
        """The engine decode buffer in the shared cache layout
        ([num_slots, seq_len, kvH, D] x2 per layer, pool dtype)."""
        return alloc_kv_caches(self.config, num_slots, seq_len, self.dtype)

    def register_slab(self, num_slots, seq_len):
        slab = SlotSlab(self, num_slots, seq_len)
        self._slabs.append(slab)
        return slab

    # ------------------------------------------------------- accounting
    @property
    def occupancy(self):
        """Live allocations: outstanding blocks + claimed slab slots."""
        return self._live_blocks + sum(s.claimed for s in self._slabs)

    def _bytes(self, bucket, rows=1):
        from ..quantization.kv import kv_token_bytes

        cfg = self.config
        # int8 counts its per-token fp32 scale overhead — residency
        # numbers must not flatter quantized caches
        return (
            2 * cfg.num_hidden_layers * rows * bucket
            * kv_token_bytes(cfg.kv_heads, cfg.head_dim, self.dtype)
        )

    def stats(self):
        free_blocks = sum(len(v) for v in self._freelists.values())
        # resident = every block ever created (live + freelist; freed
        # blocks stay mapped for reuse) + the registered decode slabs
        reserved = self._block_bytes + sum(
            self._bytes(s.seq_len, s.num_slots) for s in self._slabs
        )
        return {
            "dtype": str(self.dtype),
            "live_blocks": self._live_blocks,
            "free_blocks": free_blocks,
            "claimed_slots": sum(s.claimed for s in self._slabs),
            "slab_slots": sum(s.num_slots for s in self._slabs),
            "occupancy": self.occupancy,
            "reserved_bytes": int(reserved),
            "allocs": self.allocs,
            "reuse_hits": self.reuse_hits,
        }


class PoolExhausted(RuntimeError):
    """Raised when the pool's live-block cap is hit (backpressure)."""
