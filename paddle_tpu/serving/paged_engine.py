"""Paged continuous-batching engine — resident HBM as the unit of win.

``ServingEngine``'s decode slab reserves a full ``[S_max]`` row per
request, so short requests waste most of their residency and the
concurrency ceiling is ``HBM / (S_max * token_bytes)`` regardless of
actual lengths. This engine keeps K/V in a PAGE ARENA
(:class:`~.paged_pool.PagedKVPool`) and each request claims only
``ceil(total_tokens / page_size)`` pages — at equal KV HBM, a
mixed-length workload admits strictly more concurrent requests (the
tier-1 test pins it against the slab engine, same budget, same
workload).

Compiled-program inventory (all fixed-shape, admission/retirement never
recompiles — the slab engine's core discipline carries over):

- **prefill** (per power-of-two prompt bucket): unchanged — the shared
  per-bucket programs from the base engine run the padded prompt
  through a transient block from the bucketed block pool.
- **adopt-pages** (per bucket): scatters the prefilled ``[1, bucket]``
  block into the arena as ``bucket / page_size`` whole pages at
  table-supplied ids (tail ids past the request's claim point at the
  garbage page 0 — no shape variance, no recompiles).
- **decode step** (exactly one): ``[B]`` tokens + the ``[B, P_max]``
  page table -> next tokens; attention gathers K/V through the table
  (``models.llama`` paged path; a tuned Pallas paged-attention kernel
  replaces the HBM gather when the tune cache opts one in).

Prefill/decode disaggregation: prefill and decode are separate
compiled units, and ``max_prefills_per_step`` (default 1) bounds how
many prompt prefills one engine step may run before the decode step
fires — a burst of long prompts delays in-flight decodes by at most one
bucket's prefill per step instead of stalling them behind the whole
backlog. Prefilled requests enter the decode batch purely by having
their pages written and their table row set.

Token streams are exact-equal to ``net.generate`` and the slab engine:
the default paged path gathers the table and runs the SAME masked-SDPA
op order over it — extra masked columns contribute exact zeros through
the fp32 softmax.
"""
from __future__ import annotations

import time

import numpy as np

import jax
import jax.numpy as jnp

from .. import profiler
from ..models.generation import _select_next, decode_step
from .engine import ServingEngine, _Seq, _flatten, _unflatten
from .paged_pool import PagedKVPool, PagesExhausted
from .scheduler import RUNNING


class PagedServingEngine(ServingEngine):
    """Continuous batching over a paged KV pool.

    Same request surface as :class:`ServingEngine` (submit / step /
    run_until_idle / generate / close, streaming callbacks, scheduler,
    metrics). Geometry: ``page_size`` must be a power of two that
    divides ``min_bucket`` AND ``max_seq_len`` (adoption scatters whole
    pages; the top prompt bucket is capped at ``max_seq_len``).
    ``num_pages`` (usable pages, garbage page excluded) defaults to
    full-coverage ``max_batch_size * ceil(max_seq_len / page_size)`` —
    pass a smaller arena to trade concurrency headroom for HBM, the
    whole point of paging."""

    def __init__(self, net, *, max_batch_size=8, max_seq_len=256,
                 page_size=16, num_pages=None, cache_dtype=None,
                 do_sample=False, temperature=1.0, top_k=0, top_p=1.0,
                 seed=0, min_bucket=16, max_queue_size=64,
                 max_tokens_in_flight=None, max_prefills_per_step=1,
                 scheduler=None, metrics=None, pool=None, page_pool=None,
                 clock=time.monotonic, recompile_guard_max=None,
                 weights_version=None, prefill_transport=None,
                 reload_template=None):
        ps = int(page_size)
        if ps < 1 or (ps & (ps - 1)):
            raise ValueError(
                f"page_size must be a power of two, got {page_size}"
            )
        if ps > int(min_bucket) or int(min_bucket) % ps:
            raise ValueError(
                f"page_size {ps} must divide every prefill bucket: "
                f"min_bucket {min_bucket} must be a multiple of it"
            )
        if int(max_seq_len) % ps:
            raise ValueError(
                f"max_seq_len {max_seq_len} must be a multiple of "
                f"page_size {ps} (the top prompt bucket is capped at "
                f"max_seq_len and adoption scatters whole pages)"
            )
        self.page_size = ps
        self._num_pages_arg = num_pages
        self._page_pool_arg = page_pool
        self.max_prefills_per_step = (
            None if max_prefills_per_step is None
            else int(max_prefills_per_step)
        )
        # cross-process disaggregation: when a transport (a
        # fleet.kv_transfer.RemotePrefillClient) is attached, admission
        # ships the prompt to the prefill pool and adopts the returned
        # KV pages; any transfer failure falls back to LOCAL prefill on
        # this engine — disaggregation is an optimization, never a
        # correctness dependency.
        self.prefill_transport = prefill_transport
        self.remote_prefills = 0
        self.local_prefills = 0
        self.remote_prefill_fallbacks = 0
        super().__init__(
            net, max_batch_size=max_batch_size, max_seq_len=max_seq_len,
            cache_dtype=cache_dtype, do_sample=do_sample,
            temperature=temperature, top_k=top_k, top_p=top_p, seed=seed,
            min_bucket=min_bucket, max_queue_size=max_queue_size,
            max_tokens_in_flight=max_tokens_in_flight,
            scheduler=scheduler, metrics=metrics, pool=pool, clock=clock,
            recompile_guard_max=recompile_guard_max,
            weights_version=weights_version,
            reload_template=reload_template,
        )

    # ------------------------------------------------------- KV backend
    def _init_kv_backend(self):
        num_pages = self._num_pages_arg
        if num_pages is None:
            num_pages = (self.max_batch_size
                         * (-(-self.max_seq_len // self.page_size)))
        pp = self._page_pool_arg or PagedKVPool(
            self.config, page_size=self.page_size, num_pages=num_pages,
            dtype=self.cache_dtype, max_seq_len=self.max_seq_len,
        )
        if pp.page_size != self.page_size:
            raise ValueError(
                f"page_pool page_size {pp.page_size} != engine "
                f"page_size {self.page_size}"
            )
        if jnp.dtype(pp.dtype) != jnp.dtype(self.cache_dtype):
            raise ValueError(
                f"page_pool dtype {pp.dtype} != prefill block dtype "
                f"{self.cache_dtype} — adoption would silently cast"
            )
        if pp.table_width() * pp.page_size < self.max_seq_len:
            raise ValueError(
                f"page_pool table width {pp.table_width()} covers only "
                f"{pp.table_width() * pp.page_size} tokens < engine "
                f"max_seq_len {self.max_seq_len}"
            )
        self.page_pool = pp
        self.table_width = pp.table_width()
        self._flat = _flatten(pp.alloc_arena_arrays())
        self._tables = np.zeros(
            (self.max_batch_size, self.table_width), np.int32
        )
        self._row_pages = [None] * self.max_batch_size
        self._free_rows = list(range(self.max_batch_size))[::-1]

    def _release_slot(self, slot):
        pages = self._row_pages[slot]
        if pages:
            self.page_pool.release(pages)
        self._row_pages[slot] = None
        self._tables[slot, :] = 0  # free row reads/writes garbage page
        self._free_rows.append(slot)

    @property
    def free_rows(self):
        return len(self._free_rows)

    def _has_capacity(self):
        return bool(self._free_rows)

    def _too_long(self, req):
        # a request needing more pages than the whole arena would sit
        # at the head of the strict-FIFO queue forever, blocking every
        # later request — reject it at submit instead
        return (super()._too_long(req)
                or self.page_pool.pages_for(req.total_tokens)
                > self.page_pool.num_pages)

    def _admission_budget(self):
        """Head must fit BOTH the in-flight token cap and the free
        pages. ``total <= free_pages * page_size`` is exactly
        ``ceil(total / page_size) <= free_pages``, so the token-budget
        gate doubles as the page gate — strict FIFO is preserved (a big
        head waits, nothing overtakes it)."""
        base = super()._admission_budget()
        page_budget = self.page_pool.free_pages * self.page_size
        return page_budget if base is None else min(base, page_budget)

    def _max_admissions_per_step(self):
        return self.max_prefills_per_step

    # ------------------------------------------------- compiled programs
    def _decode_body(self, params, buffers, tok, flat, tbl, pos,
                     temperature, key):
        self.net.load_functional_state(params, buffers)
        self.net.eval()
        logits, caches = decode_step(
            self.net, tok[:, None], _unflatten(flat), pos,
            page_table=tbl,
        )
        nxt = _select_next(logits, self.do_sample, temperature,
                           self.top_k, self.top_p, key)
        return nxt, _flatten(caches)

    def _decode_extra(self):
        return (jnp.asarray(self._tables),)

    def _adopt_fn(self, bucket):
        """Scatter a prefilled [1, bucket] block into the arena as
        ``bucket / page_size`` whole pages at traced page ids — one
        program per bucket, ids beyond the request's claim point at the
        garbage page 0 (duplicate scatter indices there are fine: the
        page is garbage by contract)."""
        fn = self._adopt_fns.get(bucket)
        if fn is not None:
            return fn
        ps = self.page_size
        n_pages_b = bucket // ps

        def body(flat_arena, flat_block, page_ids):
            from ..quantization.kv import adopt_into_pages

            return [
                adopt_into_pages(a, b, page_ids, n_pages_b, ps)
                for a, b in zip(flat_arena, flat_block)
            ]

        fn = jax.jit(
            body, donate_argnums=(0,) if self._donate else ()
        )
        self._adopt_fns[bucket] = fn
        self.trace_guard.record_compile(
            "serving::adopt_pages", bucket,
            origin="serving/paged_engine.py",
        )
        return fn

    def _adopt_example_args(self, flat_block, bucket):
        return (
            self._flat, flat_block,
            jnp.zeros((bucket // self.page_size,), jnp.int32),
        )

    def _program_signature(self, name):
        sig = super()._program_signature(name)
        sig["page_size"] = self.page_size
        sig["num_pages"] = self.page_pool.num_pages
        sig["table_width"] = self.table_width
        return sig

    # ---------------------------------------------------------- requests
    def _drop_block(self, blk):
        """Return a prefill block after a failed admission. Under
        donation the failed call may already have consumed the block's
        buffers — recycling would poison the freelist, so discard."""
        if blk is None:
            return
        if self._donate:
            self.pool.discard(blk)
        else:
            self.pool.free(blk)

    def _remote_prefill(self, req, bucket, key):
        """Try the attached prefill pool: ``(first_token, flat_block)``
        on success, None when the transport is absent/down/failing (the
        caller runs local prefill — clean fallback, counted)."""
        tr = self.prefill_transport
        if tr is None or not tr.available():
            return None
        from .fleet.kv_transfer import TransferError

        try:
            out = tr.prefill(
                [int(t) for t in req.input_ids], req.prompt_len, bucket,
                self.page_size, str(self.cache_dtype),
                float(self.temperature), key,
            )
        except TransferError:
            self.remote_prefill_fallbacks += 1
            return None
        self.remote_prefills += 1
        return out

    def _admit_one(self, handle):
        req = handle.request
        now = self.clock()
        bucket = self.pool.bucket_for(req.prompt_len)
        n_req = self.page_pool.pages_for(req.total_tokens)
        # sampling key drawn ONCE so a remote-prefill failure that falls
        # back locally consumes the same key the pure-local path would —
        # sampled streams stay reproducible either way
        key = self._next_key()
        remote = self._remote_prefill(req, bucket, key)
        blk = None
        if remote is None:
            ids = np.zeros((1, bucket), np.int32)
            ids[0, : req.prompt_len] = req.input_ids
            blk = self.pool.alloc(req.prompt_len)
        # the budget gate already sized the claim against free pages;
        # claim + row pop still guarded so an exception can never
        # strand pages or a row
        try:
            pages = self.page_pool.claim(n_req)
        except PagesExhausted:
            self._drop_block(blk)
            raise
        row = self._free_rows.pop()
        try:
            self._tables[row, :] = 0
            self._tables[row, :n_req] = pages
            if remote is None:
                self.local_prefills += 1
                with profiler.RecordEvent(f"serving::prefill_b{bucket}"):
                    nxt, new_flat = self._run(
                        ("prefill", bucket), self._prefill_fn(bucket),
                        self._params, self._buffers, jnp.asarray(ids),
                        jnp.int32(req.prompt_len), _flatten(blk.caches),
                        jnp.float32(self.temperature), key,
                    )
                    blk.caches = _unflatten(new_flat)
                    t0 = int(np.asarray(nxt)[0])
            else:
                # the prefill pool already ran the bucket program; the
                # wire block adopts through the SAME compiled scatter
                t0, new_flat = remote
            with profiler.RecordEvent(f"serving::adopt_b{bucket}"):
                # adopt: first min(n_req, bucket/ps) block pages land in
                # the claim; block pad pages (prompt shorter than the
                # bucket's page span) scatter to garbage page 0
                page_ids = np.zeros((bucket // self.page_size,),
                                    np.int32)
                k = min(n_req, bucket // self.page_size)
                page_ids[:k] = pages[:k]
                self._flat = self._run(
                    ("adopt", bucket), self._adopt_fn(bucket),
                    self._flat, new_flat, jnp.asarray(page_ids),
                )
        except BaseException:
            self._tables[row, :] = 0
            self._free_rows.append(row)
            self.page_pool.release(pages)
            self._drop_block(blk)
            raise
        if blk is not None:
            self.pool.free(blk)
        self._row_pages[row] = pages
        handle.status = RUNNING
        handle.weights_version = self.weights_version
        handle.admit_time = now
        handle.admitted_step = self.step_count
        handle.first_token_time = self.clock()
        self.metrics.admitted.inc()
        self.metrics.prefill_tokens.inc(req.prompt_len)
        self.metrics.queue_wait.observe(now - handle.submit_time)
        self.metrics.ttft.observe(handle.first_token_time
                                  - handle.submit_time)
        self._seqs[row] = _Seq(handle, t0)
        self._append(row, t0)

    def close(self):
        super().close()
        if self.prefill_transport is not None:
            self.prefill_transport.close()
        self._tables = None
        self._row_pages = [None] * self.max_batch_size
